"""Fluid-analog operator library: the registry + pure-jax compute kernels.

Reference analog: paddle/operators/ (76 op families, each a CPU .cc + GPU .cu
kernel pair registered via REGISTER_OP*, framework/op_registry.h) and
paddle/operators/math (shared kernel lib).

TPU-native design: ONE implementation per op, written in jax, traced by the
Executor into a single XLA program — there is no CPU/GPU kernel split (XLA
targets every backend) and no hand-written gradient kernels (grad ops are
computed with ``jax.vjp`` of the forward compute; see backward.py/executor.py,
replacing the reference's per-op grad kernels and GradOpDescMaker).

``compute(ins, attrs, ctx)`` takes a dict slot -> list of values and returns
a dict slot -> list of values. Values are ``jax.Array`` or ``LoDArray``
(ragged sequence batch; lod_tensor.h:57-80 analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.platform.enforce import EnforceError, enforce_that

# ---------------------------------------------------------------------------
# LoDArray: the LoDTensor analog flowing through fluid programs
# ---------------------------------------------------------------------------


@dataclass
class LoDArray:
    """Dense data + level-of-detail ragged boundaries.

    ``lod`` is a list of levels, each a python list of monotonically
    increasing offsets (lod_tensor.h:57: LoD = vector<Vector<size_t>>).
    Offsets are static per trace — ragged structure is a compile-time
    property on TPU (re-trace per bucket), the data is not."""

    data: Any
    lod: Tuple[Tuple[int, ...], ...]

    def sequence_ids(self) -> np.ndarray:
        """Per-row segment id from the finest lod level."""
        offs = self.lod[-1]
        ids = np.zeros(offs[-1], np.int32)
        for i in range(len(offs) - 1):
            ids[offs[i]:offs[i + 1]] = i
        return ids

    @property
    def num_sequences(self) -> int:
        return len(self.lod[-1]) - 1


def _dat(v):
    return v.data if isinstance(v, LoDArray) else v


def _like(template, data):
    if isinstance(template, LoDArray):
        return LoDArray(data, template.lod)
    return data


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


@dataclass
class OpInfo:
    type: str
    compute: Callable
    family: str = "misc"
    stateful_outputs: Tuple[str, ...] = ()   # outputs that alias persistables
    no_grad: bool = False                    # not differentiable (metrics etc.)
    uses_rng: bool = False


_REGISTRY: Dict[str, OpInfo] = {}


def register(type: str, *, family: str = "misc", stateful: Sequence[str] = (),
             no_grad: bool = False, uses_rng: bool = False):
    def deco(fn):
        enforce_that(type not in _REGISTRY, f"op {type} already registered",
                     context="fluid")
        _REGISTRY[type] = OpInfo(type, fn, family, tuple(stateful), no_grad,
                                 uses_rng)
        return fn
    return deco


def get(type: str) -> OpInfo:
    enforce_that(type in _REGISTRY, f"unknown op type {type!r}",
                 context="fluid")
    return _REGISTRY[type]


def check_registered(type: str) -> None:
    if type.endswith("_grad"):
        type = type[:-5]
    get(type)


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


class ComputeCtx:
    """Per-trace context: rng, test mode, and sub-block tracer hook."""

    def __init__(self, rng: Optional[jax.Array], is_test: bool,
                 trace_block: Optional[Callable] = None):
        self.rng = rng
        self.is_test = is_test
        self.trace_block = trace_block  # set by the Executor

    def rng_for(self, salt: int) -> jax.Array:
        key = self.rng if self.rng is not None else jax.random.PRNGKey(0)
        return jax.random.fold_in(key, salt)


def _one(ins, slot):
    vs = ins.get(slot, [])
    enforce_that(len(vs) == 1, f"slot {slot} expects 1 input, got {len(vs)}",
                 context="fluid")
    return vs[0]


def _opt(ins, slot, default=None):
    vs = ins.get(slot, [])
    return vs[0] if vs else default


# ---------------------------------------------------------------------------
# elementwise family (elementwise_op.cc analog, with axis broadcast)
# ---------------------------------------------------------------------------


def _bcast(x, y, axis: int):
    """Reference broadcast semantics: y's shape matches a contiguous slice of
    x's dims starting at `axis` (elementwise_op.h); -1 = rank(x)-rank(y)."""
    if x.ndim == y.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * x.ndim
    for i, s in enumerate(y.shape):
        shape[axis + i] = s
    return y.reshape(shape)


def _elementwise(fn):
    def compute(ins, attrs, ctx):
        x, y = _one(ins, "X"), _one(ins, "Y")
        xd, yd = _dat(x), _dat(y)
        out = fn(xd, _bcast(xd, yd, int(attrs.get("axis", -1))))
        return {"Out": [_like(x, out)]}
    return compute


for _name, _fn in [("elementwise_add", jnp.add),
                   ("elementwise_sub", jnp.subtract),
                   ("elementwise_mul", jnp.multiply),
                   ("elementwise_div", jnp.divide),
                   ("elementwise_pow", jnp.power),
                   ("elementwise_max", jnp.maximum),
                   ("elementwise_min", jnp.minimum)]:
    register(_name, family="elementwise")(_elementwise(_fn))


# ---------------------------------------------------------------------------
# activations (activation_op.cc bundle)
# ---------------------------------------------------------------------------


def _unary(fn):
    def compute(ins, attrs, ctx):
        x = _one(ins, "X")
        return {"Out": [_like(x, fn(_dat(x), attrs))]}
    return compute


_ACTS = {
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "exp": lambda x, a: jnp.exp(x),
    "relu": lambda x, a: jax.nn.relu(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "log": lambda x, a: jnp.log(x),
    "square": lambda x, a: jnp.square(x),
    "softsign": lambda x, a: x / (1.0 + jnp.abs(x)),
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
    "soft_relu": lambda x, a: jnp.log1p(jnp.exp(jnp.clip(
        x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))),
    "pow": lambda x, a: jnp.power(x, a.get("factor", 1.0)),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
        a.get("scale_a", 2.0 / 3.0) * x),
    "leaky_relu": lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x),
    "relu6": lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)),
    "softplus": lambda x, a: jax.nn.softplus(x),
    "hard_shrink": lambda x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "soft_shrink": lambda x, a: jnp.sign(x) * jax.nn.relu(
        jnp.abs(x) - a.get("lambda", 0.5)),
    "elu": lambda x, a: jnp.where(x > 0, x, a.get("alpha", 1.0)
                                  * (jnp.exp(x) - 1.0)),
    "sign": lambda x, a: jnp.sign(x),
    "floor": lambda x, a: jnp.floor(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "round": lambda x, a: jnp.round(x),
}

for _name, _fn in _ACTS.items():
    register(_name, family="activation")(_unary(_fn))


@register("scale", family="elementwise")
def _scale(ins, attrs, ctx):
    x = _one(ins, "X")
    out = _dat(x) * attrs.get("scale", 1.0) + attrs.get("bias", 0.0)
    return {"Out": [_like(x, out)]}


@register("clip", family="elementwise")
def _clip(ins, attrs, ctx):
    x = _one(ins, "X")
    return {"Out": [_like(x, jnp.clip(_dat(x), attrs["min"], attrs["max"]))]}


@register("cast", family="elementwise")
def _cast(ins, attrs, ctx):
    x = _one(ins, "X")
    return {"Out": [_like(x, _dat(x).astype(attrs["out_dtype"]))]}


# ---------------------------------------------------------------------------
# matmul family (mul_op / matmul_op; MXU-bound — keep batched & fusable)
# ---------------------------------------------------------------------------


@register("mul", family="matmul")
def _mul(ins, attrs, ctx):
    x, y = _dat(_one(ins, "X")), _dat(_one(ins, "Y"))
    xn = int(attrs.get("x_num_col_dims", 1))
    yn = int(attrs.get("y_num_col_dims", 1))
    xm = x.reshape((int(np.prod(x.shape[:xn])), -1))
    ym = y.reshape((int(np.prod(y.shape[:yn])), -1))
    out = xm @ ym
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": [_like(_one(ins, "X"), out.reshape(out_shape))]}


@register("matmul", family="matmul")
def _matmul(ins, attrs, ctx):
    x, y = _dat(_one(ins, "X")), _dat(_one(ins, "Y"))
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": [x @ y]}


# ---------------------------------------------------------------------------
# conv / pool (NCHW like the reference; lax targets the MXU directly,
# no im2col materialisation — operators/math/im2col is unnecessary on TPU)
# ---------------------------------------------------------------------------


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))


@register("conv2d", family="conv")
def _conv2d(ins, attrs, ctx):
    x, w = _dat(_one(ins, "Input")), _dat(_one(ins, "Filter"))
    s, p = _pair(attrs.get("strides", 1)), _pair(attrs.get("paddings", 0))
    d = _pair(attrs.get("dilations", 1))
    groups = int(attrs.get("groups", 1))
    out = lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    b = _opt(ins, "Bias")
    if b is not None:
        out = out + _dat(b).reshape(1, -1, 1, 1)
    return {"Output": [out]}


@register("conv2d_transpose", family="conv")
def _conv2d_transpose(ins, attrs, ctx):
    x, w = _dat(_one(ins, "Input")), _dat(_one(ins, "Filter"))
    s, p = _pair(attrs.get("strides", 1)), _pair(attrs.get("paddings", 0))
    # filter layout [in, out, H, W] (conv2dtranspose_op.cc convention)
    out = lax.conv_transpose(
        x, w, strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
        dimension_numbers=("NCHW", "IOHW", "NCHW"))
    return {"Output": [out]}


@register("conv3d", family="conv")
def _conv3d(ins, attrs, ctx):
    x, w = _dat(_one(ins, "Input")), _dat(_one(ins, "Filter"))
    s = tuple(attrs.get("strides", (1, 1, 1)))
    p = tuple(attrs.get("paddings", (0, 0, 0)))
    out = lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(q, q) for q in p],
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": [out]}


def _pool(x, ksize, strides, paddings, ptype, exclusive=True):
    k, s, p = _pair(ksize), _pair(strides), _pair(paddings)
    window = (1, 1) + k
    stride = (1, 1) + s
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, stride, pads)
    summed = lax.reduce_window(x, 0.0, lax.add, window, stride, pads)
    if exclusive and (p[0] or p[1]):
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, stride, pads)
        return summed / counts
    return summed / float(k[0] * k[1])


@register("pool2d", family="pool")
def _pool2d(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    if attrs.get("global_pooling", False):
        k = x.shape[2:4]
        return {"Out": [_pool(x, k, k, 0, attrs.get("pooling_type", "max"))]}
    return {"Out": [_pool(x, attrs.get("ksize", 2),
                          attrs.get("strides", 1), attrs.get("paddings", 0),
                          attrs.get("pooling_type", "max"))]}


@register("pool2d_with_index", family="pool")
def _pool2d_with_index(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    k, s = _pair(attrs.get("ksize", 2)), _pair(attrs.get("strides", 1))
    p = _pair(attrs.get("paddings", 0))
    window = (1, 1) + k
    stride = (1, 1) + s
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    # Out through the differentiable single-operand reduce_window; the index
    # Mask through a stop_gradient variadic pass (its transpose is undefined)
    out = lax.reduce_window(x, -jnp.inf, lax.max, window, stride, pads)

    n, c, h, w = x.shape
    flat_idx = jnp.arange(h * w, dtype=jnp.float32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)

    def sel(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    _, idx = lax.reduce_window(
        (lax.stop_gradient(x), flat_idx), (-jnp.inf, -1.0),
        sel, window, stride, pads)
    return {"Out": [out], "Mask": [lax.stop_gradient(idx).astype(jnp.int32)]}


# ---------------------------------------------------------------------------
# batch_norm (batch_norm_op.cc; stateful moving stats)
# ---------------------------------------------------------------------------


@register("batch_norm", family="norm",
          stateful=("MeanOut", "VarianceOut"))
def _batch_norm(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    scale, bias = _dat(_one(ins, "Scale")), _dat(_one(ins, "Bias"))
    mean_in = _dat(_one(ins, "Mean"))
    var_in = _dat(_one(ins, "Variance"))
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim)
                 if i != (1 if layout == "NCHW" else x.ndim - 1))
    shape = [1] * x.ndim
    shape[1 if layout == "NCHW" else -1] = -1
    if ctx.is_test or attrs.get("is_test", False):
        mean, var = mean_in, var_in
        mean_out, var_out = mean_in, var_in
        saved_mean = mean
        saved_var = var
    else:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        mean_out = momentum * mean_in + (1 - momentum) * mean
        var_out = momentum * var_in + (1 - momentum) * var
        saved_mean, saved_var = mean, var
    inv = lax.rsqrt(var.reshape(shape) + eps)
    y = (x - mean.reshape(shape)) * inv * scale.reshape(shape) \
        + bias.reshape(shape)
    return {"Y": [y], "MeanOut": [mean_out], "VarianceOut": [var_out],
            "SavedMean": [saved_mean], "SavedVariance": [saved_var]}


@register("lrn", family="norm")
def _lrn(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    n = int(attrs.get("n", 5))
    k, alpha, beta = (attrs.get("k", 2.0), attrs.get("alpha", 1e-4),
                      attrs.get("beta", 0.75))
    sq = jnp.square(x)
    pad = n // 2
    sq = jnp.pad(sq, ((0, 0), (pad, pad), (0, 0), (0, 0)))
    acc = sum(sq[:, i:i + x.shape[1]] for i in range(n))
    return {"Out": [x / jnp.power(k + alpha * acc, beta)]}


@register("layer_norm", family="norm")
def _layer_norm(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    eps = attrs.get("epsilon", 1e-5)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    scale, bias = _opt(ins, "Scale"), _opt(ins, "Bias")
    if scale is not None:
        y = y * _dat(scale)
    if bias is not None:
        y = y + _dat(bias)
    return {"Y": [y]}


# ---------------------------------------------------------------------------
# softmax / losses
# ---------------------------------------------------------------------------


@register("softmax", family="softmax")
def _softmax(ins, attrs, ctx):
    x = _one(ins, "X")
    return {"Out": [_like(x, jax.nn.softmax(_dat(x), axis=-1))]}


def _xent(probs, label, soft):
    if soft:
        return -jnp.sum(label * jnp.log(jnp.clip(probs, 1e-10, None)),
                        axis=-1, keepdims=True)
    idx = label.reshape(-1).astype(jnp.int32)
    picked = jnp.take_along_axis(probs, idx[:, None], axis=-1)
    return -jnp.log(jnp.clip(picked, 1e-10, None))


@register("cross_entropy", family="loss")
def _cross_entropy(ins, attrs, ctx):
    x, label = _dat(_one(ins, "X")), _dat(_one(ins, "Label"))
    return {"Y": [_xent(x, label, attrs.get("soft_label", False))]}


@register("softmax_with_cross_entropy", family="loss")
def _softmax_xent(ins, attrs, ctx):
    logits, label = _dat(_one(ins, "Logits")), _dat(_one(ins, "Label"))
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        idx = label.reshape(-1).astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, idx[:, None], axis=-1)
    return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}


@register("sigmoid_cross_entropy_with_logits", family="loss")
def _sigmoid_xent(ins, attrs, ctx):
    x, label = _dat(_one(ins, "X")), _dat(_one(ins, "Labels"))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register("squared_l2_distance", family="loss")
def _sq_l2_dist(ins, attrs, ctx):
    x, y = _dat(_one(ins, "X")), _dat(_one(ins, "Y"))
    sub = x - y
    return {"sub_result": [sub],
            "Out": [jnp.sum(jnp.square(sub), axis=-1, keepdims=True)]}


@register("squared_l2_norm", family="loss")
def _sq_l2_norm(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    return {"Out": [jnp.sum(jnp.square(x)).reshape(1)]}


@register("rank_loss", family="loss")
def _rank_loss(ins, attrs, ctx):
    label = _dat(_one(ins, "Label"))
    left, right = _dat(_one(ins, "Left")), _dat(_one(ins, "Right"))
    d = left - right
    return {"Out": [jnp.log1p(jnp.exp(d)) - label * d]}


@register("margin_rank_loss", family="loss")
def _margin_rank_loss(ins, attrs, ctx):
    label = _dat(_one(ins, "Label"))
    x1, x2 = _dat(_one(ins, "X1")), _dat(_one(ins, "X2"))
    margin = attrs.get("margin", 0.0)
    out = jax.nn.relu(-label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register("smooth_l1_loss", family="loss")
def _smooth_l1(ins, attrs, ctx):
    x, y = _dat(_one(ins, "X")), _dat(_one(ins, "Y"))
    sigma2 = attrs.get("sigma", 1.0) ** 2
    diff = x - y
    iw, ow = _opt(ins, "InsideWeight"), _opt(ins, "OutsideWeight")
    if iw is not None:
        diff = diff * _dat(iw)
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * diff * diff,
                    ad - 0.5 / sigma2)
    if ow is not None:
        val = val * _dat(ow)
    return {"Diff": [diff],
            "Out": [jnp.sum(val, axis=-1, keepdims=True)]}


@register("huber_loss", family="loss")
def _huber(ins, attrs, ctx):
    x, y = _dat(_one(ins, "X")), _dat(_one(ins, "Y"))
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Residual": [r], "Out": [out]}


# ---------------------------------------------------------------------------
# embeddings / gather / scatter
# ---------------------------------------------------------------------------


@register("lookup_table", family="embedding")
def _lookup_table(ins, attrs, ctx):
    w, ids = _dat(_one(ins, "W")), _one(ins, "Ids")
    idx = _dat(ids).reshape(-1).astype(jnp.int32)
    out = jnp.take(w, idx, axis=0)
    return {"Out": [_like(ids, out)]}


@register("gather", family="embedding")
def _gather(ins, attrs, ctx):
    x, idx = _dat(_one(ins, "X")), _dat(_one(ins, "Index"))
    return {"Out": [jnp.take(x, idx.reshape(-1).astype(jnp.int32), axis=0)]}


@register("scatter", family="embedding")
def _scatter(ins, attrs, ctx):
    ref = _dat(_one(ins, "Ref"))
    idx = _dat(_one(ins, "Index")).reshape(-1).astype(jnp.int32)
    upd = _dat(_one(ins, "Updates"))
    return {"Out": [ref.at[idx].set(upd)]}


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------


@register("reshape", family="shape")
def _reshape(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    shape = list(attrs["shape"])
    return {"Out": [x.reshape(shape)]}


@register("transpose", family="shape")
def _transpose(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    return {"Out": [jnp.transpose(x, attrs["axis"])]}


@register("concat", family="shape")
def _concat(ins, attrs, ctx):
    xs = [_dat(v) for v in ins["X"]]
    return {"Out": [jnp.concatenate(xs, axis=int(attrs.get("axis", 0)))]}


@register("split", family="shape")
def _split(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    axis = int(attrs.get("axis", 0))
    if "sections" in attrs and attrs["sections"]:
        secs = np.cumsum(attrs["sections"])[:-1].tolist()
        outs = jnp.split(x, secs, axis=axis)
    else:
        outs = jnp.split(x, int(attrs["num"]), axis=axis)
    return {"Out": list(outs)}


@register("pad", family="shape")
def _pad(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    p = attrs["paddings"]
    pads = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads,
                            constant_values=attrs.get("pad_value", 0.0))]}


@register("crop", family="shape")
def _crop(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    offsets = attrs["offsets"]
    shape = attrs["shape"]
    idx = tuple(slice(int(o), int(o) + int(s))
                for o, s in zip(offsets, shape))
    return {"Out": [x[idx]]}


@register("squeeze", family="shape")
def _squeeze(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    axes = attrs.get("axes")
    return {"Out": [jnp.squeeze(x, axis=tuple(axes) if axes else None)]}


@register("unsqueeze", family="shape")
def _unsqueeze(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# reductions / stats
# ---------------------------------------------------------------------------


def _reduce(fn):
    def compute(ins, attrs, ctx):
        x = _dat(_one(ins, "X"))
        dim = attrs.get("dim")
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", dim is None):
            return {"Out": [fn(x)]}
        return {"Out": [fn(x, axis=int(dim), keepdims=keep)]}
    return compute


for _name, _fn in [("reduce_sum", jnp.sum), ("reduce_mean", jnp.mean),
                   ("reduce_max", jnp.max), ("reduce_min", jnp.min)]:
    register(_name, family="reduce")(_reduce(_fn))


@register("mean", family="reduce")
def _mean(ins, attrs, ctx):
    return {"Out": [jnp.mean(_dat(_one(ins, "X")))]}


@register("sum", family="reduce")
def _sum(ins, attrs, ctx):
    xs = [_dat(v) for v in ins["X"]]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register("minus", family="elementwise")
def _minus(ins, attrs, ctx):
    return {"Out": [_dat(_one(ins, "X")) - _dat(_one(ins, "Y"))]}


@register("top_k", family="search", no_grad=True)
def _top_k(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    k = int(attrs.get("k", 1))
    vals, idx = lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int32)]}


@register("accuracy", family="metric", no_grad=True)
def _accuracy(ins, attrs, ctx):
    pred = _dat(_one(ins, "Out"))          # top-k indices [N, k]
    label = _dat(_one(ins, "Label")).reshape(-1, 1)
    correct = jnp.any(pred == label, axis=1)
    # int32: jax defaults to 32-bit; the reference's int64 width is not
    # meaningful for batch-local counters
    total = jnp.array(pred.shape[0], jnp.int32)
    num_correct = jnp.sum(correct).astype(jnp.int32)
    return {"Accuracy": [num_correct.astype(jnp.float32) / pred.shape[0]],
            "Correct": [num_correct], "Total": [total]}


@register("argmax", family="search", no_grad=True)
def _argmax(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    return {"Out": [jnp.argmax(x, axis=int(attrs.get("axis", -1)))
                    .astype(jnp.int32)]}


# ---------------------------------------------------------------------------
# random / fill
# ---------------------------------------------------------------------------


@register("uniform_random", family="random", no_grad=True, uses_rng=True)
def _uniform_random(ins, attrs, ctx):
    key = ctx.rng_for(attrs.get("_rng_salt", 0))
    shape = tuple(int(s) for s in attrs["shape"])
    out = jax.random.uniform(key, shape, minval=attrs.get("min", -1.0),
                             maxval=attrs.get("max", 1.0))
    return {"Out": [out.astype(attrs.get("dtype", "float32"))]}


@register("gaussian_random", family="random", no_grad=True, uses_rng=True)
def _gaussian_random(ins, attrs, ctx):
    key = ctx.rng_for(attrs.get("_rng_salt", 1))
    shape = tuple(int(s) for s in attrs["shape"])
    out = (attrs.get("mean", 0.0)
           + attrs.get("std", 1.0) * jax.random.normal(key, shape))
    return {"Out": [out.astype(attrs.get("dtype", "float32"))]}


@register("fill_constant", family="fill", no_grad=True)
def _fill_constant(ins, attrs, ctx):
    shape = tuple(int(s) for s in attrs["shape"])
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0),
                             dtype=attrs.get("dtype", "float32"))]}


@register("fill_zeros_like", family="fill", no_grad=True)
def _fill_zeros_like(ins, attrs, ctx):
    x = _one(ins, "X")
    return {"Out": [_like(x, jnp.zeros_like(_dat(x)))]}


@register("increment", family="fill", no_grad=True)
def _increment(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))
    return {"Out": [x + attrs.get("step", 1.0)]}


@register("dropout", family="random", uses_rng=True)
def _dropout(ins, attrs, ctx):
    x = _one(ins, "X")
    prob = attrs.get("dropout_prob", 0.5)
    if ctx.is_test or attrs.get("is_test", False) or prob == 0.0:
        return {"Out": [x], "Mask": [jnp.ones_like(_dat(x))]}
    key = ctx.rng_for(attrs.get("_rng_salt", 2))
    mask = (jax.random.uniform(key, _dat(x).shape) >= prob).astype(
        _dat(x).dtype)
    return {"Out": [_like(x, _dat(x) * mask / (1.0 - prob))], "Mask": [mask]}


# ---------------------------------------------------------------------------
# recurrent building blocks (lstm_unit_op / gru_unit_op)
# ---------------------------------------------------------------------------


@register("lstm_unit", family="rnn")
def _lstm_unit(ins, attrs, ctx):
    x = _dat(_one(ins, "X"))          # [N, 4D] pre-activations i,f,c,o
    c_prev = _dat(_one(ins, "C_prev"))
    forget_bias = attrs.get("forget_bias", 0.0)
    i, f, g, o = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev \
        + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register("gru_unit", family="rnn")
def _gru_unit(ins, attrs, ctx):
    x = _dat(_one(ins, "Input"))       # [N, 3D] projected input
    h_prev = _dat(_one(ins, "HiddenPrev"))
    w = _dat(_one(ins, "Weight"))      # [D, 3D]: gates [D,2D] + cand [D,D]
    d = h_prev.shape[-1]
    gates_x, cand_x = x[:, :2 * d], x[:, 2 * d:]
    wg, wc = w[:, :2 * d], w[:, 2 * d:]
    b = _opt(ins, "Bias")
    gates = gates_x + h_prev @ wg
    cand_b = 0.0
    if b is not None:
        bd = _dat(b)
        gates = gates + bd[:2 * d]
        cand_b = bd[2 * d:]
    u, r = jnp.split(jax.nn.sigmoid(gates), 2, axis=-1)
    c = jnp.tanh(cand_x + (r * h_prev) @ wc + cand_b)
    h = u * h_prev + (1.0 - u) * c
    return {"Gate": [jnp.concatenate([u, r], -1)], "ResetHiddenPrev":
            [r * h_prev], "Hidden": [h]}


# ---------------------------------------------------------------------------
# sequence (LoD) ops — segment-id based, padding-free capability
# ---------------------------------------------------------------------------


def _seg_matrix(la: LoDArray):
    """[num_seq, rows] one-hot segment matrix (static per trace)."""
    ids = la.sequence_ids()
    n = la.num_sequences
    m = np.zeros((n, len(ids)), np.float32)
    m[ids, np.arange(len(ids))] = 1.0
    return jnp.asarray(m)


@register("sequence_pool", family="sequence")
def _sequence_pool(ins, attrs, ctx):
    x = _one(ins, "X")
    enforce_that(isinstance(x, LoDArray), "sequence_pool needs LoD input",
                 context="fluid")
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    seg = _seg_matrix(x)                     # [S, R]
    data = x.data.reshape(x.data.shape[0], -1)
    if ptype == "SUM":
        out = seg @ data
    elif ptype == "AVERAGE":
        # clamp: a zero-length sequence pools to 0, not 0/0 -> NaN
        cnt = jnp.maximum(jnp.sum(seg, axis=1, keepdims=True), 1.0)
        out = (seg @ data) / cnt
    elif ptype == "SQRT":
        cnt = jnp.maximum(jnp.sum(seg, axis=1, keepdims=True), 1.0)
        out = (seg @ data) / jnp.sqrt(cnt)
    elif ptype == "MAX":
        big = jnp.where(seg[:, :, None] > 0, data[None, :, :], -jnp.inf)
        out = jnp.max(big, axis=1)
        # a zero-length sequence has every row masked: pool to 0, not -inf
        empty = jnp.sum(seg, axis=1, keepdims=True) == 0
        out = jnp.where(empty, 0.0, out)
    elif ptype == "LAST":
        lod = np.asarray(x.lod[-1])
        offs = lod[1:] - 1
        empty = lod[1:] == lod[:-1]   # off-by-one would grab a neighbor row
        out = data[jnp.asarray(np.where(empty, 0, offs))]
        out = jnp.where(jnp.asarray(empty)[:, None], 0.0, out)
    elif ptype == "FIRST":
        lod = np.asarray(x.lod[-1])
        offs = np.minimum(lod[:-1], data.shape[0] - 1)
        empty = lod[1:] == lod[:-1]
        out = data[jnp.asarray(offs)]
        out = jnp.where(jnp.asarray(empty)[:, None], 0.0, out)
    else:
        raise EnforceError(f"bad pooltype {ptype}", context="fluid")
    return {"Out": [out.reshape((out.shape[0],) + x.data.shape[1:])]}


@register("sequence_softmax", family="sequence")
def _sequence_softmax(ins, attrs, ctx):
    x = _one(ins, "X")
    enforce_that(isinstance(x, LoDArray), "sequence_softmax needs LoD",
                 context="fluid")
    ids = jnp.asarray(x.sequence_ids())
    data = x.data.reshape(-1)
    n = x.num_sequences
    seg_max = jax.ops.segment_max(data, ids, num_segments=n)
    e = jnp.exp(data - seg_max[ids])
    seg_sum = jax.ops.segment_sum(e, ids, num_segments=n)
    return {"Out": [LoDArray((e / seg_sum[ids]).reshape(x.data.shape),
                             x.lod)]}


@register("sequence_concat", family="sequence")
def _sequence_concat(ins, attrs, ctx):
    xs = ins["X"]
    enforce_that(all(isinstance(v, LoDArray) for v in xs),
                 "sequence_concat needs LoD inputs", context="fluid")
    level = int(attrs.get("level", 0))
    axis = int(attrs.get("axis", 0))
    if axis == 1:
        return {"Out": [LoDArray(
            jnp.concatenate([v.data for v in xs], axis=1), xs[0].lod)]}
    # axis 0: interleave per sequence
    lods = [np.asarray(v.lod[-1]) for v in xs]
    pieces, new_offs = [], [0]
    for s in range(len(lods[0]) - 1):
        for v, lod in zip(xs, lods):
            pieces.append(v.data[int(lod[s]):int(lod[s + 1])])
        new_offs.append(new_offs[-1]
                        + sum(int(l[s + 1] - l[s]) for l in lods))
    del level
    return {"Out": [LoDArray(jnp.concatenate(pieces, axis=0),
                             (tuple(new_offs),))]}


@register("sequence_expand", family="sequence")
def _sequence_expand(ins, attrs, ctx):
    x, y = _one(ins, "X"), _one(ins, "Y")
    enforce_that(isinstance(y, LoDArray), "sequence_expand needs LoD Y",
                 context="fluid")
    ids = jnp.asarray(y.sequence_ids())
    xd = _dat(x)
    return {"Out": [LoDArray(jnp.take(xd, ids, axis=0), y.lod)]}


# ---------------------------------------------------------------------------
# recurrent op — sub-block over time via lax.scan (recurrent_op.cc analog,
# StaticRNN python/paddle/v2/framework/layers.py:333)
# ---------------------------------------------------------------------------


@register("recurrent", family="rnn")
def _recurrent(ins, attrs, ctx):
    enforce_that(ctx.trace_block is not None,
                 "recurrent op needs executor trace hook", context="fluid")
    xs = [_dat(v) for v in ins.get("Inputs", [])]        # each [T, B, ...]
    init_states = [_dat(v) for v in ins.get("InitStates", [])]
    params = list(ins.get("Parameters", []))
    step_in = list(attrs["step_inputs"])            # sub-block var names
    st_in = list(attrs["step_states_in"])
    st_out = list(attrs["step_states_out"])
    step_out = list(attrs["step_outputs"])
    param_names = list(attrs.get("param_names", []))
    sub_idx = int(attrs["sub_block"])

    def body(carry, xt):
        env = dict(zip(step_in, xt))
        env.update(zip(st_in, carry))
        # parameters enter through the op's input slots so program-level
        # autodiff (vjp over this compute) reaches them through the scan
        env.update(zip(param_names, params))
        env = ctx.trace_block(sub_idx, env)
        new_carry = tuple(env[n] for n in st_out)
        outs = tuple(env[n] for n in step_out)
        return new_carry, outs

    # reverse=True runs the recurrence from the last frame backwards with
    # outputs stacked at their original positions (lax.scan reverse, not an
    # output flip — the carry must flow backwards)
    carry, ys = lax.scan(body, tuple(init_states), tuple(xs),
                         reverse=bool(attrs.get("reverse", False)))
    return {"Outputs": list(ys), "FinalStates": list(carry)}


# ---------------------------------------------------------------------------
# control flow — cond (cond_op.h:28-46) and dynamic_recurrent
# (dynamic_recurrent_op.cc) analogs
# ---------------------------------------------------------------------------


@register("cond", family="control_flow")
def _cond(ins, attrs, ctx):
    """Dynamic if-else (reference cond_op.h:28-46: gather the true/false
    row subsets, run each subnet on its subset, scatter-merge).

    TPU-native: subset gather/scatter means dynamic shapes, which kill XLA
    tiling — instead BOTH sub-blocks run on the full batch and a per-row
    mask selects each output. Statically shaped, fully fusable; costs at
    most 2x branch FLOPs, which a masked-merge wins back by never leaving
    the compiled program."""
    enforce_that(ctx.trace_block is not None,
                 "cond op needs executor trace hook", context="fluid")
    cond = _dat(_one(ins, "Cond"))
    names = list(attrs.get("x_names", []))
    env0 = dict(zip(names, ins.get("Xs", [])))
    env_t = ctx.trace_block(int(attrs["true_block"]), dict(env0))
    env_f = ctx.trace_block(int(attrs["false_block"]), dict(env0))
    outs = []
    for tn, fn in zip(attrs["true_outputs"], attrs["false_outputs"]):
        t, f = _dat(env_t[tn]), _dat(env_f[fn])
        enforce_that(t.shape == f.shape,
                     f"cond branch shapes differ: {t.shape} vs {f.shape}",
                     context="fluid")
        m = cond.reshape((-1,) + (1,) * (t.ndim - 1)).astype(bool)
        outs.append(jnp.where(m, t, f))
    return {"Out": outs}


@register("dynamic_recurrent", family="rnn")
def _dynamic_recurrent(ins, attrs, ctx):
    """Variable-length RNN over a LoD batch (dynamic_recurrent_op.cc
    analog). The reference packs per-step TensorArrays and launches the
    step net T times; here the ragged batch is packed ONCE to padded
    time-major [T, B, ...] with host-side indices (the LoD is static per
    trace), a single ``lax.scan`` runs the step with mask-gated carries,
    and rows scatter back to LoD order. ``reverse=True`` packs each
    sequence back-to-front so the same forward scan IS the backward
    recurrence."""
    enforce_that(ctx.trace_block is not None,
                 "dynamic_recurrent needs executor trace hook",
                 context="fluid")
    x = _one(ins, "Inputs")
    enforce_that(isinstance(x, LoDArray),
                 "dynamic_recurrent needs a LoD input", context="fluid")
    init_states = [_dat(v) for v in ins.get("InitStates", [])]
    params = list(ins.get("Parameters", []))
    step_in = attrs["step_inputs"][0]
    st_in = list(attrs["step_states_in"])
    st_out = list(attrs["step_states_out"])
    step_out = list(attrs["step_outputs"])
    param_names = list(attrs.get("param_names", []))
    sub_idx = int(attrs["sub_block"])
    reverse = bool(attrs.get("reverse", False))

    lod = np.asarray(x.lod[-1])
    starts, lens = lod[:-1], lod[1:] - lod[:-1]
    n_seq, t_max = len(lens), int(lens.max()) if len(lens) else 0
    rows = x.data.reshape(x.data.shape[0], -1)

    # host-side pack/unpack index plans (LoD is trace-static)
    tb_idx = np.zeros((t_max, n_seq), np.int32)
    mask = np.zeros((t_max, n_seq), np.float32)
    flat_pos = np.zeros(int(lod[-1]), np.int64)
    for b in range(n_seq):
        for t in range(int(lens[b])):
            tt = int(lens[b]) - 1 - t if reverse else t
            tb_idx[tt, b] = starts[b] + t
            mask[tt, b] = 1.0
            flat_pos[starts[b] + t] = tt * n_seq + b

    xt = jnp.take(rows, jnp.asarray(tb_idx.reshape(-1)), axis=0)
    xt = xt.reshape(t_max, n_seq, -1)
    mask_d = jnp.asarray(mask)

    def body(carry, inp):
        x_t, m_t = inp
        env = {step_in: x_t}
        env.update(zip(st_in, carry))
        env.update(zip(param_names, params))
        env = ctx.trace_block(sub_idx, env)
        new_carry = []
        for c, n in zip(carry, st_out):
            nv = _dat(env[n])
            gate = m_t.reshape((-1,) + (1,) * (nv.ndim - 1))
            # finished sequences hold their final state (mask-gated carry)
            new_carry.append(gate * nv + (1.0 - gate) * c)
        outs = tuple(_dat(env[n]) for n in step_out)
        return tuple(new_carry), outs

    carry, ys = lax.scan(body, tuple(init_states), (xt, mask_d))
    pos = jnp.asarray(flat_pos)
    out_arrays = []
    for y in ys:
        flat = y.reshape(t_max * n_seq, *y.shape[2:])
        out_arrays.append(LoDArray(jnp.take(flat, pos, axis=0), x.lod))
    return {"Outputs": out_arrays, "FinalStates": list(carry)}


# ---------------------------------------------------------------------------
# checkpoint IO — save_restore_op.cc analog. These never enter the traced
# program: the Executor runs IO-only programs eagerly on the host (file IO
# inside an XLA program is nonsense); see Executor.run.
# ---------------------------------------------------------------------------


def _io_never_traced(ins, attrs, ctx):
    raise EnforceError(
        "save/restore are host-side ops: the Executor must run them "
        "eagerly, never trace them", context="fluid")


register("save", family="io", no_grad=True)(_io_never_traced)
register("restore", family="io", no_grad=True)(_io_never_traced)


# ---------------------------------------------------------------------------
# optimizer ops (sgd_op / momentum_op / adam_op ... — run server-side in the
# reference's pserver (ParameterServer2.cpp:362-541); here they're ordinary
# ops in the train program, sharded by pjit like everything else)
# ---------------------------------------------------------------------------


def _lr(ins):
    lr = _dat(_one(ins, "LearningRate"))
    return lr.reshape(()) if hasattr(lr, "reshape") else lr


@register("sgd", family="optimizer", stateful=("ParamOut",), no_grad=True)
def _sgd(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    return {"ParamOut": [p - _lr(ins) * g]}


@register("momentum", family="optimizer",
          stateful=("ParamOut", "VelocityOut"), no_grad=True)
def _momentum(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    v = _dat(_one(ins, "Velocity"))
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    v_new = mu * v + g
    if attrs.get("use_nesterov", False):
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register("adagrad", family="optimizer",
          stateful=("ParamOut", "MomentOut"), no_grad=True)
def _adagrad(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    m = _dat(_one(ins, "Moment"))
    eps = attrs.get("epsilon", 1e-6)
    m_new = m + jnp.square(g)
    return {"ParamOut": [p - _lr(ins) * g / (jnp.sqrt(m_new) + eps)],
            "MomentOut": [m_new]}


@register("adadelta", family="optimizer",
          stateful=("ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"),
          no_grad=True)
def _adadelta(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    ag = _dat(_one(ins, "AvgSquaredGrad"))
    au = _dat(_one(ins, "AvgSquaredUpdate"))
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    ag_new = rho * ag + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt(au + eps) / jnp.sqrt(ag_new + eps) * g
    au_new = rho * au + (1 - rho) * jnp.square(upd)
    return {"ParamOut": [p + _lr(ins) * upd], "AvgSquaredGradOut": [ag_new],
            "AvgSquaredUpdateOut": [au_new]}


@register("rmsprop", family="optimizer",
          stateful=("ParamOut", "MomentOut", "MeanSquareOut"), no_grad=True)
def _rmsprop(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    ms = _dat(_one(ins, "MeanSquare"))
    mom = _dat(_one(ins, "Moment"))
    rho, eps = attrs.get("decay", 0.9), attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    mom_new = momentum * mom + _lr(ins) * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": [p - mom_new], "MomentOut": [mom_new],
            "MeanSquareOut": [ms_new]}


@register("decayed_adagrad", family="optimizer",
          stateful=("ParamOut", "MomentOut"), no_grad=True)
def _decayed_adagrad(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    m = _dat(_one(ins, "Moment"))
    decay, eps = attrs.get("decay", 0.95), attrs.get("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    return {"ParamOut": [p - _lr(ins) * g / (jnp.sqrt(m_new) + eps)],
            "MomentOut": [m_new]}


@register("adam", family="optimizer",
          stateful=("ParamOut", "Moment1Out", "Moment2Out"), no_grad=True)
def _adam(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    m1, m2 = _dat(_one(ins, "Moment1")), _dat(_one(ins, "Moment2"))
    b1p = _dat(_one(ins, "Beta1Pow")).reshape(())
    b2p = _dat(_one(ins, "Beta2Pow")).reshape(())
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    lr = _lr(ins) * jnp.sqrt(1 - b2p * b2) / (1 - b1p * b1)
    return {"ParamOut": [p - lr * m1n / (jnp.sqrt(m2n) + eps)],
            "Moment1Out": [m1n], "Moment2Out": [m2n]}


@register("adamax", family="optimizer",
          stateful=("ParamOut", "MomentOut", "InfNormOut"), no_grad=True)
def _adamax(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    m, inf = _dat(_one(ins, "Moment")), _dat(_one(ins, "InfNorm"))
    b1p = _dat(_one(ins, "Beta1Pow")).reshape(())
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    lr = _lr(ins) / (1 - b1p * b1)
    return {"ParamOut": [p - lr * m_new / (inf_new + eps)],
            "MomentOut": [m_new], "InfNormOut": [inf_new]}


@register("proximal_gd", family="optimizer", stateful=("ParamOut",),
          no_grad=True)
def _proximal_gd(ins, attrs, ctx):
    p, g = _dat(_one(ins, "Param")), _dat(_one(ins, "Grad"))
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr = _lr(ins)
    prox = p - lr * g
    if l1 > 0:
        prox = jnp.sign(prox) * jax.nn.relu(jnp.abs(prox) - lr * l1)
    return {"ParamOut": [prox / (1.0 + lr * l2)]}


@register("beta_pow_update", family="optimizer",
          stateful=("Beta1PowOut", "Beta2PowOut"), no_grad=True)
def _beta_pow_update(ins, attrs, ctx):
    """Adam/Adamax beta^t accumulators (adam_op.cc keeps them as inputs;
    we advance them explicitly once per step)."""
    b1p = _dat(_one(ins, "Beta1Pow"))
    out = {"Beta1PowOut": [b1p * attrs.get("beta1", 0.9)]}
    if "Beta2Pow" in ins:
        out["Beta2PowOut"] = [_dat(_one(ins, "Beta2Pow"))
                              * attrs.get("beta2", 0.999)]
    return out
