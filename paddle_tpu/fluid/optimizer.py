"""Fluid-analog optimizers: append backward + optimize ops to the Program.

Reference analog: python/paddle/v2/framework/optimizer.py (SGD/Momentum/
Adagrad/Adam/Adamax/... each building optimize ops after
append_backward_ops) and the server-side optimizer ops the pserver runs
(ParameterServer2.cpp:362-541).

The optimize ops are ordinary program ops; under pjit they shard with the
parameters (ZeRO-style), which is the TPU-native replacement for running
them pserver-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.framework import (Parameter, Variable,
                                        default_main_program)
from paddle_tpu.platform.enforce import enforce_that


class Optimizer:
    op_type = ""

    def __init__(self, learning_rate: float = 0.01):
        self.learning_rate = float(learning_rate)
        self._lr_var: Optional[Variable] = None

    # -- accumulator helpers ------------------------------------------------

    def _lr(self) -> Variable:
        if self._lr_var is None:
            g = default_main_program().global_block()
            v = g.create_var(
                name=default_main_program().unique_name("learning_rate"),
                shape=(1,), dtype="float32", persistable=True)
            v.initializer = {"type": "constant",
                             "value": self.learning_rate}
            self._lr_var = v
        return self._lr_var

    def _accum(self, param: Parameter, suffix: str, value: float = 0.0,
               shape=None) -> Variable:
        g = default_main_program().global_block()
        v = g.create_var(name=f"{param.name}.{suffix}",
                         shape=shape if shape is not None else param.shape,
                         dtype=param.dtype, persistable=True)
        v.initializer = {"type": "constant", "value": value}
        return v

    # -- per-class hooks ----------------------------------------------------

    def _append_optimize_op(self, block, param: Parameter, grad: Variable):
        raise NotImplementedError

    def _finish(self, block):
        pass

    # -- public -------------------------------------------------------------

    def minimize(self, loss: Variable,
                 parameter_list: Optional[List[str]] = None):
        params_grads = append_backward(loss, parameter_list)
        enforce_that(len(params_grads) > 0, "no trainable parameters reach "
                     "the loss", context="optimizer")
        block = default_main_program().global_block()
        for p, g in params_grads:
            self._append_optimize_op(block, p, g)
        self._finish(block)
        return params_grads


class SGDOptimizer(Optimizer):
    op_type = "sgd"

    def _append_optimize_op(self, block, param, grad):
        block.append_op("sgd", inputs={"Param": param, "Grad": grad,
                                       "LearningRate": self._lr()},
                        outputs={"ParamOut": param})


class MomentumOptimizer(Optimizer):
    op_type = "momentum"

    def __init__(self, learning_rate=0.01, momentum=0.9,
                 use_nesterov=False):
        super().__init__(learning_rate)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _append_optimize_op(self, block, param, grad):
        vel = self._accum(param, "velocity")
        block.append_op("momentum",
                        inputs={"Param": param, "Grad": grad,
                                "Velocity": vel,
                                "LearningRate": self._lr()},
                        outputs={"ParamOut": param, "VelocityOut": vel},
                        attrs={"mu": self.momentum,
                               "use_nesterov": self.use_nesterov})


class AdagradOptimizer(Optimizer):
    op_type = "adagrad"

    def __init__(self, learning_rate=0.01, epsilon=1e-6):
        super().__init__(learning_rate)
        self.epsilon = epsilon

    def _append_optimize_op(self, block, param, grad):
        m = self._accum(param, "moment")
        block.append_op("adagrad",
                        inputs={"Param": param, "Grad": grad, "Moment": m,
                                "LearningRate": self._lr()},
                        outputs={"ParamOut": param, "MomentOut": m},
                        attrs={"epsilon": self.epsilon})


class AdadeltaOptimizer(Optimizer):
    op_type = "adadelta"

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6):
        super().__init__(learning_rate)
        self.rho, self.epsilon = rho, epsilon

    def _append_optimize_op(self, block, param, grad):
        ag = self._accum(param, "avg_squared_grad")
        au = self._accum(param, "avg_squared_update")
        block.append_op(
            "adadelta",
            inputs={"Param": param, "Grad": grad, "AvgSquaredGrad": ag,
                    "AvgSquaredUpdate": au, "LearningRate": self._lr()},
            outputs={"ParamOut": param, "AvgSquaredGradOut": ag,
                     "AvgSquaredUpdateOut": au},
            attrs={"rho": self.rho, "epsilon": self.epsilon})


class RMSPropOptimizer(Optimizer):
    op_type = "rmsprop"

    def __init__(self, learning_rate=0.01, decay=0.9, momentum=0.0,
                 epsilon=1e-6):
        super().__init__(learning_rate)
        self.decay, self.momentum, self.epsilon = decay, momentum, epsilon

    def _append_optimize_op(self, block, param, grad):
        ms = self._accum(param, "mean_square")
        mom = self._accum(param, "moment")
        block.append_op(
            "rmsprop",
            inputs={"Param": param, "Grad": grad, "MeanSquare": ms,
                    "Moment": mom, "LearningRate": self._lr()},
            outputs={"ParamOut": param, "MeanSquareOut": ms,
                     "MomentOut": mom},
            attrs={"decay": self.decay, "momentum": self.momentum,
                   "epsilon": self.epsilon})


class DecayedAdagradOptimizer(Optimizer):
    op_type = "decayed_adagrad"

    def __init__(self, learning_rate=0.01, decay=0.95, epsilon=1e-6):
        super().__init__(learning_rate)
        self.decay, self.epsilon = decay, epsilon

    def _append_optimize_op(self, block, param, grad):
        m = self._accum(param, "moment")
        block.append_op(
            "decayed_adagrad",
            inputs={"Param": param, "Grad": grad, "Moment": m,
                    "LearningRate": self._lr()},
            outputs={"ParamOut": param, "MomentOut": m},
            attrs={"decay": self.decay, "epsilon": self.epsilon})


class AdamOptimizer(Optimizer):
    op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self._b1p: Optional[Variable] = None
        self._b2p: Optional[Variable] = None

    def _pows(self):
        if self._b1p is None:
            g = default_main_program().global_block()
            prog = default_main_program()
            self._b1p = g.create_var(name=prog.unique_name("beta1_pow"),
                                     shape=(1,), dtype="float32",
                                     persistable=True)
            self._b1p.initializer = {"type": "constant", "value": 1.0}
            self._b2p = g.create_var(name=prog.unique_name("beta2_pow"),
                                     shape=(1,), dtype="float32",
                                     persistable=True)
            self._b2p.initializer = {"type": "constant", "value": 1.0}
        return self._b1p, self._b2p

    def _append_optimize_op(self, block, param, grad):
        m1 = self._accum(param, "moment1")
        m2 = self._accum(param, "moment2")
        b1p, b2p = self._pows()
        block.append_op(
            "adam",
            inputs={"Param": param, "Grad": grad, "Moment1": m1,
                    "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p,
                    "LearningRate": self._lr()},
            outputs={"ParamOut": param, "Moment1Out": m1,
                     "Moment2Out": m2},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon})

    def _finish(self, block):
        b1p, b2p = self._pows()
        block.append_op("beta_pow_update",
                        inputs={"Beta1Pow": b1p, "Beta2Pow": b2p},
                        outputs={"Beta1PowOut": b1p, "Beta2PowOut": b2p},
                        attrs={"beta1": self.beta1, "beta2": self.beta2})


class AdamaxOptimizer(Optimizer):
    op_type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self._b1p: Optional[Variable] = None

    def _pow(self):
        if self._b1p is None:
            prog = default_main_program()
            g = prog.global_block()
            self._b1p = g.create_var(name=prog.unique_name("beta1_pow"),
                                     shape=(1,), dtype="float32",
                                     persistable=True)
            self._b1p.initializer = {"type": "constant", "value": 1.0}
        return self._b1p

    def _append_optimize_op(self, block, param, grad):
        m = self._accum(param, "moment")
        inf = self._accum(param, "inf_norm")
        block.append_op(
            "adamax",
            inputs={"Param": param, "Grad": grad, "Moment": m,
                    "InfNorm": inf, "Beta1Pow": self._pow(),
                    "LearningRate": self._lr()},
            outputs={"ParamOut": param, "MomentOut": m, "InfNormOut": inf},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon})

    def _finish(self, block):
        block.append_op("beta_pow_update",
                        inputs={"Beta1Pow": self._pow()},
                        outputs={"Beta1PowOut": self._pow()},
                        attrs={"beta1": self.beta1})


class ProximalGDOptimizer(Optimizer):
    op_type = "proximal_gd"

    def __init__(self, learning_rate=0.01, l1=0.0, l2=0.0):
        super().__init__(learning_rate)
        self.l1, self.l2 = l1, l2

    def _append_optimize_op(self, block, param, grad):
        block.append_op("proximal_gd",
                        inputs={"Param": param, "Grad": grad,
                                "LearningRate": self._lr()},
                        outputs={"ParamOut": param},
                        attrs={"l1": self.l1, "l2": self.l2})
