"""Fluid-analog program IR: Program / Block / Operator / Variable.

Reference analog (Gen-2 "Fluid prototype"): the ProgramDesc protobuf IR
(paddle/framework/framework.proto:33-137) and its python graph builder
(python/paddle/v2/framework/framework.py:10-483 — Variable/Operator/Block/
Program/Parameter).

TPU-native design: the IR is a plain-python op graph. Nothing here executes —
``Executor`` (executor.py) traces a Program's ops into ONE pure jax function
and jit-compiles it, so at step time there is no per-op interpreter loop (the
reference's Executor runs one op at a time, executor.cc:59-88; here XLA fuses
across op boundaries). Sub-blocks (for the ``recurrent`` op) are traced into
``lax.scan`` bodies rather than re-entering an interpreter.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.platform.enforce import EnforceError, enforce_that

# ---------------------------------------------------------------------------
# dtypes (framework.proto DataType analog)
# ---------------------------------------------------------------------------

_DTYPES = ("float32", "float64", "float16", "bfloat16", "int32", "int64",
           "bool", "uint8")


def normalize_dtype(dtype) -> str:
    s = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
    enforce_that(s in _DTYPES, f"unsupported dtype {s}", context="fluid")
    return s


# ---------------------------------------------------------------------------
# Variable (VarDesc analog)
# ---------------------------------------------------------------------------


class Variable:
    """A named tensor slot in a Block (VarDesc analog, framework.proto:89-106).

    ``shape`` may contain -1 in the leading (batch) dim. ``lod_level`` > 0
    marks a LoDTensor-analog: at feed time the value carries ragged sequence
    boundaries (see executor.LoDArray; lod_tensor.h:57-80)."""

    def __init__(self, block: "Block", name: str, shape: Sequence[int] = (),
                 dtype="float32", lod_level: int = 0, persistable: bool = False,
                 trainable: bool = False, stop_gradient: bool = False):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = normalize_dtype(dtype)
        self.lod_level = int(lod_level)
        self.persistable = bool(persistable)
        self.trainable = bool(trainable)
        self.stop_gradient = bool(stop_gradient)
        self.initializer: Optional[dict] = None  # e.g. {"type": "normal", ...}
        self.op: Optional["Operator"] = None     # producing op, if any

    # Sugar so layers compose like expressions.
    def _binop(self, other, op_type):
        from paddle_tpu.fluid import layers as L
        return L._elementwise(op_type, self, other)

    def __add__(self, other):
        return self._binop(other, "elementwise_add")

    def __sub__(self, other):
        return self._binop(other, "elementwise_sub")

    def __mul__(self, other):
        return self._binop(other, "elementwise_mul")

    def __truediv__(self, other):
        return self._binop(other, "elementwise_div")

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, lod={self.lod_level}, "
                f"persistable={self.persistable})")


class Parameter(Variable):
    """A trainable, persistable Variable (framework.py Parameter analog)."""

    def __init__(self, block, name, shape, dtype="float32",
                 initializer: Optional[dict] = None, trainable: bool = True,
                 regularizer=None):
        super().__init__(block, name, shape=shape, dtype=dtype,
                         persistable=True, trainable=trainable)
        enforce_that(all(s > 0 for s in self.shape),
                     f"parameter {name} needs static shape, got {shape}",
                     context="fluid")
        self.initializer = initializer or {"type": "xavier"}
        self.regularizer = regularizer


# ---------------------------------------------------------------------------
# Operator (OpDesc analog)
# ---------------------------------------------------------------------------


@dataclass
class Operator:
    """One op node (OpDesc analog, framework.proto:33-57): a type string,
    named input/output slots each holding variable-name lists, and attrs."""

    type: str
    inputs: Dict[str, List[str]] = field(default_factory=dict)
    outputs: Dict[str, List[str]] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Operator({self.type}, in={ins}, out={outs})"


# ---------------------------------------------------------------------------
# Block / Program (BlockDesc / ProgramDesc analogs)
# ---------------------------------------------------------------------------


class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    # -- vars ---------------------------------------------------------------

    def create_var(self, name: Optional[str] = None, **kw) -> Variable:
        name = name or self.program.unique_name("tmp")
        enforce_that(name not in self.vars, f"duplicate var {name}",
                     context="fluid")
        v = Variable(self, name, **kw)
        self.vars[name] = v
        return v

    def create_parameter(self, name: Optional[str] = None, shape=(),
                         dtype="float32", **kw) -> Parameter:
        # parameters always live in block 0 (global scope analog,
        # executor.cc:62-66 persistable→global scope) so sub-block step
        # graphs can route them through op input slots for autodiff
        g = self.program.global_block()
        name = name or self.program.unique_name("param")
        enforce_that(name not in g.vars, f"duplicate param {name}",
                     context="fluid")
        p = Parameter(g, name, shape, dtype=dtype, **kw)
        g.vars[name] = p
        return p

    def var(self, name: str) -> Variable:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = (self.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        raise EnforceError(f"variable {name!r} not found in block {self.idx}",
                           context="fluid")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except EnforceError:
            return False

    # -- ops ----------------------------------------------------------------

    def append_op(self, type: str, inputs: Dict[str, Any] = None,
                  outputs: Dict[str, Any] = None,
                  attrs: Dict[str, Any] = None) -> Operator:
        def _names(d):
            out: Dict[str, List[str]] = {}
            for slot, vs in (d or {}).items():
                if vs is None:
                    continue
                vs = vs if isinstance(vs, (list, tuple)) else [vs]
                out[slot] = [v.name if isinstance(v, Variable) else str(v)
                             for v in vs]
            return out

        op = Operator(type=type, inputs=_names(inputs),
                      outputs=_names(outputs), attrs=dict(attrs or {}))
        from paddle_tpu.fluid import ops as op_lib
        op_lib.check_registered(type)
        self.ops.append(op)
        for slot, vs in (outputs or {}).items():
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            for v in vs:
                if isinstance(v, Variable):
                    v.op = op
        return op

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]


class Program:
    """ProgramDesc analog: an ordered list of Blocks; block 0 is global."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._name_counters: Dict[str, int] = {}
        self._current_block_idx = 0
        self.random_seed = 0
        self._version = 0  # bumped on mutation → executor cache key

    # -- naming -------------------------------------------------------------

    def unique_name(self, prefix: str) -> str:
        i = self._name_counters.get(prefix, 0)
        self._name_counters[prefix] = i + 1
        return f"{prefix}_{i}"

    # -- blocks -------------------------------------------------------------

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def create_block(self) -> Block:
        parent = self._current_block_idx
        b = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def rollback(self) -> None:
        enforce_that(self._current_block_idx != 0,
                     "rollback() at the global block", context="fluid")
        self._current_block_idx = self.current_block().parent_idx

    # -- introspection ------------------------------------------------------

    def fingerprint(self) -> Tuple:
        """Structural identity for executor compile caching."""
        sig = []
        for b in self.blocks:
            for op in b.ops:
                sig.append((b.idx, op.type,
                            tuple(sorted((k, tuple(v))
                                         for k, v in op.inputs.items())),
                            tuple(sorted((k, tuple(v))
                                         for k, v in op.outputs.items())),
                            tuple(sorted(
                                (k, _hashable(v))
                                for k, v in op.attrs.items()))))
        return tuple(sig)

    def to_string(self) -> str:
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.parent_idx}):")
            for name, v in b.vars.items():
                kind = "param" if isinstance(v, Parameter) else "var"
                lines.append(f"  {kind} {name}: {v.dtype}{list(v.shape)}"
                             + (f" lod={v.lod_level}" if v.lod_level else ""))
            for op in b.ops:
                lines.append(f"  op {op!r}")
        return "\n".join(lines)

    __str__ = to_string


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return (v.dtype.str, v.shape, v.tobytes())
    return v


# ---------------------------------------------------------------------------
# default program / guards (framework.py g_main_program analog)
# ---------------------------------------------------------------------------

_tls = threading.local()


def _stack() -> List[Program]:
    if not hasattr(_tls, "stack"):
        _tls.stack = [Program()]
    return _tls.stack


def default_main_program() -> Program:
    return _stack()[-1]


def reset_default_program() -> Program:
    _stack()[:] = [Program()]
    return _stack()[-1]


class program_guard:
    """`with program_guard(prog): ...` — layer calls build into `prog`."""

    def __init__(self, program: Program):
        self.program = program

    def __enter__(self):
        _stack().append(self.program)
        return self.program

    def __exit__(self, *exc):
        _stack().pop()
        return False


GRAD_SUFFIX = "@GRAD"


def grad_name(name: str) -> str:
    return name + GRAD_SUFFIX


def prune(program: Program, targets) -> Program:
    """Dead-op elimination: a new Program keeping only ops/vars the target
    variables depend on (framework/prune.cc analog). Grad/optimize ops are
    dropped unless a target depends on them — the inference-program
    extraction path."""
    names = {t.name if isinstance(t, Variable) else str(t) for t in targets}
    src = program.global_block()
    needed = set(names)
    kept: List[tuple] = []          # (old_idx, op)
    for idx in range(len(src.ops) - 1, -1, -1):
        op = src.ops[idx]
        if any(n in needed for n in op.output_names()):
            kept.append((idx, op))
            needed.update(op.input_names())
    kept.reverse()
    keep = [op for _, op in kept]
    # grad ops bind to their forward op positionally; dropping earlier ops
    # shifts indices, so fwd_idx must be remapped into the pruned program
    old_to_new = {old: new for new, (old, _) in enumerate(kept)}

    def copy_op(op: Operator) -> Operator:
        # inner name lists/attrs must not be shared: later mutation of the
        # pruned program must never corrupt the source program
        return Operator(op.type,
                        {k: list(v) for k, v in op.inputs.items()},
                        {k: list(v) for k, v in op.outputs.items()},
                        {k: (list(v) if isinstance(v, list) else v)
                         for k, v in op.attrs.items()})

    out = Program()
    out.random_seed = program.random_seed
    dst = out.global_block()
    block_map = {0: 0}
    for op in keep:
        if "sub_block" in op.attrs:
            sub = program.blocks[int(op.attrs["sub_block"])]
            nb = Block(out, len(out.blocks), parent_idx=0)
            nb.vars = dict(sub.vars)   # Variables are structural leaves
            nb.ops = [copy_op(sop) for sop in sub.ops]
            out.blocks.append(nb)
            block_map[sub.idx] = nb.idx
            for sop in sub.ops:
                needed.update(sop.input_names())
    for name in needed:
        if name in src.vars:
            dst.vars[name] = src.vars[name]
    for op in keep:
        new_op = copy_op(op)
        if "sub_block" in new_op.attrs:
            new_op.attrs["sub_block"] = block_map[
                int(new_op.attrs["sub_block"])]
        if "fwd_idx" in new_op.attrs:
            old = int(new_op.attrs["fwd_idx"])
            enforce_that(old in old_to_new,
                         f"grad op {new_op.type} survives pruning but its "
                         f"forward op (idx {old}) was pruned",
                         context="fluid")
            new_op.attrs["fwd_idx"] = old_to_new[old]
        dst.ops.append(new_op)
    return out
