"""paddle_tpu.fluid — the Gen-2 "Fluid prototype" analog, TPU-native.

Reference: paddle/framework (ProgramDesc/Scope/Operator/Executor/autodiff),
paddle/operators (76 op families), python/paddle/v2/framework (graph builder,
Executor, layers, optimizer) — see SURVEY.md §2.2.

Design: Program/Block/Operator IR built in python; ``append_backward`` is a
program transform adding grad ops; ``Executor`` traces the whole program into
one jit-compiled XLA function (grad ops via jax.vjp of the forward computes).
"""

from paddle_tpu.fluid import backward, io, layers, optimizer, ops
from paddle_tpu.fluid.backward import append_backward
from paddle_tpu.fluid.executor import Executor, Scope, global_scope
from paddle_tpu.fluid.framework import (Block, Operator, Parameter, Program,
                                        Variable, default_main_program,
                                        grad_name, program_guard,
                                        reset_default_program)
from paddle_tpu.fluid.ops import LoDArray, registered_ops

__all__ = [
    "backward", "io", "layers", "optimizer", "ops", "append_backward",
    "Executor", "Scope", "global_scope", "Block", "Operator", "Parameter",
    "Program", "Variable", "default_main_program", "grad_name",
    "program_guard", "reset_default_program", "LoDArray", "registered_ops",
]
