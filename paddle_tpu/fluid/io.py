"""Program-level checkpoint IO — the save_restore_op.cc + (later-era)
fluid.io surface.

Reference: paddle/operators/save_restore_op.cc (SaveOp writes each input
tensor's raw bytes under a folder attr; RestoreOp reads them back). Here
save/restore are host-side ops the Executor runs eagerly (never traced —
file IO inside an XLA program is nonsense); each variable lands as one
``<dir>/<name>.npy``.
"""

from __future__ import annotations

from typing import List, Optional

from paddle_tpu.fluid.framework import (Parameter, Program, Variable,
                                        default_main_program)
from paddle_tpu.platform.enforce import enforce_that


def _io_program(op_type: str, dirname: str, names: List[str]) -> Program:
    prog = Program()
    blk = prog.global_block()
    vars_ = [blk.create_var(name=n, shape=(1,), persistable=True)
             for n in names]
    if op_type == "save":
        blk.append_op("save", inputs={"X": vars_}, outputs={},
                      attrs={"path": dirname})
    else:
        blk.append_op("restore", inputs={}, outputs={"Out": vars_},
                      attrs={"path": dirname})
    return prog


def _persistable_names(main_program: Optional[Program],
                       predicate) -> List[str]:
    prog = main_program or default_main_program()
    return sorted(v.name for v in prog.global_block().vars.values()
                  if v.persistable and predicate(v))


def save_vars(executor, dirname: str, vars: List[Variable],
              scope=None) -> None:
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]
    enforce_that(bool(names), "save_vars: nothing to save", context="io")
    executor.run(_io_program("save", dirname, names), scope=scope)


def load_vars(executor, dirname: str, vars: List[Variable],
              scope=None) -> None:
    names = [v.name if isinstance(v, Variable) else str(v) for v in vars]
    enforce_that(bool(names), "load_vars: nothing to load", context="io")
    executor.run(_io_program("restore", dirname, names), scope=scope)


def save_params(executor, dirname: str,
                main_program: Optional[Program] = None, scope=None) -> None:
    """Persist trainable parameters only."""
    names = _persistable_names(main_program,
                               lambda v: isinstance(v, Parameter))
    executor.run(_io_program("save", dirname, names), scope=scope)


def load_params(executor, dirname: str,
                main_program: Optional[Program] = None, scope=None) -> None:
    names = _persistable_names(main_program,
                               lambda v: isinstance(v, Parameter))
    executor.run(_io_program("restore", dirname, names), scope=scope)


def save_persistables(executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope=None) -> None:
    """Persist every persistable var (params + optimizer slots + stats)."""
    names = _persistable_names(main_program, lambda v: True)
    executor.run(_io_program("save", dirname, names), scope=scope)


def load_persistables(executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope=None) -> None:
    names = _persistable_names(main_program, lambda v: True)
    executor.run(_io_program("restore", dirname, names), scope=scope)
