"""Fluid-analog Executor: traces a Program into ONE jitted XLA function.

Reference analog: paddle/framework/executor.cc:59-88 (create vars,
instantiate each OpDesc, run sequentially — an interpreter) and
python/paddle/v2/framework/executor.py (feed/fetch injection).

TPU-native design: instead of interpreting one op at a time, ``Executor.run``
traces the whole op list into a pure jax function of
``(persistable_values, feed_values, rng) -> (fetches, updated_persistables)``
and jit-compiles it, cached by (program fingerprint, feed shapes/lods). XLA
then fuses across op boundaries — the per-op dispatch the reference pays at
every step happens here exactly once per program/shape bucket.

Grad ops (backward.py) are executed with ``jax.vjp`` of the recorded forward
op applications; gradient fan-in is summed here (the reference emits add ops).
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.fluid import ops as op_lib
from paddle_tpu.fluid.framework import (Parameter, Program, Variable,
                                        default_main_program, grad_name)
from paddle_tpu.fluid.ops import ComputeCtx, LoDArray
from paddle_tpu.platform.enforce import EnforceError, enforce_that

# LoDArray must be a pytree so jax.vjp/jit can see through it.
jax.tree_util.register_pytree_node(
    LoDArray,
    lambda la: ((la.data,), la.lod),
    lambda lod, children: LoDArray(children[0], lod))


class Scope:
    """Persistable variable store (framework/scope.h analog, flat)."""

    def __init__(self):
        self.values: Dict[str, Any] = {}

    def find_var(self, name: str):
        return self.values.get(name)

    def set_var(self, name: str, value) -> None:
        self.values[name] = value

    def var_names(self) -> List[str]:
        return sorted(self.values)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _init_value(var: Parameter, seed: int) -> np.ndarray:
    """Materialise a parameter initializer (initializer.py analog)."""
    # crc32, not hash(): python string hashing is process-randomized and
    # would give every process (and host) different initial weights
    rng = np.random.RandomState(
        (seed * 2654435761 + zlib.crc32(var.name.encode())) % (2 ** 31))
    init = var.initializer or {"type": "xavier"}
    kind = init.get("type", "xavier")
    shape = var.shape
    if kind == "constant":
        out = np.full(shape, init.get("value", 0.0))
    elif kind == "uniform":
        low, high = init.get("low", -1.0), init.get("high", 1.0)
        out = rng.uniform(low, high, size=shape)
    elif kind == "normal":
        out = rng.normal(init.get("mean", 0.0), init.get("std", 1.0),
                         size=shape)
    elif kind == "xavier":
        fan_in = shape[0] if len(shape) else 1
        fan_out = shape[1] if len(shape) > 1 else fan_in
        if len(shape) == 4:  # OIHW conv filter
            rf = shape[2] * shape[3]
            fan_in, fan_out = shape[1] * rf, shape[0] * rf
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        out = rng.uniform(-limit, limit, size=shape)
    else:
        raise EnforceError(f"unknown initializer {kind!r}", context="fluid")
    return out.astype(var.dtype)


def _feed_to_value(v):
    if isinstance(v, LoDArray):
        return v
    if isinstance(v, tuple) and len(v) == 2:
        data, lod = v
        return LoDArray(np.asarray(data),
                        tuple(tuple(int(o) for o in lvl) for lvl in lod))
    return np.asarray(v)


def _abstract(v):
    if isinstance(v, LoDArray):
        return ("lod", v.lod, v.data.shape, str(v.data.dtype))
    a = np.asarray(v) if not hasattr(v, "shape") else v
    return (a.shape, str(a.dtype))


class Executor:
    """Runs Programs. ``place`` is accepted for API parity but jax device
    placement is global (paddle_tpu.platform)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Tuple, Any] = {}
        self._step = 0  # default rng stream advances per run

    # ------------------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed: Dict = None,
            fetch_list: Sequence = (), scope: Optional[Scope] = None,
            is_test: bool = False, seed: Optional[int] = None,
            return_numpy: bool = True):
        program = program or default_main_program()
        scope = scope or _global_scope
        feed = {k: _feed_to_value(v) for k, v in (feed or {}).items()}
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]

        io_ops = [op for op in program.global_block().ops
                  if not op.type.endswith("_grad")
                  and op_lib.get(op.type).family == "io"]
        if io_ops:
            enforce_that(
                len(io_ops) == len(program.global_block().ops),
                "save/restore programs must be IO-only (build them with "
                "fluid.io.save_vars/load_vars)", context="fluid")
            self._run_io(program, scope)
            return []

        self._materialize_params(program, scope)
        persist_names = self._persistable_names(program, scope)
        persist_vals = {n: scope.values[n] for n in persist_names}

        key = (program.fingerprint(), is_test, tuple(fetch_names),
               tuple(sorted((k, _abstract(v)) for k, v in feed.items())))
        fn = self._cache.get(key)
        if fn is None:
            # any new feed/fetch-name combination is a cache miss, so
            # validating (and statically verifying) only here still
            # covers every first use while steady state pays nothing
            self._validate_feed_fetch(program, feed, fetch_names)
            self._static_verify(program, feed, fetch_names)
            fn = self._compile(program, fetch_names, is_test, persist_names)
            self._cache[key] = fn

        rng = jax.random.PRNGKey(self._step if seed is None else seed)
        self._step += 1
        fetches, updates = fn(persist_vals, feed, rng)
        for n, v in updates.items():
            scope.values[n] = v
        if return_numpy:
            fetches = [np.asarray(f.data) if isinstance(f, LoDArray)
                       else np.asarray(f) for f in fetches]
        return fetches

    # ------------------------------------------------------------------
    @staticmethod
    def _validate_feed_fetch(program: Program, feed: Dict,
                             fetch_names: Sequence[str]) -> None:
        """Up-front feed/fetch validation: one clear diagnostic-style
        error naming every bad name at once, instead of a bare KeyError
        from deep inside the jit trace (fetch) or a silently-ignored
        feed (the old behavior for a mistyped feed name).  The validity
        definition itself lives in ONE place —
        ``analysis.program_check.feed_fetch_problems`` — shared with
        the verifier and the CLI (lazy import, like _static_verify)."""
        from paddle_tpu.analysis.program_check import feed_fetch_problems

        problems = feed_fetch_problems(program, tuple(feed),
                                       tuple(fetch_names))
        gb = program.global_block()
        enforce_that(not problems,
                     "invalid feed/fetch for this program:\n  "
                     + "\n  ".join(msg for _, msg in problems)
                     + f"\n(program has {len(gb.ops)} ops)",
                     context="fluid")

    def _static_verify(self, program: Program, feed: Dict,
                       fetch_names: Sequence[str]) -> None:
        """Static verification gate (FLAGS.fluid_verify): 'warn' logs
        the verifier's findings, 'strict' raises on ERRORs, 'off'
        skips.  Import is lazy so fluid does not depend on the analysis
        package at import time."""
        from paddle_tpu.platform.flags import FLAGS

        mode = str(getattr(FLAGS, "fluid_verify", "off")).lower()
        if mode in ("off", "0", "false", ""):
            return
        from paddle_tpu.analysis.diagnostics import Severity, format_report
        from paddle_tpu.analysis.program_check import verify_program

        # fetch_names=None on purpose: a per-run fetch list is NOT the
        # program's full sink set (another run may fetch the metric ops
        # this one skips), so inline dead-var analysis would cry wolf —
        # it stays a CLI concern where the fetch list is the user's
        # declared contract.  Dangling fetches are already rejected by
        # _validate_feed_fetch above.
        diags = verify_program(program, fetch_names=None,
                               feed_names=list(feed))
        if not diags:
            return
        errs = [d for d in diags if d.severity is Severity.ERROR]
        report = format_report(diags, title="fluid_verify:")
        if errs and mode == "strict":
            raise EnforceError(
                f"program verification failed ({len(errs)} error(s)):\n"
                + report, context="fluid")
        from paddle_tpu.platform import plog

        plog.warning("%s", report)

    # ------------------------------------------------------------------
    @staticmethod
    def _run_io(program: Program, scope: Scope) -> None:
        """Host-side save/restore (save_restore_op.cc analog): one .npy
        per variable under the op's ``path`` directory."""
        import os

        for op in program.global_block().ops:
            path = str(op.attrs["path"])
            if op.type == "save":
                os.makedirs(path, exist_ok=True)
                for name in op.inputs.get("X", []):
                    v = scope.find_var(name)
                    enforce_that(v is not None,
                                 f"save: no value for {name}",
                                 context="fluid")
                    np.save(os.path.join(path, name + ".npy"),
                            np.asarray(v))
            else:  # restore
                for name in op.outputs.get("Out", []):
                    f = os.path.join(path, name + ".npy")
                    enforce_that(os.path.exists(f),
                                 f"restore: missing {f}", context="fluid")
                    scope.set_var(name, np.load(f))

    # ------------------------------------------------------------------
    def _materialize_params(self, program: Program, scope: Scope) -> None:
        for var in program.global_block().vars.values():
            if var.persistable and var.name not in scope.values:
                if isinstance(var, Parameter):
                    scope.values[var.name] = _init_value(
                        var, program.random_seed)
                elif var.initializer is not None:
                    scope.values[var.name] = _init_value(
                        var, program.random_seed)  # typed init spec
                elif all(s > 0 for s in var.shape):
                    scope.values[var.name] = np.zeros(var.shape, var.dtype)

    def _persistable_names(self, program: Program, scope: Scope) -> List[str]:
        names = []
        for var in program.global_block().vars.values():
            if var.persistable and var.name in scope.values:
                names.append(var.name)
        return sorted(names)

    # ------------------------------------------------------------------
    def _compile(self, program: Program, fetch_names, is_test,
                 persist_names):
        block = program.global_block()
        written_persist = [
            n for n in persist_names
            if any(n in op.output_names() for op in block.ops)]

        def run_program(persist_vals, feed_vals, rng):
            values: Dict[str, Any] = {}
            values.update(persist_vals)
            values.update(feed_vals)
            ctx = ComputeCtx(rng, is_test)
            # record each forward op's actual inputs so grad ops and
            # aliased (in-place persistable) writes can't disagree
            recorded: Dict[int, Dict[str, List[Any]]] = {}

            def trace_block(sub_idx: int, env: Dict[str, Any]):
                sub = program.blocks[sub_idx]
                local = dict(env)

                def look(name):
                    return local[name] if name in local else values[name]

                for sop in sub.ops:
                    sins = {slot: [look(n) for n in ns]
                            for slot, ns in sop.inputs.items()}
                    souts = op_lib.get(sop.type).compute(
                        sins, dict(sop.attrs), ctx)
                    for slot, ns in sop.outputs.items():
                        for n, v in zip(ns, souts.get(slot, [])):
                            local[n] = v
                return local

            ctx.trace_block = trace_block

            for pos, op in enumerate(block.ops):
                if op.type.endswith("_grad"):
                    self._run_grad_op(op, block, values, recorded, ctx)
                    continue
                info = op_lib.get(op.type)
                attrs = dict(op.attrs)
                if info.uses_rng:
                    attrs.setdefault("_rng_salt", pos)
                ins = {slot: [values[n] for n in ns]
                       for slot, ns in op.inputs.items()}
                recorded[pos] = (ins, attrs)
                outs = info.compute(ins, attrs, ctx)
                for slot, ns in op.outputs.items():
                    vs = outs.get(slot, [])
                    enforce_that(len(vs) >= len(ns),
                                 f"op {op.type} slot {slot} produced "
                                 f"{len(vs)} values for {len(ns)} names",
                                 context="fluid")
                    for n, v in zip(ns, vs):
                        values[n] = v

            fetches = [values[n] for n in fetch_names]
            updates = {n: values[n] for n in written_persist}
            return fetches, updates

        from paddle_tpu.analysis.retrace import audit_jit

        return audit_jit(run_program, site="fluid.executor")

    # ------------------------------------------------------------------
    @staticmethod
    def _run_grad_op(op, block, values, recorded, ctx):
        fwd = block.ops[int(op.attrs["fwd_idx"])]
        info = op_lib.get(fwd.type)
        ins, attrs = recorded[int(op.attrs["fwd_idx"])]

        def f(ins_):
            return info.compute(ins_, attrs, ctx)

        primal_out, vjp_fn = jax.vjp(f, ins)

        # cotangent: grad value where present, zeros elsewhere
        def cot_for(name, template):
            t = template.data if isinstance(template, LoDArray) else template
            gname = grad_name(name)
            if gname in values:
                g = values[gname]
                g = g.data if isinstance(g, LoDArray) else g
                g = jnp.reshape(g, t.shape) if g.size == t.size else \
                    jnp.broadcast_to(g, t.shape)
            else:
                g = jnp.zeros_like(t)
            if isinstance(template, LoDArray):
                return LoDArray(g, template.lod)
            return g

        cot = {}
        for slot, ns in fwd.outputs.items():
            outs = primal_out.get(slot, [])
            cot[slot] = [cot_for(n, o) for n, o in zip(ns, outs)]
        for slot, outs in primal_out.items():
            if slot not in cot:
                cot[slot] = [jax.tree.map(jnp.zeros_like, o) for o in outs]
            # outputs the op produced beyond the named ones
            elif len(cot[slot]) < len(outs):
                cot[slot].extend(jax.tree.map(jnp.zeros_like, extra)
                                 for extra in outs[len(cot[slot]):])

        (gins,) = vjp_fn(cot)

        wanted = set(op.output("InGrad"))
        for slot, ns in fwd.inputs.items():
            for n, g in zip(ns, gins.get(slot, [])):
                gname = grad_name(n)
                if gname not in wanted:
                    continue
                gd = g.data if isinstance(g, LoDArray) else g
                if gd is None or (hasattr(gd, "dtype")
                                  and gd.dtype == jax.dtypes.float0):
                    continue
                if gname in values:
                    prev = values[gname]
                    pd = prev.data if isinstance(prev, LoDArray) else prev
                    gd = pd + gd
                values[gname] = gd
