"""append_backward: the program-level reverse-mode autodiff transform.

Reference analog: AppendBackward / BackwardRecursive
(paddle/framework/backward.cc:101,434; design doc framework/backward.md) —
walk the forward ops in reverse, appending one grad op per forward op and
``@GRAD`` variables.

TPU-native design: the IR transform is kept (grad ops appear in the Program,
inspectable and prunable), but each grad op carries NO hand-written kernel —
the Executor computes it with ``jax.vjp`` of the forward op's jax compute
(executor.py), so every op's gradient is exact by construction. Gradient
accumulation for fan-out vars is done by the executor summing contributions
(the reference inserts explicit add ops with @RENAME vars).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from paddle_tpu.fluid import ops as op_lib
from paddle_tpu.fluid.framework import (Block, Operator, Parameter, Program,
                                        Variable, grad_name)
from paddle_tpu.platform.enforce import enforce_that


def append_backward(loss: Variable, parameter_list: Optional[List[str]] = None,
                    no_grad_set: Optional[Set[str]] = None
                    ) -> List[tuple]:
    """Append grad ops for ``loss`` to its program's global block.

    Returns [(param, grad_var)] for all trainable parameters (or
    ``parameter_list``), mirroring the reference's optimizer contract
    (v2/framework/optimizer.py create_backward_pass)."""
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())

    # ---- forward reachability: which vars feed the loss ------------------
    ops = list(block.ops)
    needed: Set[str] = {loss.name}
    on_path: List[int] = []
    for idx in range(len(ops) - 1, -1, -1):
        op = ops[idx]
        info = op_lib.get(op.type)
        if info.no_grad:
            continue
        if any(n in needed for n in op.output_names()):
            on_path.append(idx)
            needed.update(op.input_names())
    on_path.reverse()

    # ---- seed d loss / d loss = 1 ---------------------------------------
    enforce_that(loss.name not in no_grad, "loss in no_grad_set",
                 context="backward")
    _make_grad_var(block, loss)
    block.append_op(
        type="fill_constant",
        outputs={"Out": [grad_name(loss.name)]},
        attrs={"shape": [1], "value": 1.0, "dtype": loss.dtype,
               "_seed_for": loss.name})

    # ---- one grad op per forward op, reverse order -----------------------
    for idx in reversed(on_path):
        op = ops[idx]
        out_grads = [grad_name(n) for n in op.output_names()]
        in_grads = []
        for n in op.input_names():
            if n in no_grad:
                continue
            v = block.var(n)
            if v.stop_gradient or v.dtype.startswith(("int", "bool", "uint")):
                continue
            _make_grad_var(block, v)
            in_grads.append(grad_name(n))
        if not in_grads:
            continue
        block.append_op(
            type=op.type + "_grad",
            inputs={"OutGrad": out_grads},
            outputs={"InGrad": in_grads},
            attrs={"fwd_idx": idx})

    # ---- collect (param, grad) pairs -------------------------------------
    params_and_grads = []
    for p in block.program.global_block().all_parameters():
        if parameter_list is not None and p.name not in parameter_list:
            continue
        if not p.trainable or p.name in no_grad:
            continue
        gname = grad_name(p.name)
        if block.has_var(gname):
            params_and_grads.append((p, block.var(gname)))
    return params_and_grads


def _make_grad_var(block: Block, v: Variable) -> Variable:
    gname = grad_name(v.name)
    if gname in block.vars:
        return block.vars[gname]
    g = block.create_var(name=gname, shape=v.shape, dtype=v.dtype,
                         lod_level=v.lod_level)
    return g
