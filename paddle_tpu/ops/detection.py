"""SSD detection math: prior boxes, IoU matching, box coding, NMS.

Reference analog: paddle/gserver/layers/PriorBox.cpp,
MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp and DetectionUtil.cpp.

TPU-native design: everything is fixed-shape and branch-free — matching is
a dense [num_priors, num_gt] IoU argmax (no per-box loops), hard-negative
mining is a top-k over masked losses, and NMS is a lax.fori_loop over a
static max_keep budget. All of it jits and batches with vmap.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


# ---------------------------------------------------------------------------
# prior (anchor) boxes
# ---------------------------------------------------------------------------


def prior_boxes(feat_h: int, feat_w: int, img_h: int, img_w: int,
                min_sizes: Sequence[float], max_sizes: Sequence[float],
                aspect_ratios: Sequence[float],
                variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                clip: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Static prior grid (PriorBoxLayer.cpp:forward analog).

    Returns (boxes [P, 4] in normalized xmin/ymin/xmax/ymax, variances
    [P, 4]). Priors per cell: one per min_size, one per sqrt(min*max),
    two per extra aspect ratio (r and 1/r)."""
    ars = [1.0]
    for r in aspect_ratios:
        if not any(abs(r - a) < 1e-6 for a in ars):
            ars.append(float(r))
            ars.append(1.0 / float(r))
    boxes = []
    for y in range(feat_h):
        for x in range(feat_w):
            cx = (x + 0.5) / feat_w
            cy = (y + 0.5) / feat_h
            for i, ms in enumerate(min_sizes):
                # square min box
                boxes.append([cx - ms / img_w / 2, cy - ms / img_h / 2,
                              cx + ms / img_w / 2, cy + ms / img_h / 2])
                if i < len(max_sizes):
                    s = float(np.sqrt(ms * max_sizes[i]))
                    boxes.append([cx - s / img_w / 2, cy - s / img_h / 2,
                                  cx + s / img_w / 2, cy + s / img_h / 2])
                for r in ars[1:]:
                    rw = ms * float(np.sqrt(r))
                    rh = ms / float(np.sqrt(r))
                    boxes.append([cx - rw / img_w / 2, cy - rh / img_h / 2,
                                  cx + rw / img_w / 2, cy + rh / img_h / 2])
    out = np.asarray(boxes, np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.asarray(variances, np.float32)[None, :],
                  (out.shape[0], 1))
    return out, var


def num_priors_per_cell(min_sizes, max_sizes, aspect_ratios) -> int:
    ars = {1.0}
    for r in aspect_ratios:
        ars.add(float(r))
        ars.add(1.0 / float(r))
    return len(min_sizes) + min(len(max_sizes), len(min_sizes)) \
        + len(min_sizes) * (len(ars) - 1)


# ---------------------------------------------------------------------------
# IoU / encode / decode (DetectionUtil.cpp jaccardOverlap/encodeBBox)
# ---------------------------------------------------------------------------


def iou_matrix(a: jax.Array, b: jax.Array) -> jax.Array:
    """[Na, 4] x [Nb, 4] → [Na, Nb] IoU."""
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def encode_boxes(gt: jax.Array, priors: jax.Array,
                 variances: jax.Array) -> jax.Array:
    """Ground-truth → regression targets wrt priors (encodeBBoxWithVar)."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-8)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-8)
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    t = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                   jnp.log(gw / pw), jnp.log(gh / ph)], axis=-1)
    return t / variances


def decode_boxes(loc: jax.Array, priors: jax.Array,
                 variances: jax.Array) -> jax.Array:
    """Regression preds → boxes (decodeBBoxWithVar analog)."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    v = variances
    cx = v[:, 0] * loc[:, 0] * pw + pcx
    cy = v[:, 1] * loc[:, 1] * ph + pcy
    w = jnp.exp(v[:, 2] * loc[:, 2]) * pw
    h = jnp.exp(v[:, 3] * loc[:, 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


# ---------------------------------------------------------------------------
# matching + multibox loss (MultiBoxLossLayer.cpp analog)
# ---------------------------------------------------------------------------


def match_priors(priors: jax.Array, gt_boxes: jax.Array,
                 gt_valid: jax.Array, overlap_threshold: float = 0.5):
    """Bipartite + per-prediction matching, dense.

    gt_boxes [G, 4] with validity mask [G]. Returns (match_idx [P] int32 —
    index into gt or -1, matched_iou [P])."""
    iou = iou_matrix(priors, gt_boxes)                  # [P, G]
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)                   # [P]
    best_iou = jnp.max(iou, axis=1)
    match = jnp.where(best_iou >= overlap_threshold, best_gt, -1)
    # bipartite pass: every valid gt claims its best prior. Non-claiming
    # gts are routed to an out-of-range index and dropped — a stale write
    # from an invalid gt must not clobber a real claim (scatter with
    # duplicate indices is order-undefined)
    best_prior = jnp.argmax(iou, axis=0)                # [G]
    g_idx = jnp.arange(gt_boxes.shape[0])
    has_any = jnp.max(iou, axis=0) > 0
    claim = gt_valid & has_any
    tgt = jnp.where(claim, best_prior, priors.shape[0])
    match = match.at[tgt].set(g_idx, mode="drop")
    return match.astype(jnp.int32), best_iou


def multibox_loss(loc_pred: jax.Array, conf_pred: jax.Array,
                  priors: jax.Array, prior_var: jax.Array,
                  gt_boxes: jax.Array, gt_labels: jax.Array,
                  gt_valid: jax.Array, num_classes: int,
                  overlap_threshold: float = 0.5,
                  neg_pos_ratio: float = 3.0,
                  background_id: int = 0) -> jax.Array:
    """Per-example SSD loss (conf xent + loc smooth-l1), hard-negative
    mined at neg:pos ratio. Shapes: loc_pred [P,4], conf_pred [P,C],
    gt_boxes [G,4], gt_labels [G] (excluding background), gt_valid [G]."""
    P = priors.shape[0]
    match, _ = match_priors(priors, gt_boxes, gt_valid, overlap_threshold)
    pos = match >= 0
    num_pos = jnp.sum(pos)

    safe = jnp.maximum(match, 0)
    target_box = encode_boxes(gt_boxes[safe], priors, prior_var)
    diff = loc_pred - target_box
    ad = jnp.abs(diff)
    sl1 = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5).sum(-1)
    loc_loss = jnp.sum(jnp.where(pos, sl1, 0.0))

    target_cls = jnp.where(pos, gt_labels[safe], background_id)
    logp = jax.nn.log_softmax(conf_pred, axis=-1)
    xent = -jnp.take_along_axis(logp, target_cls[:, None].astype(jnp.int32),
                                axis=-1)[:, 0]
    # hard negative mining: keep top (ratio * num_pos) negative losses
    neg_score = jnp.where(pos, -jnp.inf, xent)
    order = jnp.argsort(-neg_score)
    rank = jnp.zeros(P, jnp.int32).at[order].set(jnp.arange(P, dtype=jnp.int32))
    num_neg = jnp.minimum((neg_pos_ratio * num_pos).astype(jnp.int32),
                          P - num_pos)
    neg = (~pos) & (rank < num_neg)
    conf_loss = jnp.sum(jnp.where(pos | neg, xent, 0.0))
    denom = jnp.maximum(num_pos.astype(loc_loss.dtype), 1.0)
    return (conf_loss + loc_loss) / denom


# ---------------------------------------------------------------------------
# NMS + detection output (DetectionOutputLayer.cpp analog)
# ---------------------------------------------------------------------------


def nms(boxes: jax.Array, scores: jax.Array, iou_threshold: float,
        max_keep: int, iou: Optional[jax.Array] = None
        ) -> Tuple[jax.Array, jax.Array]:
    """Greedy NMS with a static keep budget. Pass a precomputed ``iou``
    matrix when suppressing the same boxes for many classes.

    Returns (keep_idx [max_keep] int32 (-1 padded), keep_mask [max_keep])."""
    n = boxes.shape[0]
    if iou is None:
        iou = iou_matrix(boxes, boxes)

    def body(i, state):
        alive, keep_idx, keep_ok = state
        masked = jnp.where(alive, scores, -jnp.inf)
        j = jnp.argmax(masked)
        ok = masked[j] > -jnp.inf
        keep_idx = keep_idx.at[i].set(jnp.where(ok, j, -1))
        keep_ok = keep_ok.at[i].set(ok)
        # kill j and everything overlapping it
        kill = (iou[j] >= iou_threshold) | (jnp.arange(n) == j)
        alive = alive & (~kill | ~ok)
        return alive, keep_idx, keep_ok

    alive0 = jnp.ones(n, bool)
    keep0 = jnp.full(max_keep, -1, jnp.int32)
    ok0 = jnp.zeros(max_keep, bool)
    _, keep_idx, keep_ok = lax.fori_loop(0, max_keep, body,
                                         (alive0, keep0, ok0))
    return keep_idx, keep_ok


def detection_output(loc_pred: jax.Array, conf_pred: jax.Array,
                     priors: jax.Array, prior_var: jax.Array,
                     num_classes: int, nms_threshold: float = 0.45,
                     confidence_threshold: float = 0.01,
                     keep_top_k: int = 100,
                     background_id: int = 0) -> jax.Array:
    """Per-example detections [keep_top_k, 6] = (label, score,
    xmin, ymin, xmax, ymax); invalid rows have label -1."""
    boxes = decode_boxes(loc_pred, priors, prior_var)      # [P, 4]
    probs = jax.nn.softmax(conf_pred, axis=-1)             # [P, C]
    iou = iou_matrix(boxes, boxes)       # class-invariant: computed once

    per_class = keep_top_k

    def one_class(c):
        scores = jnp.where(probs[:, c] >= confidence_threshold,
                           probs[:, c], -jnp.inf)
        keep_idx, keep_ok = nms(boxes, scores, nms_threshold, per_class,
                                iou=iou)
        safe = jnp.maximum(keep_idx, 0)
        det = jnp.concatenate([
            jnp.full((per_class, 1), c, jnp.float32),
            probs[safe, c][:, None],
            boxes[safe]], axis=-1)
        return jnp.where(keep_ok[:, None], det,
                         jnp.full_like(det, -1.0))

    cls_ids = [c for c in range(num_classes) if c != background_id]
    dets = jnp.concatenate([one_class(c) for c in cls_ids], axis=0)
    # global top keep_top_k by score
    score = jnp.where(dets[:, 0] >= 0, dets[:, 1], -jnp.inf)
    _, top = lax.top_k(score, keep_top_k)
    out = dets[top]
    return jnp.where(jnp.isfinite(score[top])[:, None], out,
                     jnp.full_like(out, -1.0))
