"""Embedding / table lookup — the TableProjection / lookup_table analog.

Reference: paddle/gserver/layers/TableProjection.cpp, cuda hl_table_apply.cu,
Gen-2 operators/lookup_table_op.cc (with SelectedRows sparse gradient).

The sparse-gradient capability (SelectedRows) is realized by the optimizer
treating embedding grads row-wise; the distributed row-sharded table lives in
paddle_tpu/parallel/embedding_sharded.py (all_to_all row exchange — the
GET_PARAM_SPARSE prefetch analog).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     padding_idx: int | None = None) -> jax.Array:
    """table: [V, D], ids: int [...]. Out-of-range ids clamp (reference pads)."""
    ids = ids.astype(jnp.int32)
    clipped = jnp.clip(ids, 0, table.shape[0] - 1)
    out = jnp.take(table, clipped, axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


def one_hot(ids: jax.Array, depth: int, dtype=jnp.float32) -> jax.Array:
    return jax.nn.one_hot(ids, depth, dtype=dtype)
