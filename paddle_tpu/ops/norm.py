"""Normalization kernels — BatchNorm/CrossMapNorm analogs.

Reference: paddle/gserver/layers/BatchNormalizationLayer.cpp,
CudnnBatchNormLayer.cpp (moving mean/var, use_global_stats),
CMRProjectionNormLayer + paddle/function/CrossMapNormalOp.cpp (LRN),
SumToOneNormLayer, RowL2NormLayer; Gen-2 paddle/operators/batch_norm_op.cc.

Batch norm is functional: ``batch_norm`` returns (y, new_moving_mean,
new_moving_var) in train mode so the trainer threads running statistics through
its state pytree — the TPU-native replacement for in-place moving buffers.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def batch_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               moving_mean: jax.Array, moving_var: jax.Array, *,
               train: bool, momentum: float = 0.9, eps: float = 1e-5,
               use_global_stats: Optional[bool] = None
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Normalize over all axes but the last (channel) axis.

    Works for [N, C] and [N, H, W, C]. Returns (y, new_mean, new_var).
    """
    reduce_axes = tuple(range(x.ndim - 1))
    use_batch_stats = train and not (use_global_stats or False)
    n = x.size // x.shape[-1]
    if use_batch_stats:
        # stats in f32 (bf16 mean/var over N*H*W elements loses too many
        # mantissa bits), via ONE fused pass: both sums are a multi-output
        # reduction XLA fuses into a single read of x, where the
        # mean-then-squared-deviation formulation costs two passes — for a
        # bandwidth-bound BN that second read is the dominant cost. The
        # sums are taken about the per-channel moving mean as a pilot so
        # E[d^2]-E[d]^2 subtracts small quantities even when |mean| >> std
        # (the raw-moment form cancels catastrophically there).
        pilot = jax.lax.stop_gradient(moving_mean).astype(jnp.float32)
        d = x.astype(jnp.float32) - pilot
        s1 = jnp.sum(d, axis=reduce_axes)
        s2 = jnp.sum(jnp.square(d), axis=reduce_axes)
        mean = pilot + s1 / n
        var = jnp.maximum(s2 / n - jnp.square(s1 / n), 0.0)
        unbiased = var * (n / max(1, n - 1))
        new_mean = momentum * moving_mean + (1.0 - momentum) * mean
        new_var = momentum * moving_var + (1.0 - momentum) * unbiased
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = jax.lax.rsqrt(var + eps)
    # fold the whole affine into per-channel scale/bias kept in f32 (the
    # folded bias can be large relative to the normalized signal, so
    # rounding it to bf16 before use adds error); only the final y is cast
    # to the activation dtype — HBM traffic is the bf16 read of x and
    # write of y either way, and XLA fuses the f32 elementwise middle
    scale = inv * gamma.astype(jnp.float32)
    bias = beta.astype(jnp.float32) - mean * scale
    y = (x.astype(jnp.float32) * scale + bias).astype(x.dtype)
    return y, new_mean, new_var


def layer_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               eps: float = 1e-5) -> jax.Array:
    """Row-stat normalization; like batch_norm above, statistics always
    reduce in f32 (bf16 residual streams exist under
    FLAGS.bf16_dense_activations), output in the input dtype."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mean) * jax.lax.rsqrt(var + eps) * gamma
            + beta).astype(x.dtype)


def cross_map_norm(x: jax.Array, size: int = 5, scale: float = 1e-4,
                   power: float = 0.75) -> jax.Array:
    """Local response normalization across channels (reference:
    function/CrossMapNormalOp.cpp). x: [N,H,W,C]."""
    # denominator in f32: bf16 activations would make the window-summed
    # squares (and the pow) lossy; cast back to the input dtype at the end
    sq = jnp.square(x.astype(jnp.float32))
    half = size // 2
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, size - 1 - half)))
    acc = jax.lax.reduce_window(padded, 0.0, jax.lax.add,
                                (1, 1, 1, size), (1, 1, 1, 1), "VALID")
    denom = jnp.power(1.0 + scale * acc, power)
    return (x.astype(jnp.float32) / denom).astype(x.dtype)


def sum_to_one_norm(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Normalize rows to sum 1 (reference: SumToOneNormLayer.cpp)."""
    return x / (jnp.sum(x, axis=-1, keepdims=True) + eps)


def row_l2_norm(x: jax.Array, eps: float = 1e-12) -> jax.Array:
    """Row-wise L2 normalization (reference: RowL2NormLayer.cpp)."""
    return x * jax.lax.rsqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True) + eps)
