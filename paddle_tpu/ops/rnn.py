"""Recurrent kernels — LSTM/GRU cells and time scans.

Reference: paddle/gserver/layers/LstmLayer.cpp, GatedRecurrentLayer.cpp and the
fused CUDA kernels hl_cuda_lstm.cu / hl_gpu_gru.cuh (all four gates in one
kernel). TPU-native: the gate matmul is one [B, 4H] MXU gemm per step inside a
``lax.scan``; XLA fuses the elementwise gate math — the same fusion the hand
-written CUDA kernels achieve, without hand-writing them.

Gate layout matches the reference (LstmCompute.cu): i, f, g(candidate), o.
Masked steps carry state through unchanged, which is how padded slots of
variable-length sequences stay exact (SequenceToBatch analog without the
reordering machinery).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.ops.math import matmul


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_cell(x_proj: jax.Array, state: LSTMState, w_h: jax.Array,
              bias: Optional[jax.Array] = None,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
              out_act=jnp.tanh) -> Tuple[jax.Array, LSTMState]:
    """One LSTM step. x_proj: [B, 4H] (input already projected), w_h: [H, 4H]
    or None when the h-recurrence is pre-projected into x_proj."""
    h, c = state
    gates = x_proj if w_h is None else x_proj + matmul(h, w_h)
    if bias is not None:
        gates = gates + bias
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = gate_act(i), gate_act(f), gate_act(o)
    g = cell_act(g)
    new_c = f * c + i * g
    new_h = o * out_act(new_c)
    return new_h, LSTMState(new_h, new_c)


def gru_cell(x_proj: jax.Array, h: jax.Array, w_h: jax.Array,
             bias: Optional[jax.Array] = None,
             gate_act=jax.nn.sigmoid, cand_act=jnp.tanh) -> jax.Array:
    """One GRU step (reference gate order: update z, reset r, candidate).

    x_proj: [B, 3H], w_h: [H, 3H] split as [H, 2H] (z,r) + [H, H] (candidate).
    """
    H = h.shape[-1]
    zr_x, c_x = x_proj[..., : 2 * H], x_proj[..., 2 * H:]
    w_zr, w_c = w_h[:, : 2 * H], w_h[:, 2 * H:]
    zr = zr_x + matmul(h, w_zr)
    if bias is not None:
        zr = zr + bias[: 2 * H]
    z, r = jnp.split(gate_act(zr), 2, axis=-1)
    c = c_x + matmul(r * h, w_c)
    if bias is not None:
        c = c + bias[2 * H:]
    c = cand_act(c)
    return (1.0 - z) * h + z * c


def lstm_scan(x: jax.Array, mask: jax.Array, w_x: Optional[jax.Array],
              w_h: jax.Array, bias: Optional[jax.Array], *,
              reverse: bool = False, init: Optional[LSTMState] = None,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh, out_act=jnp.tanh
              ) -> Tuple[jax.Array, LSTMState]:
    """Full-sequence LSTM: x [B,T,D], mask [B,T] -> (h_all [B,T,H], final).

    The input projection for ALL timesteps is one [B*T, D]x[D, 4H] gemm — the
    big-MXU-matmul formulation; the scan carries only the [H,4H] recurrence.
    ``w_x=None`` means x is already projected to [B,T,4H] (the reference's
    ``lstmemory`` contract: projection happens in the upstream mixed/fc layer).
    """
    B, T, _ = x.shape
    H = w_h.shape[0]
    xp = matmul(x, w_x) if w_x is not None else x  # [B, T, 4H]
    if init is None:
        init = LSTMState(jnp.zeros((B, H), xp.dtype), jnp.zeros((B, H), xp.dtype))

    def step(state, inp):
        xt, mt = inp
        h, new_state = lstm_cell(xt, state, w_h, bias, gate_act, cell_act, out_act)
        m = mt[:, None].astype(h.dtype)
        new_state = LSTMState(m * new_state.h + (1 - m) * state.h,
                              m * new_state.c + (1 - m) * state.c)
        return new_state, new_state.h

    xs = (jnp.swapaxes(xp, 0, 1), jnp.swapaxes(mask, 0, 1))
    final, hs = jax.lax.scan(step, init, xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), final


def gru_scan(x: jax.Array, mask: jax.Array, w_x: Optional[jax.Array],
             w_h: jax.Array, bias: Optional[jax.Array], *,
             reverse: bool = False,
             init: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence GRU: x [B,T,D] -> (h_all [B,T,H], final_h).
    ``w_x=None`` means x is already [B,T,3H] (grumemory contract)."""
    B, T, _ = x.shape
    H = w_h.shape[0]
    xp = matmul(x, w_x) if w_x is not None else x  # [B, T, 3H]
    h0 = init if init is not None else jnp.zeros((B, H), xp.dtype)

    def step(h, inp):
        xt, mt = inp
        new_h = gru_cell(xt, h, w_h, bias)
        m = mt[:, None].astype(new_h.dtype)
        new_h = m * new_h + (1 - m) * h
        return new_h, new_h

    xs = (jnp.swapaxes(xp, 0, 1), jnp.swapaxes(mask, 0, 1))
    final, hs = jax.lax.scan(step, h0, xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), final
