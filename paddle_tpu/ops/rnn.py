"""Recurrent kernels — LSTM/GRU cells and time scans.

Reference: paddle/gserver/layers/LstmLayer.cpp, GatedRecurrentLayer.cpp and the
fused CUDA kernels hl_cuda_lstm.cu / hl_gpu_gru.cuh (all four gates in one
kernel). TPU-native: the gate matmul is one [B, 4H] MXU gemm per step inside a
``lax.scan``; XLA fuses the elementwise gate math — the same fusion the hand
-written CUDA kernels achieve, without hand-writing them.

Gate layout matches the reference (LstmCompute.cu): i, f, g(candidate), o.
Masked steps carry state through unchanged, which is how padded slots of
variable-length sequences stay exact (SequenceToBatch analog without the
reordering machinery).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.math import matmul
from paddle_tpu.platform.flags import FLAGS


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_cell(x_proj: jax.Array, state: LSTMState, w_h: jax.Array,
              bias: Optional[jax.Array] = None,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh,
              out_act=jnp.tanh) -> Tuple[jax.Array, LSTMState]:
    """One LSTM step. x_proj: [B, 4H] (input already projected), w_h: [H, 4H]
    or None when the h-recurrence is pre-projected into x_proj."""
    h, c = state
    gates = x_proj if w_h is None else x_proj + matmul(h, w_h)
    if bias is not None:
        gates = gates + bias
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = gate_act(i), gate_act(f), gate_act(o)
    g = cell_act(g)
    new_c = f * c + i * g
    new_h = o * out_act(new_c)
    return new_h, LSTMState(new_h, new_c)


def gru_cell(x_proj: jax.Array, h: jax.Array, w_h: jax.Array,
             bias: Optional[jax.Array] = None,
             gate_act=jax.nn.sigmoid, cand_act=jnp.tanh) -> jax.Array:
    """One GRU step (reference gate order: update z, reset r, candidate).

    x_proj: [B, 3H], w_h: [H, 3H] split as [H, 2H] (z,r) + [H, H] (candidate).
    """
    H = h.shape[-1]
    zr_x, c_x = x_proj[..., : 2 * H], x_proj[..., 2 * H:]
    w_zr, w_c = w_h[:, : 2 * H], w_h[:, 2 * H:]
    zr = zr_x + matmul(h, w_zr)
    if bias is not None:
        zr = zr + bias[: 2 * H]
    z, r = jnp.split(gate_act(zr), 2, axis=-1)
    c = c_x + matmul(r * h, w_c)
    if bias is not None:
        c = c + bias[2 * H:]
    c = cand_act(c)
    return (1.0 - z) * h + z * c


# ---------------------------------------------------------------------------
# Fused pallas LSTM step — the hl_cuda_lstm.cu analog: recurrent gate gemm
# + all four gates' elementwise math in ONE kernel, fp32 accumulation, so
# the per-step intermediates (gates, candidate) never round-trip to HBM.
# Backward is closed-form plain JAX over saved activations (one gemm pair).
# ---------------------------------------------------------------------------


def _lstm_fused_kernel_tiled(xp_ref, h_ref, c_ref, wh_ref, b_ref, newh_ref,
                             newc_ref, acts_ref=None):
    """Hidden-tiled variant: this grid step owns hidden units [jT, (j+1)T).

    xp/b/wh arrive pre-reshaped with a separate gate axis ([B,4,T], [1,4,T],
    [H,4,T]) so a BlockSpec can slice one hidden tile of all four gates;
    the full previous h ([B,H]) is the gemm contraction input and is the
    same for every tile."""
    xp = xp_ref[...].astype(jnp.float32)            # [B, 4, T]
    h = h_ref[...].astype(jnp.float32)              # [B, H]
    c = c_ref[...].astype(jnp.float32)              # [B, T]
    wh = wh_ref[...].astype(jnp.float32)            # [H, 4, T]
    gates = xp + jax.lax.dot_general(
        h, wh, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [B, 4, T]
    gates = gates + b_ref[...].astype(jnp.float32)
    i = jax.nn.sigmoid(gates[:, 0])
    f = jax.nn.sigmoid(gates[:, 1])
    g = jnp.tanh(gates[:, 2])
    o = jax.nn.sigmoid(gates[:, 3])
    new_c = f * c + i * g
    tanh_nc = jnp.tanh(new_c)
    newh_ref[...] = (o * tanh_nc).astype(newh_ref.dtype)
    newc_ref[...] = new_c.astype(newc_ref.dtype)
    if acts_ref is not None:
        acts_ref[...] = jnp.stack([i, f, g, o, tanh_nc], axis=1)  # [B,5,T]


def _hidden_tile(H: int, B: int, gate_cols: int, io_rows: int):
    """Largest hidden tile for a fused RNN kernel: H itself (grid=(1,),
    the whole-cell case) or a lane-aligned (multiple-of-128) divisor of H.
    Per-tile residents: weight slice [H, gate_cols, t] f32 + the full h
    [B, H] + ``io_rows`` [B, t] rows. None = no admissible tile ->
    plain-XLA fallback."""
    cands = [H] + [d for d in range(128, H, 128) if H % d == 0]
    for t in sorted(cands, reverse=True):
        if (H * gate_cols * t + B * H + B * io_rows * t) * 4 \
                <= _FUSED_VMEM_BUDGET:
            return t
    return None


def _lstm_tile(H: int, B: int):
    # accounting matches the 17-row single-block guard at t == H
    return _hidden_tile(H, B, 4, 16)


def _fused_call(xp, h, c, w_h, bias, interpret, save_acts: bool):
    B, H = h.shape
    t = _lstm_tile(H, B)
    if t is None:
        raise ValueError(f"no fused-LSTM tile for H={H} B={B}; "
                         "_use_fused should have fallen back")
    n = H // t
    enums = [
        jax.ShapeDtypeStruct((B, H), xp.dtype),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((B, t), lambda j: (0, j)),
        pl.BlockSpec((B, t), lambda j: (0, j)),
    ]
    if save_acts:
        enums.append(jax.ShapeDtypeStruct((B, 5, H), jnp.float32))
        out_specs.append(pl.BlockSpec((B, 5, t), lambda j: (0, 0, j)))
    outs = pl.pallas_call(
        _lstm_fused_kernel_tiled,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((B, 4, t), lambda j: (0, 0, j)),     # xp
            pl.BlockSpec((B, H), lambda j: (0, 0)),           # h (full)
            pl.BlockSpec((B, t), lambda j: (0, j)),           # c tile
            pl.BlockSpec((H, 4, t), lambda j: (0, 0, j)),     # w_h tile
            pl.BlockSpec((1, 4, t), lambda j: (0, 0, j)),     # bias tile
        ],
        out_shape=enums,
        out_specs=out_specs,
        interpret=interpret,
    )(xp.reshape(B, 4, H), h, c, w_h.reshape(H, 4, H),
      bias.reshape(1, 4, H))
    if save_acts:
        new_h, new_c, acts = outs
        return new_h, new_c, acts.reshape(B, 5 * H)
    return outs


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_lstm_cell(xp, h, c, w_h, bias, interpret):
    # primal-only variant skips the (B, 5H) acts write entirely —
    # inference/eval passes shouldn't pay HBM for backward residuals
    new_h, new_c = _fused_call(xp, h, c, w_h, bias, interpret,
                               save_acts=False)
    return new_h, new_c


def _fused_lstm_fwd(xp, h, c, w_h, bias, interpret):
    new_h, new_c, acts = _fused_call(xp, h, c, w_h, bias, interpret,
                                     save_acts=True)
    # zero-size tokens carry primal dtypes (a bare dtype is not a JAX type)
    return (new_h, new_c), (h, c, w_h, acts, jnp.zeros((0,), xp.dtype),
                            jnp.zeros((0,), bias.dtype))


def _fused_lstm_bwd(interpret, res, grads):
    d_newh, d_newc = grads
    h, c, w_h, acts, xp_token, bias_token = res
    xp_dtype = xp_token.dtype
    H = h.shape[1]
    i, f, g, o, tanh_nc = (acts[:, :H], acts[:, H:2 * H], acts[:, 2 * H:3 * H],
                           acts[:, 3 * H:4 * H], acts[:, 4 * H:])
    d_newh = d_newh.astype(jnp.float32)
    d_newc = d_newc.astype(jnp.float32)
    do_ = d_newh * tanh_nc
    dct = d_newc + d_newh * o * (1.0 - tanh_nc * tanh_nc)
    dgates = jnp.concatenate([
        dct * g * i * (1.0 - i),
        dct * c.astype(jnp.float32) * f * (1.0 - f),
        dct * i * (1.0 - g * g),
        do_ * o * (1.0 - o),
    ], axis=1)
    dxp = dgates.astype(xp_dtype)
    dh = matmul(dgates, w_h, trans_b=True).astype(h.dtype)
    dc = (dct * f).astype(c.dtype)
    dwh = matmul(h.astype(jnp.float32), dgates,
                 trans_a=True).astype(w_h.dtype)
    db = jnp.sum(dgates, axis=0).astype(bias_token.dtype)
    return dxp, dh, dc, dwh, db


_fused_lstm_cell.defvjp(_fused_lstm_fwd, _fused_lstm_bwd)


def _gru_fused_kernel(xp_ref, h_ref, wh_ref, b_ref, newh_ref, acts_ref=None):
    """Fused GRU step (hl_gpu_gru.cuh analog): both recurrent gemms + all
    gate elementwise in one kernel, fp32 accumulation. Gate order matches
    gru_cell: update z, reset r, candidate."""
    xp = xp_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    wh = wh_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    hd = h.shape[1]
    zr = xp[:, :2 * hd] + jax.lax.dot_general(
        h, wh[:, :2 * hd], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b[:, :2 * hd]
    z = jax.nn.sigmoid(zr[:, :hd])
    r = jax.nn.sigmoid(zr[:, hd:])
    c = jnp.tanh(xp[:, 2 * hd:] + jax.lax.dot_general(
        r * h, wh[:, 2 * hd:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b[:, 2 * hd:])
    newh_ref[...] = ((1.0 - z) * h + z * c).astype(newh_ref.dtype)
    if acts_ref is not None:
        acts_ref[...] = jnp.concatenate([z, r, c], axis=1)


def _gru_zr_kernel_tiled(xp_ref, h_ref, wzr_ref, b_ref, z_ref, r_ref):
    """Phase 1, hidden tile: update/reset gates for units [jT, (j+1)T)."""
    h = h_ref[...].astype(jnp.float32)                       # [B, H]
    zr = xp_ref[...].astype(jnp.float32) + jax.lax.dot_general(
        h, wzr_ref[...].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[...].astype(jnp.float32)
    z_ref[...] = jax.nn.sigmoid(zr[:, 0])
    r_ref[...] = jax.nn.sigmoid(zr[:, 1])


def _gru_cand_kernel_tiled(rh_ref, xpc_ref, wc_ref, bc_ref, z_ref, h_ref,
                           newh_ref, c_ref=None):
    """Phase 2, hidden tile: candidate + output for units [jT, (j+1)T).
    Needs the COMPLETE r*h (phase-1 result) as the gemm input — the reset
    gate couples every hidden unit into every candidate column, which is
    why the GRU needs two kernels where the LSTM needs one. ``c_ref``
    (backward residual) is only written when training asks for it."""
    rh = rh_ref[...].astype(jnp.float32)                     # [B, H]
    c = jnp.tanh(xpc_ref[...].astype(jnp.float32) + jax.lax.dot_general(
        rh, wc_ref[...].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + bc_ref[...].astype(jnp.float32))
    z = z_ref[...].astype(jnp.float32)
    h = h_ref[...].astype(jnp.float32)
    newh_ref[...] = ((1.0 - z) * h + z * c).astype(newh_ref.dtype)
    if c_ref is not None:
        c_ref[...] = c


def _gru_tile(H: int, B: int):
    # the binding constraint is phase 1's w_zr slice [H, 2, t]
    return _hidden_tile(H, B, 2, 10)


def _gru_fused_plan(H: int, B: int, w_h):
    """THE fused-GRU dispatch decision (used by gru_scan AND
    _gru_fused_call so they cannot drift): "block", a tile size, or None
    (plain-XLA fallback)."""
    if _fused_vmem_ok(w_h, B, 11):
        return "block"
    return _gru_tile(H, B)


def _gru_fused_call(xp, h, w_h, bias, interpret, save_acts: bool):
    B, H = h.shape
    plan = _gru_fused_plan(H, B, w_h)
    if plan == "block":                 # single-block fast path
        out_shape = [jax.ShapeDtypeStruct((B, H), xp.dtype)]
        if save_acts:
            out_shape.append(jax.ShapeDtypeStruct((B, 3 * H), jnp.float32))
        out = pl.pallas_call(
            _gru_fused_kernel,
            out_shape=out_shape,
            interpret=interpret,
        )(xp, h, w_h, bias.reshape(1, -1))
        return out if save_acts else (out[0], None)
    # two-phase hidden-tiled path (large H): zr gates, then candidate
    t = plan
    if t is None:
        raise ValueError(f"no fused-GRU tile for H={H} B={B}; the caller "
                         "should have taken the plain-XLA path")
    n = H // t
    z, r = pl.pallas_call(
        _gru_zr_kernel_tiled,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((B, 2, t), lambda j: (0, 0, j)),      # xp_zr
            pl.BlockSpec((B, H), lambda j: (0, 0)),            # h full
            pl.BlockSpec((H, 2, t), lambda j: (0, 0, j)),      # w_zr
            pl.BlockSpec((1, 2, t), lambda j: (0, 0, j)),      # b_zr
        ],
        out_shape=[jax.ShapeDtypeStruct((B, H), jnp.float32),
                   jax.ShapeDtypeStruct((B, H), jnp.float32)],
        out_specs=[pl.BlockSpec((B, t), lambda j: (0, j)),
                   pl.BlockSpec((B, t), lambda j: (0, j))],
        interpret=interpret,
    )(xp[:, : 2 * H].reshape(B, 2, H), h, w_h[:, : 2 * H].reshape(H, 2, H),
      bias[: 2 * H].reshape(1, 2, H))
    rh = (r * h.astype(jnp.float32))
    out_shape = [jax.ShapeDtypeStruct((B, H), xp.dtype)]
    out_specs = [pl.BlockSpec((B, t), lambda j: (0, j))]
    if save_acts:  # c is a backward residual; inference skips the write
        out_shape.append(jax.ShapeDtypeStruct((B, H), jnp.float32))
        out_specs.append(pl.BlockSpec((B, t), lambda j: (0, j)))
    outs = pl.pallas_call(
        _gru_cand_kernel_tiled,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((B, H), lambda j: (0, 0)),            # r*h full
            pl.BlockSpec((B, t), lambda j: (0, j)),            # xp_c
            pl.BlockSpec((H, t), lambda j: (0, j)),            # w_c
            pl.BlockSpec((1, t), lambda j: (0, j)),            # b_c
            pl.BlockSpec((B, t), lambda j: (0, j)),            # z
            pl.BlockSpec((B, t), lambda j: (0, j)),            # h
        ],
        out_shape=out_shape,
        out_specs=out_specs,
        interpret=interpret,
    )(rh, xp[:, 2 * H:], w_h[:, 2 * H:], bias[2 * H:].reshape(1, H), z, h)
    if save_acts:
        new_h, c = outs
        return new_h, jnp.concatenate([z, r, c], axis=1)
    return outs[0], None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_gru_cell(xp, h, w_h, bias, interpret):
    new_h, _ = _gru_fused_call(xp, h, w_h, bias, interpret, save_acts=False)
    return new_h


def _fused_gru_fwd(xp, h, w_h, bias, interpret):
    new_h, acts = _gru_fused_call(xp, h, w_h, bias, interpret,
                                  save_acts=True)
    return new_h, (h, w_h, acts, jnp.zeros((0,), xp.dtype),
                   jnp.zeros((0,), bias.dtype))


def _fused_gru_bwd(interpret, res, d_newh):
    h, w_h, acts, xp_token, bias_token = res
    H = h.shape[1]
    z, r, c = acts[:, :H], acts[:, H:2 * H], acts[:, 2 * H:]
    hf = h.astype(jnp.float32)
    d_newh = d_newh.astype(jnp.float32)
    dz = d_newh * (c - hf)
    dc = d_newh * z
    dh = d_newh * (1.0 - z)
    dgc = dc * (1.0 - c * c)
    d_rh = matmul(dgc, w_h[:, 2 * H:], trans_b=True)
    dr = d_rh * hf
    dh = dh + d_rh * r
    dgz = dz * z * (1.0 - z)
    dgr = dr * r * (1.0 - r)
    dgzr = jnp.concatenate([dgz, dgr], axis=1)
    dh = dh + matmul(dgzr, w_h[:, :2 * H], trans_b=True)
    dgates = jnp.concatenate([dgzr, dgc], axis=1)
    dwh = jnp.concatenate([
        matmul(hf, dgzr, trans_a=True),
        matmul((r * hf), dgc, trans_a=True),
    ], axis=1).astype(w_h.dtype)
    dxp = dgates.astype(xp_token.dtype)
    db = jnp.sum(dgates, axis=0).astype(bias_token.dtype)
    return dxp, dh.astype(h.dtype), dwh, db


_fused_gru_cell.defvjp(_fused_gru_fwd, _fused_gru_bwd)


# conservative per-kernel VMEM budget (bytes): w_h f32 + gates/acts/io all
# resident at once; real v5e VMEM is ~16MB, leave headroom for the compiler
_FUSED_VMEM_BUDGET = 10 * 1024 * 1024


def _fused_vmem_ok(w_h, batch: int, rows_per_item: int) -> bool:
    """Shared budget check: w_h (f32) + ``rows_per_item`` H-wide f32 rows
    per batch element resident at once. LSTM: 4H gates in+out, 5H acts,
    4H io = 17H; GRU: 3H xp + 3H zr/c stages + 3H acts + 2H h/out = 11H."""
    return (w_h.size + batch * rows_per_item * w_h.shape[0]) * 4 \
        <= _FUSED_VMEM_BUDGET


def _use_fused(batch: int, w_h, gate_act, cell_act, out_act) -> bool:
    return (FLAGS.use_pallas and w_h is not None
            and gate_act is jax.nn.sigmoid and cell_act is jnp.tanh
            and out_act is jnp.tanh
            and _lstm_tile(w_h.shape[0], batch) is not None)


def lstm_scan(x: jax.Array, mask: jax.Array, w_x: Optional[jax.Array],
              w_h: jax.Array, bias: Optional[jax.Array], *,
              reverse: bool = False, init: Optional[LSTMState] = None,
              gate_act=jax.nn.sigmoid, cell_act=jnp.tanh, out_act=jnp.tanh,
              interpret: Optional[bool] = None
              ) -> Tuple[jax.Array, LSTMState]:
    """Full-sequence LSTM: x [B,T,D], mask [B,T] -> (h_all [B,T,H], final).

    The input projection for ALL timesteps is one [B*T, D]x[D, 4H] gemm — the
    big-MXU-matmul formulation; the scan carries only the [H,4H] recurrence.
    ``w_x=None`` means x is already projected to [B,T,4H] (the reference's
    ``lstmemory`` contract: projection happens in the upstream mixed/fc layer).
    """
    B, T, _ = x.shape
    H = w_h.shape[0]
    xp = matmul(x, w_x) if w_x is not None else x  # [B, T, 4H]
    if init is None:
        init = LSTMState(jnp.zeros((B, H), xp.dtype), jnp.zeros((B, H), xp.dtype))

    fused = _use_fused(B, w_h, gate_act, cell_act, out_act)
    if interpret is None:
        from paddle_tpu.ops.kernel_util import interpret_default

        interpret = interpret_default()
    bias_arr = (bias if bias is not None
                else jnp.zeros((4 * H,), jnp.float32)) if fused else bias

    def step(state, inp):
        xt, mt = inp
        if fused:
            new_h, new_c = _fused_lstm_cell(xt, state.h,
                                            state.c.astype(jnp.float32),
                                            w_h, bias_arr, interpret)
            new_state = LSTMState(new_h, new_c.astype(state.c.dtype))
            h = new_h
        else:
            h, new_state = lstm_cell(xt, state, w_h, bias, gate_act,
                                     cell_act, out_act)
        m = mt[:, None].astype(h.dtype)
        new_state = LSTMState(m * new_state.h + (1 - m) * state.h,
                              m * new_state.c + (1 - m) * state.c)
        return new_state, new_state.h

    xs = (jnp.swapaxes(xp, 0, 1), jnp.swapaxes(mask, 0, 1))
    final, hs = jax.lax.scan(step, init, xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), final


def gru_scan(x: jax.Array, mask: jax.Array, w_x: Optional[jax.Array],
             w_h: jax.Array, bias: Optional[jax.Array], *,
             reverse: bool = False, init: Optional[jax.Array] = None,
             interpret: Optional[bool] = None) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence GRU: x [B,T,D] -> (h_all [B,T,H], final_h).
    ``w_x=None`` means x is already [B,T,3H] (grumemory contract)."""
    B, T, _ = x.shape
    H = w_h.shape[0]
    xp = matmul(x, w_x) if w_x is not None else x  # [B, T, 3H]
    h0 = init if init is not None else jnp.zeros((B, H), xp.dtype)

    fused = FLAGS.use_pallas and _gru_fused_plan(H, B, w_h) is not None
    if interpret is None:
        from paddle_tpu.ops.kernel_util import interpret_default

        interpret = interpret_default()
    bias_arr = (bias if bias is not None
                else jnp.zeros((3 * H,), jnp.float32)) if fused else bias

    def step(h, inp):
        xt, mt = inp
        if fused:
            new_h = _fused_gru_cell(xt, h, w_h, bias_arr, interpret)
        else:
            new_h = gru_cell(xt, h, w_h, bias)
        m = mt[:, None].astype(new_h.dtype)
        new_h = m * new_h + (1 - m) * h
        return new_h, new_h

    xs = (jnp.swapaxes(xp, 0, 1), jnp.swapaxes(mask, 0, 1))
    final, hs = jax.lax.scan(step, h0, xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), final
