"""Functional kernel library — the paddle/math + paddle/function + paddle/cuda analog.

Everything here is a pure jax function designed to fuse under jit and tile onto
the MXU: matmuls/convs run in bfloat16 with float32 accumulation when
FLAGS.use_bf16 (the TPU-native replacement for the reference's float32 cuBLAS
path), elementwise ops are left to XLA fusion, and segment/sequence ops use the
segment-ids formulation from paddle_tpu.sequence.
"""

from paddle_tpu.ops import math as pmath
from paddle_tpu.ops import conv as pconv
from paddle_tpu.ops import pool as ppool
from paddle_tpu.ops import norm as pnorm
from paddle_tpu.ops import losses
from paddle_tpu.ops import sequence_ops
from paddle_tpu.ops import rnn
from paddle_tpu.ops.math import matmul, fc
