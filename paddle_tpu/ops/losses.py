"""Loss kernels — the cost-layer family.

Reference: paddle/gserver/layers/CostLayer.cpp (MultiClassCrossEntropy,
SoftBinaryClassCrossEntropy, SumOfSquaresCostLayer, RankingCost,
LambdaCost, MultiBinaryLabelCrossEntropy, HuberRegressionLoss,
HuberTwoClassification), CrossEntropyOverBeam, and Gen-2 operators
(softmax_with_cross_entropy, sigmoid_cross_entropy_with_logits, rank_loss,
margin_rank_loss, smooth_l1, squared_l2_distance).

All losses return per-example values [N]; trainers reduce with masks so
variable-length batches weight correctly.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Integer labels; fused log-softmax (reference: classification_cost).

    Always reduces in f32: with bf16 activation storage
    (FLAGS.bf16_dense_activations) a bf16 logsumexp over a 32k vocab loses
    the loss signal's low bits."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    return logz - picked


def soft_cross_entropy(probs_or_logits: jax.Array, soft_labels: jax.Array,
                       *, from_logits: bool = True) -> jax.Array:
    if from_logits:
        logp = jax.nn.log_softmax(probs_or_logits, axis=-1)
    else:
        logp = jnp.log(jnp.clip(probs_or_logits, 1e-10, 1.0))
    return -jnp.sum(soft_labels * logp, axis=-1)


def sigmoid_cross_entropy_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Elementwise then summed over the last dim (reference:
    operators/sigmoid_cross_entropy_with_logits_op.cc)."""
    zeros = jnp.zeros_like(logits)
    loss = jnp.maximum(logits, zeros) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(loss, axis=-1)


def multi_binary_label_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Reference: MultiBinaryLabelCrossEntropy (CostLayer.cpp)."""
    return sigmoid_cross_entropy_with_logits(logits, labels)


def square_error(pred: jax.Array, target: jax.Array) -> jax.Array:
    """Sum-of-squares cost, 0.5*||p-t||^2 (reference: SumOfSquaresCostLayer)."""
    d = pred - target
    return 0.5 * jnp.sum(jnp.square(d), axis=tuple(range(1, d.ndim)))


def squared_l2_distance(a: jax.Array, b: jax.Array) -> jax.Array:
    d = a - b
    return jnp.sum(jnp.square(d), axis=-1)


def huber_regression(pred: jax.Array, target: jax.Array, delta: float = 1.0) -> jax.Array:
    """Reference: HuberRegressionLoss (CostLayer.cpp)."""
    d = jnp.abs(pred - target)
    quad = 0.5 * jnp.square(d)
    lin = delta * (d - 0.5 * delta)
    return jnp.sum(jnp.where(d <= delta, quad, lin), axis=-1)


def huber_classification(pred: jax.Array, label01: jax.Array) -> jax.Array:
    """Two-class huber on y∈{-1,1} (reference: HuberTwoClassification)."""
    y = 2.0 * label01.astype(pred.dtype) - 1.0
    z = y * pred[..., 0] if pred.ndim > label01.ndim else y * pred
    loss = jnp.where(z < -1.0, -4.0 * z, jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    return loss


def smooth_l1(pred: jax.Array, target: jax.Array, sigma: float = 1.0) -> jax.Array:
    """Reference: operators/smooth_l1_loss_op.cc."""
    s2 = sigma * sigma
    d = jnp.abs(pred - target)
    loss = jnp.where(d < 1.0 / s2, 0.5 * s2 * jnp.square(d), d - 0.5 / s2)
    return jnp.sum(loss, axis=tuple(range(1, loss.ndim)))


def rank_cost(left: jax.Array, right: jax.Array, label: jax.Array,
              weight: Optional[jax.Array] = None) -> jax.Array:
    """Pairwise ranking cost (reference: RankingCost, CostLayer.cpp):
    C = log(1 + e^{o}) - t*o with o = left - right, t in [0,1]."""
    o = (left - right).reshape(left.shape[0])
    t = label.reshape(label.shape[0]).astype(o.dtype)
    c = jnp.log1p(jnp.exp(-jnp.abs(o))) + jnp.maximum(o, 0.0) - t * o
    if weight is not None:
        c = c * weight.reshape(weight.shape[0])
    return c


def margin_rank_loss(left: jax.Array, right: jax.Array, label: jax.Array,
                     margin: float = 0.0) -> jax.Array:
    """Reference: operators/margin_rank_loss_op.cc: max(0, -l*(x1-x2)+margin)."""
    y = label.reshape(label.shape[0]).astype(left.dtype)
    o = (left - right).reshape(left.shape[0])
    return jnp.maximum(0.0, -y * o + margin)


def cosine_similarity(a: jax.Array, b: jax.Array, scale: float = 1.0,
                      eps: float = 1e-8) -> jax.Array:
    """Reference: CosSimLayer / function/CosSimOp.cpp."""
    num = jnp.sum(a * b, axis=-1)
    den = jnp.sqrt(jnp.sum(a * a, -1) * jnp.sum(b * b, -1) + eps)
    return scale * num / den


def classification_error(logits_or_probs: jax.Array, labels: jax.Array,
                         top_k: int = 1) -> jax.Array:
    """0/1 error per example (reference: ClassificationErrorLayer /
    classification_error_evaluator)."""
    if top_k == 1:
        pred = jnp.argmax(logits_or_probs, axis=-1)
        return (pred != labels.astype(pred.dtype)).astype(jnp.float32)
    _, idx = jax.lax.top_k(logits_or_probs, top_k)
    hit = jnp.any(idx == labels[..., None].astype(idx.dtype), axis=-1)
    return (~hit).astype(jnp.float32)


def cross_entropy_with_selfnorm(logits: jax.Array, labels: jax.Array,
                                alpha: float = 0.1) -> jax.Array:
    """Reference: CrossEntropyWithSelfNorm (CostLayer.cpp): xent + alpha*logZ^2."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    return (logz - picked) + alpha * jnp.square(logz)


def cross_entropy_over_beam(beams) -> jax.Array:
    """Globally-normalized beam cost for learning-to-search training.

    Reference: paddle/gserver/layers/CrossEntropyOverBeam.cpp:131-162
    (CostForOneSequence::globallyNormalizedScore): each candidate PATH's
    score is the sum of its per-expansion scores, the paths at the
    decisive expansion are softmax-normalized, and the cost is
    -log P(gold path). If gold falls off the beam at expansion t, the
    cost is computed over the beam AT step t; the gold path joins the
    normalizer as an extra path. Gradient flows to EVERY expansion on a
    surviving path (the reference backward()'s addToRows over all
    expansions).

    TPU-native formulation: per expansion the inputs are dense
    (scores[B, N_t], selected[B, K_t], gold[B][, parents[B, K_t]]).
    ``parents`` links candidate k at expansion t to the beam slot at
    t-1 it extends; path scores accumulate along those links. Without
    parents, every candidate extends the gold prefix — the shared
    prefix then cancels in the softmax (and correctly receives zero
    gradient, since d(-log softmax(c+x))/dc = 0). Branch-free: the
    decisive step is selected by index, not control flow.

    Returns per-sequence costs [B].
    """
    neg = -1e9
    kmax = max(int(b[1].shape[1]) for b in beams)
    batch = beams[0][0].shape[0]

    gold_in = []        # [B] per t: gold (with gold ancestry) in beam
    logits_t = []       # [B, Kmax+1] per t: [path scores, gold path]
    path = None         # [B, Kmax] accumulated candidate-path scores
    gold_prefix = jnp.zeros((batch,), beams[0][0].dtype)
    gold_slot_prev = None  # [B] beam slot holding the gold path at t-1

    for b in beams:
        scores, selected, gold = b[0], b[1].astype(jnp.int32), \
            b[2].astype(jnp.int32)
        parents = b[3].astype(jnp.int32) if len(b) > 3 else None
        k = selected.shape[1]
        beam_scores = jnp.take_along_axis(scores, selected, axis=1)
        if path is None or parents is None:
            # first expansion, or unlinked: extend the gold prefix
            path_t = gold_prefix[:, None] + beam_scores
        else:
            path_t = jnp.take_along_axis(path, parents, axis=1) + beam_scores
        gold_score = jnp.take_along_axis(scores, gold[:, None], axis=1)[:, 0]
        gold_prefix = gold_prefix + gold_score
        # the gold PATH sits in the beam only where the candidate id is
        # gold AND (when linked) its ancestry is the gold path's slot
        dup = selected == gold[:, None]
        if parents is not None and gold_slot_prev is not None:
            dup = dup & (parents == gold_slot_prev[:, None])
        gold_slot_prev = jnp.argmax(dup, axis=1)
        gold_in.append(jnp.any(dup, axis=1))
        # mask gold's in-beam copy: it is re-appended as the explicit
        # gold path so it is counted exactly once in the normalizer
        masked = jnp.where(dup, neg, path_t)
        if k < kmax:
            masked = jnp.concatenate(
                [masked, jnp.full((batch, kmax - k), neg, masked.dtype)],
                axis=1)
            path_t = jnp.concatenate(
                [path_t, jnp.full((batch, kmax - k), neg, path_t.dtype)],
                axis=1)
        path = path_t
        logits_t.append(jnp.concatenate([masked, gold_prefix[:, None]],
                                        axis=1))

    gold_in = jnp.stack(gold_in, axis=1)              # [B, T]
    logits = jnp.stack(logits_t, axis=1)              # [B, T, K+1]
    t_count = gold_in.shape[1]
    # decisive expansion: first fall-off, else the last expansion
    fell = jnp.any(~gold_in, axis=1)
    first_off = jnp.argmax(~gold_in, axis=1)
    f = jnp.where(fell, first_off, t_count - 1)       # [B]
    picked = jnp.take_along_axis(
        logits, f[:, None, None], axis=1)[:, 0]       # [B, K+1]
    # gold path is always the LAST logit
    return softmax_cross_entropy(
        picked, jnp.full(picked.shape[:1], picked.shape[1] - 1, jnp.int32))


# ---------------------------------------------------------------------------
# blockwise LM-head cross entropy — flash-style: the [N, V] logits matrix
# never exists in HBM
# ---------------------------------------------------------------------------


def _compute_dtype(x):
    from paddle_tpu.ops.math import compute_dtype  # deferred: avoids a cycle
    return compute_dtype(x)


_PAD_NEG = -1e30   # finite -inf: exp underflows to 0, no NaNs


def _lm_blocks(w, block_v):
    """Resolve (block_v, vocab, n_blocks) with ceil-div blocking: any vocab
    works at full block width — the last block is PADDED (zero weight
    columns, -1e30 bias) rather than shrinking block_v toward 1, which for
    an odd vocab (e.g. 50257) would silently degrade the scan to [N, 1]
    matmuls."""
    v = w.shape[1]
    if block_v <= 0 or block_v > v:
        block_v = v
    nb = -(-v // block_v)
    return block_v, v, nb


def _padded_wb(w, b, bv, nb):
    """Pad w/b out to nb*bv columns: padded logits come out ~-1e30, so
    exp() underflows to exactly 0 in fwd softmax stats and bwd probs."""
    v = w.shape[1]
    pad = nb * bv - v
    if pad == 0:
        return w, b
    wp = jnp.concatenate([w, jnp.zeros((w.shape[0], pad), w.dtype)], axis=1)
    bp = jnp.concatenate([b, jnp.full((pad,), _PAD_NEG, b.dtype)])
    return wp, bp


def lm_head_xent(x, w, b, labels, block_v: int = 4096):
    """loss[i] = logsumexp(x_i @ W + b) - (x_i @ W + b)[labels_i].

    The LM-head fc + softmax_cross_entropy fusion, computed in vocab
    blocks with an online logsumexp (the flash-attention trick applied to
    the classifier): per block only [N, block_v] activations exist, so
    the [N, V] logits (0.5-1 GB at bench shapes) never hit HBM in either
    pass — the backward recomputes each block's softmax from the saved
    logz. Matmuls ride the bf16/f32-accum policy (ops/math.py).

    x: [N, D] tokens; w: [D, V]; b: [V] or None; labels: [N] int.
    Returns per-token loss [N] in f32.
    """
    return _lm_head_xent(x, w, b if b is not None else jnp.zeros(
        (w.shape[1],), jnp.float32), labels.astype(jnp.int32), int(block_v))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _lm_head_xent(x, w, b, labels, block_v):
    loss, _ = _lm_head_fwd_impl(x, w, b, labels, block_v)
    return loss


def _block_logits(x, w, b, j, bv):
    d = w.shape[0]
    wj = jax.lax.dynamic_slice(w, (0, j * bv), (d, bv))
    bj = jax.lax.dynamic_slice(b, (j * bv,), (bv,))
    ct = _compute_dtype(x)
    lg = jnp.matmul(x.astype(ct), wj.astype(ct),
                    preferred_element_type=jnp.float32)
    return lg + bj.astype(jnp.float32)


def _lm_head_fwd_impl(x, w, b, labels, block_v):
    bv, v, nb = _lm_blocks(w, block_v)
    w, b = _padded_wb(w, b, bv, nb)
    n = x.shape[0]
    neg = jnp.float32(-jnp.inf)

    def body(carry, j):
        m, s, picked = carry
        lg = _block_logits(x, w, b, j, bv)               # [N, bv] f32
        bm = jnp.max(lg, axis=-1)
        new_m = jnp.maximum(m, bm)
        s = s * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(lg - new_m[:, None]), axis=-1)
        in_blk = (labels >= j * bv) & (labels < (j + 1) * bv)
        idx = jnp.clip(labels - j * bv, 0, bv - 1)
        pick_j = jnp.take_along_axis(lg, idx[:, None], axis=-1)[:, 0]
        picked = jnp.where(in_blk, pick_j, picked)
        return (new_m, s, picked), None

    init = (jnp.full((n,), neg), jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, picked), _ = jax.lax.scan(body, init,
                                     jnp.arange(nb, dtype=jnp.int32))
    logz = m + jnp.log(s)
    return logz - picked, logz


def _lm_head_xent_fwd(x, w, b, labels, block_v):
    loss, logz = _lm_head_fwd_impl(x, w, b, labels, block_v)
    return loss, (x, w, b, labels, logz)


def _lm_head_xent_bwd(block_v, res, g):
    x, w, b, labels, logz = res
    bv, v, nb = _lm_blocks(w, block_v)
    w, b = _padded_wb(w, b, bv, nb)
    d = w.shape[0]
    gf = g.astype(jnp.float32)

    def body(carry, j):
        dx, dw, db = carry
        lg = _block_logits(x, w, b, j, bv)
        p = jnp.exp(lg - logz[:, None])                  # softmax block
        in_blk = (labels >= j * bv) & (labels < (j + 1) * bv)
        idx = jnp.clip(labels - j * bv, 0, bv - 1)
        onehot = (jnp.arange(bv)[None, :] == idx[:, None]) & in_blk[:, None]
        dlg = (p - onehot.astype(jnp.float32)) * gf[:, None]  # [N, bv]
        wj = jax.lax.dynamic_slice(w, (0, j * bv), (d, bv))
        ct = _compute_dtype(x)
        dx = dx + jnp.matmul(dlg.astype(ct), wj.astype(ct).T,
                             preferred_element_type=jnp.float32)
        dwj = jnp.matmul(x.astype(ct).T, dlg.astype(ct),
                         preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice(
            dw, dwj.astype(dw.dtype), (0, j * bv))
        db = jax.lax.dynamic_update_slice(
            db, jnp.sum(dlg, axis=0).astype(db.dtype), (j * bv,))
        return (dx, dw, db), None

    init = (jnp.zeros(x.shape, jnp.float32), jnp.zeros_like(w),
            jnp.zeros_like(b))
    (dx, dw, db), _ = jax.lax.scan(body, init,
                                   jnp.arange(nb, dtype=jnp.int32))
    # drop the pad columns (grads there are exactly 0 by construction)
    return dx.astype(x.dtype), dw[:, :v], db[:v], None


_lm_head_xent.defvjp(_lm_head_xent_fwd, _lm_head_xent_bwd)
