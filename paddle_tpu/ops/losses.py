"""Loss kernels — the cost-layer family.

Reference: paddle/gserver/layers/CostLayer.cpp (MultiClassCrossEntropy,
SoftBinaryClassCrossEntropy, SumOfSquaresCostLayer, RankingCost,
LambdaCost, MultiBinaryLabelCrossEntropy, HuberRegressionLoss,
HuberTwoClassification), CrossEntropyOverBeam, and Gen-2 operators
(softmax_with_cross_entropy, sigmoid_cross_entropy_with_logits, rank_loss,
margin_rank_loss, smooth_l1, squared_l2_distance).

All losses return per-example values [N]; trainers reduce with masks so
variable-length batches weight correctly.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Integer labels; fused log-softmax (reference: classification_cost)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    return logz - picked


def soft_cross_entropy(probs_or_logits: jax.Array, soft_labels: jax.Array,
                       *, from_logits: bool = True) -> jax.Array:
    if from_logits:
        logp = jax.nn.log_softmax(probs_or_logits, axis=-1)
    else:
        logp = jnp.log(jnp.clip(probs_or_logits, 1e-10, 1.0))
    return -jnp.sum(soft_labels * logp, axis=-1)


def sigmoid_cross_entropy_with_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Elementwise then summed over the last dim (reference:
    operators/sigmoid_cross_entropy_with_logits_op.cc)."""
    zeros = jnp.zeros_like(logits)
    loss = jnp.maximum(logits, zeros) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return jnp.sum(loss, axis=-1)


def multi_binary_label_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Reference: MultiBinaryLabelCrossEntropy (CostLayer.cpp)."""
    return sigmoid_cross_entropy_with_logits(logits, labels)


def square_error(pred: jax.Array, target: jax.Array) -> jax.Array:
    """Sum-of-squares cost, 0.5*||p-t||^2 (reference: SumOfSquaresCostLayer)."""
    d = pred - target
    return 0.5 * jnp.sum(jnp.square(d), axis=tuple(range(1, d.ndim)))


def squared_l2_distance(a: jax.Array, b: jax.Array) -> jax.Array:
    d = a - b
    return jnp.sum(jnp.square(d), axis=-1)


def huber_regression(pred: jax.Array, target: jax.Array, delta: float = 1.0) -> jax.Array:
    """Reference: HuberRegressionLoss (CostLayer.cpp)."""
    d = jnp.abs(pred - target)
    quad = 0.5 * jnp.square(d)
    lin = delta * (d - 0.5 * delta)
    return jnp.sum(jnp.where(d <= delta, quad, lin), axis=-1)


def huber_classification(pred: jax.Array, label01: jax.Array) -> jax.Array:
    """Two-class huber on y∈{-1,1} (reference: HuberTwoClassification)."""
    y = 2.0 * label01.astype(pred.dtype) - 1.0
    z = y * pred[..., 0] if pred.ndim > label01.ndim else y * pred
    loss = jnp.where(z < -1.0, -4.0 * z, jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    return loss


def smooth_l1(pred: jax.Array, target: jax.Array, sigma: float = 1.0) -> jax.Array:
    """Reference: operators/smooth_l1_loss_op.cc."""
    s2 = sigma * sigma
    d = jnp.abs(pred - target)
    loss = jnp.where(d < 1.0 / s2, 0.5 * s2 * jnp.square(d), d - 0.5 / s2)
    return jnp.sum(loss, axis=tuple(range(1, loss.ndim)))


def rank_cost(left: jax.Array, right: jax.Array, label: jax.Array,
              weight: Optional[jax.Array] = None) -> jax.Array:
    """Pairwise ranking cost (reference: RankingCost, CostLayer.cpp):
    C = log(1 + e^{o}) - t*o with o = left - right, t in [0,1]."""
    o = (left - right).reshape(left.shape[0])
    t = label.reshape(label.shape[0]).astype(o.dtype)
    c = jnp.log1p(jnp.exp(-jnp.abs(o))) + jnp.maximum(o, 0.0) - t * o
    if weight is not None:
        c = c * weight.reshape(weight.shape[0])
    return c


def margin_rank_loss(left: jax.Array, right: jax.Array, label: jax.Array,
                     margin: float = 0.0) -> jax.Array:
    """Reference: operators/margin_rank_loss_op.cc: max(0, -l*(x1-x2)+margin)."""
    y = label.reshape(label.shape[0]).astype(left.dtype)
    o = (left - right).reshape(left.shape[0])
    return jnp.maximum(0.0, -y * o + margin)


def cosine_similarity(a: jax.Array, b: jax.Array, scale: float = 1.0,
                      eps: float = 1e-8) -> jax.Array:
    """Reference: CosSimLayer / function/CosSimOp.cpp."""
    num = jnp.sum(a * b, axis=-1)
    den = jnp.sqrt(jnp.sum(a * a, -1) * jnp.sum(b * b, -1) + eps)
    return scale * num / den


def classification_error(logits_or_probs: jax.Array, labels: jax.Array,
                         top_k: int = 1) -> jax.Array:
    """0/1 error per example (reference: ClassificationErrorLayer /
    classification_error_evaluator)."""
    if top_k == 1:
        pred = jnp.argmax(logits_or_probs, axis=-1)
        return (pred != labels.astype(pred.dtype)).astype(jnp.float32)
    _, idx = jax.lax.top_k(logits_or_probs, top_k)
    hit = jnp.any(idx == labels[..., None].astype(idx.dtype), axis=-1)
    return (~hit).astype(jnp.float32)


def cross_entropy_with_selfnorm(logits: jax.Array, labels: jax.Array,
                                alpha: float = 0.1) -> jax.Array:
    """Reference: CrossEntropyWithSelfNorm (CostLayer.cpp): xent + alpha*logZ^2."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    return (logz - picked) + alpha * jnp.square(logz)
