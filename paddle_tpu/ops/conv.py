"""Convolution kernels — the ExpandConvLayer/CudnnConvLayer/hl_cnn analog.

Reference: paddle/gserver/layers/ExpandConvLayer.cpp (im2col+gemm),
CudnnConvBaseLayer.cpp, paddle/function/GemmConvOp.cpp, DepthwiseConvOp.cpp,
Conv3D; Gen-2 paddle/operators/conv_op.cc / conv_transpose.

TPU-native: ``lax.conv_general_dilated`` in NHWC/HWIO layout (the layout XLA
tiles best onto the MXU) with bf16 inputs + f32 accumulation. No im2col — XLA
lowers convs directly to MXU matmuls.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.platform.flags import FLAGS

IntOr2 = Union[int, Tuple[int, int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_dtype(x):
    if FLAGS.use_bf16 and x.dtype in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return jnp.dtype(jnp.bfloat16)
    return x.dtype


def activation_dtype() -> jnp.dtype:
    """Storage dtype for inter-layer image activations.

    bf16 activations halve HBM traffic between conv blocks — on TPU the
    usual ResNet bottleneck is bandwidth, not MXU FLOPs. Batch-norm stats,
    losses, and all parameters stay f32 (see ops/norm.py batch_norm).
    """
    if FLAGS.use_bf16 and FLAGS.bf16_activations:
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(jnp.float32)


def conv2d(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
           padding: Union[str, IntOr2] = 0, dilation: IntOr2 = 1,
           groups: int = 1, out_dtype=None) -> jax.Array:
    """x: [N,H,W,C], w: [kh,kw,Cin/groups,Cout] -> [N,H',W',Cout]."""
    s = _pair(stride)
    d = _pair(dilation)
    if isinstance(padding, str):
        pad = padding
    else:
        ph, pw = _pair(padding)
        pad = ((ph, ph), (pw, pw))
    ct = _conv_dtype(x)
    # NOTE: output dtype == input dtype keeps the VJP's transposed conv
    # dtype-consistent (bf16 cotangents); the MXU still accumulates bf16
    # products in f32 internally. Upcast after.
    y = lax.conv_general_dilated(
        x.astype(ct), w.astype(ct), window_strides=s, padding=pad,
        rhs_dilation=d, feature_group_count=groups,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y.astype(jnp.dtype(out_dtype) if out_dtype is not None
                    else activation_dtype())


def conv2d_transpose(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
                     padding: IntOr2 = 0, out_dtype=None) -> jax.Array:
    """Transposed conv (reference: ConvTransLayer / conv2dtranspose op)."""
    s = _pair(stride)
    ph, pw = _pair(padding)
    kh, kw = w.shape[0], w.shape[1]
    ct = _conv_dtype(x)
    # w layout: [kh, kw, Cin, Cout] with Cin = x's channels. lhs_dilation
    # implements the fractional stride; padding converts to the equivalent
    # forward-conv padding: k - 1 - p on each side.
    y = lax.conv_general_dilated(
        x.astype(ct), jnp.flip(w, (0, 1)).astype(ct),
        window_strides=(1, 1),
        padding=((kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)),
        lhs_dilation=s, dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y.astype(jnp.dtype(out_dtype) if out_dtype is not None
                    else activation_dtype())


def depthwise_conv2d(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
                     padding: Union[str, IntOr2] = 0) -> jax.Array:
    """Depthwise conv (reference: paddle/function/DepthwiseConvOp.cpp).

    w: [kh, kw, C, channel_multiplier] — grouped conv with groups=C.
    """
    c = x.shape[-1]
    kh, kw, _, m = w.shape
    return conv2d(x, w.reshape(kh, kw, 1, c * m), stride=stride,
                  padding=padding, groups=c)


def conv3d(x: jax.Array, w: jax.Array, *, stride=1, padding=0) -> jax.Array:
    """3-D conv, NDHWC/DHWIO (reference: gserver/layers/Conv3DLayer.cpp)."""
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, str):
        pad = padding
    else:
        p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        pad = tuple((pi, pi) for pi in p)
    ct = _conv_dtype(x)
    y = lax.conv_general_dilated(
        x.astype(ct), w.astype(ct), window_strides=s, padding=pad,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return y.astype(activation_dtype())


def row_conv(x: jax.Array, w: jax.Array) -> jax.Array:
    """Row (lookahead) convolution over time (reference: function/RowConvOp.cpp).

    x: [B, T, D], w: [future_context, D]. y[t] = sum_k x[t+k] * w[k].
    """
    k = w.shape[0]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    stacked = jnp.stack([xp[:, i:i + x.shape[1]] for i in range(k)], axis=0)
    return jnp.einsum("kbtd,kd->btd", stacked, w)


def block_expand(x: jax.Array, block: Tuple[int, int], stride: Tuple[int, int],
                 padding: Tuple[int, int] = (0, 0)) -> jax.Array:
    """im2col-as-a-layer (reference: BlockExpandLayer / function/BlockExpandOp).

    x: [N,H,W,C] -> [N, num_blocks_h*num_blocks_w, bh*bw*C]
    """
    bh, bw = block
    sh, sw = stride
    ph, pw = padding
    xp = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    patches = lax.conv_general_dilated_patches(
        xp, filter_shape=(bh, bw), window_strides=(sh, sw), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    n, oh, ow, f = patches.shape
    return patches.reshape(n, oh * ow, f)
