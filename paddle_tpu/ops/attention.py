"""Blockwise (flash) attention for TPU — pallas kernel + pure-JAX reference.

This is the TPU-native successor of the reference's attention machinery
(trainer_config_helpers/networks.py:1304 simple_attention, :1402
dot_product_attention) extended to the modern multi-head form the new
framework needs for long-context support.  Segment-id masking plays the role
of the reference's ragged-sequence representation
(Argument.sequenceStartPositions, paddle/parameter/Argument.h:84-90;
LoDTensor, paddle/framework/lod_tensor.h:57): sequences are packed
back-to-back in one buffer and attention never crosses a segment boundary,
so there is no padding waste.

Design notes (TPU-first):
  - forward is a pallas kernel: grid (batch, heads, q-blocks, k-blocks) with
    the key axis STREAMED through the grid — only one (block_q x D) and one
    (block_k x D) tile is ever resident in VMEM, with the online-softmax
    carry (m, l, acc) held in VMEM scratch across the key axis.  VMEM use is
    O(block^2) at ANY sequence length (the previous design kept full-seq K/V
    resident per grid cell and hit the 16 MB scoped-vmem wall at 8192 packed
    tokens).  Pallas double-buffers the streamed tiles, so the K/V DMA for
    block j+1 overlaps the block-j matmuls; matmuls hit the MXU with
    block_q x head_dim x block_k shapes and fp32 accumulation.
  - backward is TWO pallas kernels (dK/dV with the QUERY axis streamed
    through the grid, dQ with the KEY axis streamed), each recomputing P
    blockwise from (q, k, lse) — the S x S score matrix never exists in
    either direction, and neither kernel holds a full sequence in VMEM.
    FLAGS.use_pallas=False falls back to a blockwise lax.scan in plain JAX
    with identical semantics.
  - causal masking skips fully-masked blocks with pl.when AND clamps the
    streamed-tile index maps, so the revisiting optimisation elides the DMA
    for blocks that would be skipped (~half the grid for causal).
  - on CPU (tests / 8-device virtual mesh) the kernels run in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


from paddle_tpu.ops.kernel_util import interpret_default as _interpret_default


# ---------------------------------------------------------------------------
# Reference implementation (test oracle; also used for tiny shapes)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, segment_ids=None, kv_segment_ids=None,
                  causal: bool = False, sm_scale: Optional[float] = None):
    """Plain-JAX multi-head attention.

    q: (B, Sq, H, D); k, v: (B, Sk, H, D); segment_ids: (B, Sq) int32,
    kv_segment_ids: (B, Sk).  Returns (B, Sq, H, D).

    GQA: k/v may carry FEWER heads than q (H_kv dividing H) — each
    group of H // H_kv query heads then attends over one shared KV head
    (query head h reads KV head h // group).  The heads are replicated
    here, so this stays the oracle for the serving kernel's head-group
    packing.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    mask = None
    if segment_ids is not None:
        kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
        mask = (segment_ids[:, None, :, None] == kv_seg[:, None, None, :])
    if causal:
        cm = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))[None, None]
        mask = cm if mask is None else (mask & cm)
    if mask is not None:
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

_LANES = 128  # lane width for the (block_q, _LANES) m/l scratch carries


def _pv_operands(probs, other, pv_f32: bool):
    """Operand dtypes for the P/dS-side matmuls (PV, dV, dK, dQ).

    Default: cast the f32 probs/dS down to the tiles' native dtype so the
    MXU runs its fast path. ``pv_f32`` (FLAGS.attn_pv_f32): upcast the
    other operand instead — no softmax-prob rounding, slower f32 MXU."""
    if pv_f32:
        return probs, other.astype(jnp.float32)
    return probs.astype(other.dtype), other


def _seg_live(qseg_ref, kseg_ref, b):
    """Runtime block-skip predicate: packed sequences give each (q, k) block
    an id range; disjoint ranges mean no q_seg == k_seg pair exists, so the
    whole block is dead.  Conservative (overlapping ranges without an equal
    pair still compute), hence correct for ANY id assignment.  Forward and
    both backward kernels MUST use this same predicate so lse is never
    consumed by a pair the forward skipped."""
    q_sg = qseg_ref[b, :]
    k_sg = kseg_ref[b, :]
    return ((jnp.max(q_sg) >= jnp.min(k_sg)) &
            (jnp.min(q_sg) <= jnp.max(k_sg)))


def _clamped_kv_maps(causal, block_q, block_k):
    """Index maps for the streamed key-axis tiles on a (b, h, i, j) grid.
    Under causal masking, clamp j to the last live key block for q block i
    (`j*block_k < (i+1)*block_q` — the same bound the kernels' live
    predicate uses), so skipped blocks repeat the previous index and the
    revisiting optimisation elides their DMA entirely."""
    if causal:
        def kv_idx(b, h, i, j):
            return (b, h, jnp.minimum(j, ((i + 1) * block_q - 1) // block_k),
                    0)

        def kseg_idx(b, h, i, j):
            return (0, jnp.minimum(j, ((i + 1) * block_q - 1) // block_k))
    else:
        def kv_idx(b, h, i, j):
            return (b, h, j, 0)

        def kseg_idx(b, h, i, j):
            return (0, j)
    return kv_idx, kseg_idx


def _flash_fwd_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref,
                      lse_ref, m_scr, l_scr, acc_scr, *, sm_scale: float,
                      causal: bool, num_kb: int, pv_f32: bool):
    # q_ref: (1, 1, block_q, D); k_ref/v_ref: (1, 1, block_k, D) — the key
    # axis is the LAST grid dim, streamed; carries (m, l, acc) persist in
    # VMEM scratch across it.  qseg_ref: (B, block_q); kseg_ref: (B, block_k)
    # — full batch dim because TPU block shapes must tile (8, 128) or span
    # the whole array dim.
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    b = pl.program_id(0)
    qi = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: key blocks strictly after this q block are fully masked;
    # _seg_live skips cross-segment blocks at runtime
    seg_live = _seg_live(qseg_ref, kseg_ref, b)
    live = seg_live & (j * block_k < (qi + 1) * block_q) if causal \
        else seg_live

    @pl.when(live)
    def _compute():
        # MXU inputs stay in the tiles' native dtype (bf16 under the
        # global compute policy; f32 in f32 models/tests) with f32
        # accumulation — an .astype(f32) before the dot would force the
        # ~4x-slower f32 MXU path. sm_scale is applied to the f32 product
        # (same math as pre-scaling q, better bf16 precision).
        q = q_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        q_seg = qseg_ref[b, :].reshape(block_q, 1)
        k_seg = kseg_ref[b, :].reshape(1, block_k)
        mask = (q_seg == k_seg)
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = mask & (q_ids >= k_ids)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        # m/l ride as (block_q, _LANES) lane-replicated values; a lane-max
        # recovers the scalar column
        m_prev = jnp.max(m_scr[...], axis=1, keepdims=True)
        l_prev = jnp.max(l_scr[...], axis=1, keepdims=True)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        # FLAGS.attn_pv_f32: keep the PV operands in f32 (no softmax-prob
        # rounding) for accuracy-sensitive runs; default rides the fast
        # native-dtype MXU path
        pb, vmm = _pv_operands(p, vb, pv_f32)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pb, vmm, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_kb - 1)
    def _finalize():
        m = jnp.max(m_scr[...], axis=1, keepdims=True)
        l = jnp.max(l_scr[...], axis=1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros, not NaN
        o_ref[0, 0, :, :] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m + jnp.log(l)


def _dim_semantics(grid_ndim: int, interpret: bool):
    """Grid (batch, heads, blocks, streamed): all parallel but the last —
    only the streamed axis carries scratch state, so megacore may split any
    earlier dim across cores."""
    if interpret:
        return None  # interpret mode ignores TPU compiler params
    sem = ("parallel",) * (grid_ndim - 1) + ("arbitrary",)
    return pltpu.CompilerParams(dimension_semantics=sem)


def _flash_fwd(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q, block_k,
               interpret, pv_f32=False):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    assert seq_q % block_q == 0 and seq_k % block_k == 0, (
        f"sequence lengths ({seq_q},{seq_k}) must divide by blocks "
        f"({block_q},{block_k}) — DataFeeder pads capacity to multiples")
    # (B, S, H, D) -> (B, H, S, D) for contiguous per-head blocks
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    num_kb = seq_k // block_k
    kv_idx, kseg_idx = _clamped_kv_maps(causal, block_q, block_k)
    grid = (batch, heads, seq_q // block_q, num_kb)
    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, num_kb=num_kb, pv_f32=pv_f32)
    out_t, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_idx),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_idx),
            pl.BlockSpec((batch, block_q), lambda b, h, i, j: (0, i)),
            pl.BlockSpec((batch, block_k), kseg_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, seq_q, head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        compiler_params=_dim_semantics(4, interpret),
        interpret=interpret,
    )(qt, kt, vt, q_seg, kv_seg)
    return out_t.transpose(0, 2, 1, 3), lse[..., 0]


# ---------------------------------------------------------------------------
# Backward: pallas kernels (dK/dV then dQ), mirroring the forward's
# blocking. Reference for what they replace: the reference's hand-fused
# CUDA attention-adjacent kernels (paddle/cuda/src/*.cu) — here the win is
# recomputing P blockwise from (q, k, lse) so the S x S matrix never
# exists, with fp32 accumulation on the MXU.
# ---------------------------------------------------------------------------


def _flash_bwd_kv_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref,
                         lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                         *, sm_scale: float, causal: bool, num_qb: int,
                         pv_f32: bool):
    # grid (B, H, k-blocks, q-blocks): the QUERY axis is streamed through
    # the last grid dim; dk/dv accumulate in VMEM scratch across it.
    # k_ref/v_ref: (1, 1, block_k, D); q/do: (1, 1, block_q, D);
    # lse/delta: (1, 1, block_q, 1); qseg: (B, block_q); kseg: (B, block_k)
    block_k = k_ref.shape[2]
    block_q = q_ref.shape[2]
    b = pl.program_id(0)
    kj = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # causal: q blocks whose last row precedes this k block are fully
    # masked; _seg_live skips cross-segment blocks at runtime
    seg_live = _seg_live(qseg_ref, kseg_ref, b)
    live = seg_live & ((i + 1) * block_q > kj * block_k) if causal \
        else seg_live

    @pl.when(live)
    def _compute():
        # native-dtype MXU operands, f32 accumulation (see forward kernel)
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        qb = q_ref[0, 0, :, :]
        dob = do_ref[0, 0, :, :]
        lseb = lse_ref[0, 0, :, :]
        deltab = delta_ref[0, 0, :, :]
        q_seg = qseg_ref[b, :].reshape(block_q, 1)
        k_seg = kseg_ref[b, :].reshape(1, block_k)
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        mask = q_seg == k_seg
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = mask & (q_ids >= k_ids)
        p = jnp.where(mask, jnp.exp(s - lseb), 0.0)
        pb, domm = _pv_operands(p, dob, pv_f32)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            pb, domm, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab) * sm_scale
        dsb, qmm = _pv_operands(ds, qb, pv_f32)
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            dsb, qmm, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == num_qb - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref,
                         lse_ref, delta_ref, dq_ref, dq_scr, *,
                         sm_scale: float, causal: bool, num_kb: int,
                         pv_f32: bool):
    # grid (B, H, q-blocks, k-blocks): the KEY axis is streamed through the
    # last grid dim; dq accumulates in VMEM scratch across it.
    block_q = q_ref.shape[2]
    block_k = k_ref.shape[2]
    b = pl.program_id(0)
    qi = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    seg_live = _seg_live(qseg_ref, kseg_ref, b)
    live = seg_live & (j * block_k < (qi + 1) * block_q) if causal \
        else seg_live

    @pl.when(live)
    def _compute():
        # native-dtype MXU operands, f32 accumulation (see forward kernel)
        qb = q_ref[0, 0, :, :]
        dob = do_ref[0, 0, :, :]
        lseb = lse_ref[0, 0, :, :]
        deltab = delta_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        q_seg = qseg_ref[b, :].reshape(block_q, 1)
        k_seg = kseg_ref[b, :].reshape(1, block_k)
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        mask = q_seg == k_seg
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = mask & (q_ids >= k_ids)
        p = jnp.where(mask, jnp.exp(s - lseb), 0.0)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab) * sm_scale
        dsb, kmm = _pv_operands(ds, kb, pv_f32)
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            dsb, kmm, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_kb - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(res, do, *, causal, sm_scale, block_q, block_k,
                      interpret, pv_f32=False):
    q, k, v, q_seg, kv_seg, out, lse = res
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    delta = jnp.sum(dot.astype(jnp.float32) *
                    out.transpose(0, 2, 1, 3).astype(jnp.float32),
                    axis=-1, keepdims=True)               # (B, H, Sq, 1)
    lse_t = lse[..., None]                                # (B, H, Sq, 1)

    # --- dK/dV: grid (B, H, k-blocks, q-blocks), query axis streamed ---
    if causal:
        # clamp the streamed q-tile index so fully-masked q blocks (strictly
        # before the k block) don't re-DMA; pl.when skips their compute.
        # The upper clamp to num_qb-1 covers causal cross-attention with
        # seq_k > seq_q, where (kj*block_k)//block_q can exceed the last
        # q block (the old code degraded to an out-of-range block index).
        def q_idx(b, h, kj, i):
            return (b, h, jnp.minimum(num_qb - 1,
                                      jnp.maximum(i, (kj * block_k) // block_q)),
                    0)

        def qseg_idx(b, h, kj, i):
            return (0, jnp.minimum(num_qb - 1,
                                   jnp.maximum(i, (kj * block_k) // block_q)))
    else:
        def q_idx(b, h, kj, i):
            return (b, h, i, 0)

        def qseg_idx(b, h, kj, i):
            return (0, i)

    dk_t, dv_t = pl.pallas_call(
        functools.partial(_flash_bwd_kv_kernel, sm_scale=sm_scale,
                          causal=causal, num_qb=num_qb, pv_f32=pv_f32),
        grid=(batch, heads, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), q_idx),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, kj, i: (b, h, kj, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, kj, i: (b, h, kj, 0)),
            pl.BlockSpec((batch, block_q), qseg_idx),
            pl.BlockSpec((batch, block_k), lambda b, h, kj, i: (0, kj)),
            pl.BlockSpec((1, 1, block_q, head_dim), q_idx),
            pl.BlockSpec((1, 1, block_q, 1), q_idx),
            pl.BlockSpec((1, 1, block_q, 1), q_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, kj, i: (b, h, kj, 0)),
            pl.BlockSpec((1, 1, block_k, head_dim),
                         lambda b, h, kj, i: (b, h, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, seq_k, head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_k, head_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=_dim_semantics(4, interpret),
        interpret=interpret,
    )(qt, kt, vt, q_seg, kv_seg, dot, lse_t, delta)

    # --- dQ: grid (B, H, q-blocks, k-blocks), key axis streamed ---
    kv_idx, kseg_idx = _clamped_kv_maps(causal, block_q, block_k)
    blk_q = pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i, j: (b, h, i, 0))
    blk_q1 = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dq_t = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, num_kb=num_kb, pv_f32=pv_f32),
        grid=(batch, heads, num_qb, num_kb),
        in_specs=[
            blk_q,
            pl.BlockSpec((1, 1, block_k, head_dim), kv_idx),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_idx),
            pl.BlockSpec((batch, block_q), lambda b, h, i, j: (0, i)),
            pl.BlockSpec((batch, block_k), kseg_idx),
            blk_q,
            blk_q1,
            blk_q1,
        ],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq_q, head_dim),
                                       q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_dim_semantics(4, interpret),
        interpret=interpret,
    )(qt, kt, vt, q_seg, kv_seg, dot, lse_t, delta)

    return (dq_t.transpose(0, 2, 1, 3), dk_t.transpose(0, 2, 1, 3),
            dv_t.transpose(0, 2, 1, 3), None, None)


# ---------------------------------------------------------------------------
# Backward: blockwise scan over key blocks (plain JAX fallback)
# ---------------------------------------------------------------------------

def _flash_bwd(res, do, *, causal, sm_scale, block_k):
    q, k, v, q_seg, kv_seg, out, lse = res
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_k = min(block_k, seq_k)
    nkb = seq_k // block_k

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,Sq,H)
    q_ids = jnp.arange(seq_q)
    k_ids_all = jnp.arange(seq_k).reshape(nkb, block_k)
    k_blocks = k.reshape(batch, nkb, block_k, heads, head_dim)
    v_blocks = v.reshape(batch, nkb, block_k, heads, head_dim)
    kseg_blocks = kv_seg.reshape(batch, nkb, block_k)

    def one_block(dq_acc, blk):
        kb, vb, ksegb, kids = blk  # kb: (B, block_k, H, D)
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, kb.astype(jnp.float32))
        s = s * sm_scale
        mask = (q_seg[:, :, None, None] == ksegb[:, None, None, :])
        if causal:
            mask = mask & (q_ids[None, :, None, None] >= kids[None, None, None, :])
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse.transpose(0, 2, 1)[:, :, :, None])  # (B,Sq,H,bk)
        p = jnp.where(mask, p, 0.0)
        dv = jnp.einsum("bqhk,bqhd->bkhd", p, dof)
        dp = jnp.einsum("bqhd,bkhd->bqhk", dof, vb.astype(jnp.float32))
        ds = p * (dp - delta[:, :, :, None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqhk,bkhd->bqhd", ds,
                                     kb.astype(jnp.float32))
        dk = jnp.einsum("bqhk,bqhd->bkhd", ds, qf)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((batch, seq_q, heads, head_dim), jnp.float32)
    dq, (dk_b, dv_b) = jax.lax.scan(
        one_block, dq0,
        (k_blocks.transpose(1, 0, 2, 3, 4), v_blocks.transpose(1, 0, 2, 3, 4),
         kseg_blocks.transpose(1, 0, 2), k_ids_all))
    dk = dk_b.transpose(1, 0, 2, 3, 4).reshape(batch, seq_k, heads, head_dim)
    dv = dv_b.transpose(1, 0, 2, 3, 4).reshape(batch, seq_k, heads, head_dim)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_attention(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q,
                     block_k, interpret, pv_f32):
    out, _ = _flash_fwd(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q,
                        block_k, interpret, pv_f32=pv_f32)
    return out


def _fwd_rule(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q, block_k,
              interpret, pv_f32):
    out, lse = _flash_fwd(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q,
                          block_k, interpret, pv_f32=pv_f32)
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _bwd_rule(causal, sm_scale, block_q, block_k, interpret, pv_f32, res, do):
    from paddle_tpu.platform.flags import FLAGS

    if FLAGS.use_pallas:
        return _flash_bwd_pallas(res, do, causal=causal, sm_scale=sm_scale,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret, pv_f32=pv_f32)
    return _flash_bwd(res, do, causal=causal, sm_scale=sm_scale,
                      block_k=block_k)


_flash_attention.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q, k, v, segment_ids=None, kv_segment_ids=None,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Blockwise multi-head attention (pallas forward, blockwise backward).

    Args:
      q: (B, Sq, H, D); k, v: (B, Sk, H, D).
      segment_ids: (B, Sq) int32 packed-sequence ids; tokens only attend
        within their own segment (use -1 for padding: give padding its own
        id).  None => full attention.
      kv_segment_ids: (B, Sk); defaults to segment_ids (self-attention).
      causal: lower-triangular masking (positions are absolute in the packed
        buffer — combine with segment ids for per-sequence causality).
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    # FLAGS.attn_block retunes the DEFAULT tile edge only — call sites that
    # chose their blocks explicitly (ring/ulysses shard-sized tiles, tests)
    # are never trampled.  The auto default picks the largest tile that
    # divides the sequence: streaming keeps VMEM at O(block^2), so big tiles
    # are free memory-wise and each grid cell amortizes its fixed cost over
    # 16x more MXU work than a 128 tile (measured: 128 tiles at seq 4096 =
    # 32k grid cells of ~760ns overhead each, dwarfing the matmuls).
    from paddle_tpu.platform.flags import FLAGS

    def _auto_block(seq):
        # the flag retunes the preferred edge but still falls through the
        # ladder when it doesn't divide this call's sequence (a global flag
        # must never crash an oddly-sized layer the auto path handles)
        preferred = (int(FLAGS.attn_block),) if FLAGS.attn_block else ()
        for edge in preferred + (512, 256, 128):
            if seq % edge == 0:
                return edge
        return 128  # small/ragged seqs: min() below clamps to seq

    batch, seq_q = q.shape[0], q.shape[1]
    seq_k = k.shape[1]
    # the kernels feed operands to the MXU in their native dtype (no f32
    # upcast), which requires uniform q/k/v dtypes — normalize mixed-dtype
    # calls (e.g. a bf16 query against an f32 KV cache) to q's dtype here
    if k.dtype != q.dtype:
        k = k.astype(q.dtype)
    if v.dtype != q.dtype:
        v = v.astype(q.dtype)
    if block_q is None:
        block_q = _auto_block(seq_q)
    if block_k is None:
        block_k = _auto_block(seq_k)
    if segment_ids is None:
        q_seg = jnp.zeros((batch, seq_q), jnp.int32)
        kv_seg = jnp.zeros((batch, seq_k), jnp.int32)
    else:
        q_seg = segment_ids.astype(jnp.int32)
        kv_seg = (q_seg if kv_segment_ids is None
                  else kv_segment_ids.astype(jnp.int32))
    return _flash_attention(q, k, v, q_seg, kv_seg, bool(causal),
                            float(sm_scale), int(block_q), int(block_k),
                            bool(interpret), bool(FLAGS.attn_pv_f32))
