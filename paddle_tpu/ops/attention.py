"""Blockwise (flash) attention for TPU — pallas kernel + pure-JAX reference.

This is the TPU-native successor of the reference's attention machinery
(trainer_config_helpers/networks.py:1304 simple_attention, :1402
dot_product_attention) extended to the modern multi-head form the new
framework needs for long-context support.  Segment-id masking plays the role
of the reference's ragged-sequence representation
(Argument.sequenceStartPositions, paddle/parameter/Argument.h:84-90;
LoDTensor, paddle/framework/lod_tensor.h:57): sequences are packed
back-to-back in one buffer and attention never crosses a segment boundary,
so there is no padding waste.

Design notes (TPU-first):
  - forward is a pallas kernel: grid (batch, heads, q-blocks); K/V live in
    VMEM per (batch, head); online-softmax accumulation in fp32; matmuls hit
    the MXU with block_q x head_dim x block_k shapes.
  - backward is TWO pallas kernels (dK/dV gridded over key blocks, dQ over
    query blocks) recomputing P blockwise from (q, k, lse) — the S x S score
    matrix never exists in either direction; fp32 accumulation on the MXU.
    FLAGS.use_pallas=False falls back to a blockwise lax.scan in plain JAX
    with identical semantics.
  - on CPU (tests / 8-device virtual mesh) the kernels run in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


from paddle_tpu.ops.kernel_util import interpret_default as _interpret_default


# ---------------------------------------------------------------------------
# Reference implementation (test oracle; also used for tiny shapes)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, segment_ids=None, kv_segment_ids=None,
                  causal: bool = False, sm_scale: Optional[float] = None):
    """Plain-JAX multi-head attention.

    q: (B, Sq, H, D); k, v: (B, Sk, H, D); segment_ids: (B, Sq) int32,
    kv_segment_ids: (B, Sk).  Returns (B, Sq, H, D).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    mask = None
    if segment_ids is not None:
        kv_seg = segment_ids if kv_segment_ids is None else kv_segment_ids
        mask = (segment_ids[:, None, :, None] == kv_seg[:, None, None, :])
    if causal:
        cm = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))[None, None]
        mask = cm if mask is None else (mask & cm)
    if mask is not None:
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref,
                      lse_ref, *, block_k: int, sm_scale: float,
                      causal: bool):
    # q_ref: (1, 1, block_q, D); k_ref/v_ref: (1, 1, Sk, D)
    # qseg_ref: (B, block_q); kseg_ref: (B, Sk) — full batch dim because TPU
    # block shapes must tile (8, 128) or span the whole array dim
    block_q = q_ref.shape[2]
    head_dim = q_ref.shape[3]
    seq_k = k_ref.shape[2]
    b = pl.program_id(0)
    qi = pl.program_id(2)

    q = q_ref[0, 0, :, :].astype(jnp.float32) * sm_scale
    q_ids = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    q_seg = qseg_ref[b, :].reshape(block_q, 1)

    num_kb = seq_k // block_k

    def body(j, carry):
        m_prev, l_prev, acc = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_ids = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        k_seg = kseg_ref[b, pl.ds(j * block_k, block_k)]
        mask = (q_seg == k_seg.reshape(1, block_k))
        if causal:
            mask = mask & (q_ids >= k_ids)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    if causal:
        # skip key blocks strictly after this q block
        num_kb_eff = jnp.minimum(
            num_kb, (qi + 1) * block_q // block_k +
            jnp.int32(block_q % block_k != 0) + 1)
    else:
        num_kb_eff = num_kb
    m, l, acc = jax.lax.fori_loop(0, num_kb_eff, body, (m0, l0, acc0))

    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros, not NaN
    o_ref[0, 0, :, :] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0, :, :] = m + jnp.log(l)


def _flash_fwd(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q, block_k,
               interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    assert seq_q % block_q == 0 and seq_k % block_k == 0, (
        f"sequence lengths ({seq_q},{seq_k}) must divide by blocks "
        f"({block_q},{block_k}) — DataFeeder pads capacity to multiples")
    # (B, S, H, D) -> (B, H, S, D) for contiguous per-head blocks
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (batch, heads, seq_q // block_q)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               sm_scale=sm_scale, causal=causal)
    out_t, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, seq_k, head_dim),
                         lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, seq_k, head_dim),
                         lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((batch, block_q), lambda b, h, i: (0, i)),
            pl.BlockSpec((batch, seq_k), lambda b, h, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, seq_q, head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, q_seg, kv_seg)
    return out_t.transpose(0, 2, 1, 3), lse[..., 0]


# ---------------------------------------------------------------------------
# Backward: pallas kernels (dK/dV then dQ), mirroring the forward's
# blocking. Reference for what they replace: the reference's hand-fused
# CUDA attention-adjacent kernels (paddle/cuda/src/*.cu) — here the win is
# recomputing P blockwise from (q, k, lse) so the S x S matrix never
# exists, with fp32 accumulation on the MXU.
# ---------------------------------------------------------------------------


def _flash_bwd_kv_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref,
                         lse_ref, delta_ref, dk_ref, dv_ref, *,
                         block_q: int, sm_scale: float, causal: bool):
    # k_ref/v_ref: (1, 1, block_k, D); q/do: (1, 1, Sq, D);
    # lse/delta: (1, 1, Sq, 1); qseg: (B, Sq); kseg: (B, block_k)
    block_k = k_ref.shape[2]
    head_dim = k_ref.shape[3]
    seq_q = q_ref.shape[2]
    b = pl.program_id(0)
    kj = pl.program_id(2)

    kb = k_ref[0, 0, :, :].astype(jnp.float32)
    vb = v_ref[0, 0, :, :].astype(jnp.float32)
    k_seg = kseg_ref[b, :].reshape(1, block_k)
    k_ids = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    num_qb = seq_q // block_q

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lseb = lse_ref[0, 0, pl.ds(i * block_q, block_q), :]
        deltab = delta_ref[0, 0, pl.ds(i * block_q, block_q), :]
        q_seg = qseg_ref[b, pl.ds(i * block_q, block_q)].reshape(block_q, 1)
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        mask = q_seg == k_seg
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = mask & (q_ids >= k_ids)
        p = jnp.where(mask, jnp.exp(s - lseb), 0.0)
        dv = dv + jax.lax.dot_general(p, dob, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab) * sm_scale
        dk = dk + jax.lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # q blocks strictly before this k block are fully masked
        start_qb = (kj * block_k) // block_q
    else:
        start_qb = 0
    dk0 = jnp.zeros((block_k, head_dim), jnp.float32)
    dv0 = jnp.zeros((block_k, head_dim), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (dk0, dv0))
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref,
                         lse_ref, delta_ref, dq_ref, *, block_k: int,
                         sm_scale: float, causal: bool):
    # q/do/lse/delta blocked over q; k/v full-seq per (b, h)
    block_q = q_ref.shape[2]
    head_dim = q_ref.shape[3]
    seq_k = k_ref.shape[2]
    b = pl.program_id(0)
    qi = pl.program_id(2)

    qb = q_ref[0, 0, :, :].astype(jnp.float32)
    dob = do_ref[0, 0, :, :].astype(jnp.float32)
    lseb = lse_ref[0, 0, :, :]
    deltab = delta_ref[0, 0, :, :]
    q_seg = qseg_ref[b, :].reshape(block_q, 1)
    q_ids = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    num_kb = seq_k // block_k

    def body(j, dq):
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        k_seg = kseg_ref[b, pl.ds(j * block_k, block_k)].reshape(1, block_k)
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        mask = q_seg == k_seg
        if causal:
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = mask & (q_ids >= k_ids)
        p = jnp.where(mask, jnp.exp(s - lseb), 0.0)
        dp = jax.lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltab) * sm_scale
        return dq + jax.lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    if causal:
        num_kb_eff = jnp.minimum(
            num_kb, (qi + 1) * block_q // block_k +
            jnp.int32(block_q % block_k != 0) + 1)
    else:
        num_kb_eff = num_kb
    dq = jax.lax.fori_loop(0, num_kb_eff, body,
                           jnp.zeros((block_q, head_dim), jnp.float32))
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)


def _flash_bwd_pallas(res, do, *, causal, sm_scale, block_q, block_k,
                      interpret):
    q, k, v, q_seg, kv_seg, out, lse = res
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    delta = jnp.sum(dot.astype(jnp.float32) *
                    out.transpose(0, 2, 1, 3).astype(jnp.float32),
                    axis=-1, keepdims=True)               # (B, H, Sq, 1)
    lse_t = lse[..., None]                                # (B, H, Sq, 1)

    full_q = pl.BlockSpec((1, 1, seq_q, head_dim), lambda b, h, i: (b, h, 0, 0))
    full_q1 = pl.BlockSpec((1, 1, seq_q, 1), lambda b, h, i: (b, h, 0, 0))
    blk_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i: (b, h, i, 0))
    blk_q1 = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0))
    full_k = pl.BlockSpec((1, 1, seq_k, head_dim), lambda b, h, i: (b, h, 0, 0))
    blk_k = pl.BlockSpec((1, 1, block_k, head_dim), lambda b, h, i: (b, h, i, 0))
    qseg_all = pl.BlockSpec((batch, seq_q), lambda b, h, i: (0, 0))
    qseg_blk = pl.BlockSpec((batch, block_q), lambda b, h, i: (0, i))
    kseg_all = pl.BlockSpec((batch, seq_k), lambda b, h, i: (0, 0))
    kseg_blk = pl.BlockSpec((batch, block_k), lambda b, h, i: (0, i))

    dk_t, dv_t = pl.pallas_call(
        functools.partial(_flash_bwd_kv_kernel, block_q=block_q,
                          sm_scale=sm_scale, causal=causal),
        grid=(batch, heads, seq_k // block_k),
        in_specs=[full_q, blk_k, blk_k, qseg_all, kseg_blk, full_q,
                  full_q1, full_q1],
        out_specs=[blk_k, blk_k],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, seq_k, head_dim), k.dtype),
            jax.ShapeDtypeStruct((batch, heads, seq_k, head_dim), v.dtype),
        ],
        interpret=interpret,
    )(qt, kt, vt, q_seg, kv_seg, dot, lse_t, delta)

    dq_t = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          sm_scale=sm_scale, causal=causal),
        grid=(batch, heads, seq_q // block_q),
        in_specs=[blk_q, full_k, full_k, qseg_blk, kseg_all, blk_q,
                  blk_q1, blk_q1],
        out_specs=blk_q,
        out_shape=jax.ShapeDtypeStruct((batch, heads, seq_q, head_dim),
                                       q.dtype),
        interpret=interpret,
    )(qt, kt, vt, q_seg, kv_seg, dot, lse_t, delta)

    return (dq_t.transpose(0, 2, 1, 3), dk_t.transpose(0, 2, 1, 3),
            dv_t.transpose(0, 2, 1, 3), None, None)


# ---------------------------------------------------------------------------
# Backward: blockwise scan over key blocks (plain JAX fallback)
# ---------------------------------------------------------------------------

def _flash_bwd(res, do, *, causal, sm_scale, block_k):
    q, k, v, q_seg, kv_seg, out, lse = res
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    block_k = min(block_k, seq_k)
    nkb = seq_k // block_k

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)  # (B,Sq,H)
    q_ids = jnp.arange(seq_q)
    k_ids_all = jnp.arange(seq_k).reshape(nkb, block_k)
    k_blocks = k.reshape(batch, nkb, block_k, heads, head_dim)
    v_blocks = v.reshape(batch, nkb, block_k, heads, head_dim)
    kseg_blocks = kv_seg.reshape(batch, nkb, block_k)

    def one_block(dq_acc, blk):
        kb, vb, ksegb, kids = blk  # kb: (B, block_k, H, D)
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, kb.astype(jnp.float32))
        s = s * sm_scale
        mask = (q_seg[:, :, None, None] == ksegb[:, None, None, :])
        if causal:
            mask = mask & (q_ids[None, :, None, None] >= kids[None, None, None, :])
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse.transpose(0, 2, 1)[:, :, :, None])  # (B,Sq,H,bk)
        p = jnp.where(mask, p, 0.0)
        dv = jnp.einsum("bqhk,bqhd->bkhd", p, dof)
        dp = jnp.einsum("bqhd,bkhd->bqhk", dof, vb.astype(jnp.float32))
        ds = p * (dp - delta[:, :, :, None]) * sm_scale
        dq_acc = dq_acc + jnp.einsum("bqhk,bkhd->bqhd", ds,
                                     kb.astype(jnp.float32))
        dk = jnp.einsum("bqhk,bqhd->bkhd", ds, qf)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((batch, seq_q, heads, head_dim), jnp.float32)
    dq, (dk_b, dv_b) = jax.lax.scan(
        one_block, dq0,
        (k_blocks.transpose(1, 0, 2, 3, 4), v_blocks.transpose(1, 0, 2, 3, 4),
         kseg_blocks.transpose(1, 0, 2), k_ids_all))
    dk = dk_b.transpose(1, 0, 2, 3, 4).reshape(batch, seq_k, heads, head_dim)
    dv = dv_b.transpose(1, 0, 2, 3, 4).reshape(batch, seq_k, heads, head_dim)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            None, None)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_attention(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q,
                     block_k, interpret):
    out, _ = _flash_fwd(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q,
                        block_k, interpret)
    return out


def _fwd_rule(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q, block_k,
              interpret):
    out, lse = _flash_fwd(q, k, v, q_seg, kv_seg, causal, sm_scale, block_q,
                          block_k, interpret)
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, do):
    from paddle_tpu.platform.flags import FLAGS

    if FLAGS.use_pallas:
        return _flash_bwd_pallas(res, do, causal=causal, sm_scale=sm_scale,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    return _flash_bwd(res, do, causal=causal, sm_scale=sm_scale,
                      block_k=block_k)


_flash_attention.defvjp(_fwd_rule, _bwd_rule)


def flash_attention(q, k, v, segment_ids=None, kv_segment_ids=None,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Blockwise multi-head attention (pallas forward, blockwise backward).

    Args:
      q: (B, Sq, H, D); k, v: (B, Sk, H, D).
      segment_ids: (B, Sq) int32 packed-sequence ids; tokens only attend
        within their own segment (use -1 for padding: give padding its own
        id).  None => full attention.
      kv_segment_ids: (B, Sk); defaults to segment_ids (self-attention).
      causal: lower-triangular masking (positions are absolute in the packed
        buffer — combine with segment ids for per-sequence causality).
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    batch, seq_q = q.shape[0], q.shape[1]
    seq_k = k.shape[1]
    if segment_ids is None:
        q_seg = jnp.zeros((batch, seq_q), jnp.int32)
        kv_seg = jnp.zeros((batch, seq_k), jnp.int32)
    else:
        q_seg = segment_ids.astype(jnp.int32)
        kv_seg = (q_seg if kv_segment_ids is None
                  else kv_segment_ids.astype(jnp.int32))
    return _flash_attention(q, k, v, q_seg, kv_seg, bool(causal),
                            float(sm_scale), int(block_q), int(block_k),
                            bool(interpret))
