"""Sequence/segment kernels — the ragged-sequence op family.

Reference: paddle/gserver/layers/SequencePoolLayer.cpp (max/avg/sum over each
sequence), SequenceLastInstanceLayer.cpp (seqlastins/first), ExpandLayer.cpp,
SequenceConcatLayer.cpp, SequenceReshapeLayer.cpp, SeqSliceLayer.cpp,
SubNestedSequenceLayer.cpp, KmaxSeqScoreLayer.cpp, MaxIdLayer.cpp, and the
sequence_softmax activation (ActivationFunction.cpp).

TPU-native: all ops work on the flat segment-ids form (paddle_tpu.sequence.
SequenceBatch) using jax segment reductions — no per-sequence loops, fully
static shapes, pad slots masked out.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.sequence import SequenceBatch, position_in_sequence


def _seg(sb: SequenceBatch) -> jax.Array:
    """Segment ids with pads mapped to an extra trash segment (= num_seqs)."""
    return jnp.where(sb.valid_mask, sb.segment_ids, sb.num_seqs)


def seq_pool_sum(sb: SequenceBatch) -> jax.Array:
    out = jax.ops.segment_sum(sb.data, _seg(sb), num_segments=sb.num_seqs + 1)
    return out[: sb.num_seqs]


def seq_pool_avg(sb: SequenceBatch) -> jax.Array:
    s = seq_pool_sum(sb)
    denom = jnp.maximum(sb.lengths, 1).astype(s.dtype)
    return s / denom.reshape((-1,) + (1,) * (s.ndim - 1))


def seq_pool_sqrtn(sb: SequenceBatch) -> jax.Array:
    s = seq_pool_sum(sb)
    denom = jnp.sqrt(jnp.maximum(sb.lengths, 1).astype(s.dtype))
    return s / denom.reshape((-1,) + (1,) * (s.ndim - 1))


def seq_pool_max(sb: SequenceBatch) -> jax.Array:
    neg = jnp.full_like(sb.data, -jnp.inf if jnp.issubdtype(sb.data.dtype, jnp.floating)
                        else jnp.iinfo(sb.data.dtype).min)
    masked = jnp.where(sb.valid_mask.reshape((-1,) + (1,) * (sb.data.ndim - 1)),
                       sb.data, neg)
    out = jax.ops.segment_max(masked, _seg(sb), num_segments=sb.num_seqs + 1)
    return out[: sb.num_seqs]


def seq_first(sb: SequenceBatch) -> jax.Array:
    """First token of each sequence (reference: SequenceLastInstanceLayer with
    select_first)."""
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(sb.lengths)[:-1].astype(jnp.int32)])
    return sb.data[starts]


def seq_last(sb: SequenceBatch) -> jax.Array:
    """Last token of each sequence (reference: seqlastins)."""
    ends = jnp.cumsum(sb.lengths).astype(jnp.int32) - 1
    ends = jnp.maximum(ends, 0)
    return sb.data[ends]


def sequence_softmax(sb: SequenceBatch) -> SequenceBatch:
    """Softmax over each sequence's scalar scores (reference:
    sequence_softmax activation). data: [capacity] or [capacity, 1]."""
    x = sb.data
    squeeze = x.ndim > 1
    if squeeze:
        x = x[..., 0]
    seg = _seg(sb)
    n = sb.num_seqs + 1
    x = jnp.where(sb.valid_mask, x, -jnp.inf)
    mx = jax.ops.segment_max(x, seg, num_segments=n)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
    ex = jnp.where(sb.valid_mask, jnp.exp(x - mx[seg]), 0.0)
    z = jax.ops.segment_sum(ex, seg, num_segments=n)
    out = ex / jnp.maximum(z[seg], 1e-30)
    if squeeze:
        out = out[..., None]
    return sb.with_data(out.astype(sb.data.dtype))


def seq_expand(sb_short, sb_long: SequenceBatch) -> SequenceBatch:
    """Expand per-sequence (or per-token) values of `sb_short` to the token
    layout of `sb_long` (reference: ExpandLayer.cpp).

    sb_short may be a dense [num_seqs, ...] array (one row per sequence).
    """
    if isinstance(sb_short, SequenceBatch):
        values = seq_first(sb_short)  # one representative per sequence
    else:
        values = sb_short
    seg = jnp.clip(sb_long.segment_ids, 0, values.shape[0] - 1)
    data = values[seg]
    mask = sb_long.valid_mask.reshape((-1,) + (1,) * (data.ndim - 1))
    return sb_long.with_data(jnp.where(mask, data, 0))


def seq_concat(a: SequenceBatch, b: SequenceBatch) -> SequenceBatch:
    """Concatenate sequence i of `a` with sequence i of `b` along time
    (reference: SequenceConcatLayer.cpp)."""
    pa, _ = a.to_padded()
    pb, mb = b.to_padded()
    B = a.num_seqs
    Tb = pb.shape[1]
    lengths = a.lengths + b.lengths
    # Place b's tokens after a's true length by scattering into [B, Ta+Tb, ...].
    out = jnp.concatenate([pa, jnp.zeros_like(pb)], axis=1)
    t_idx = jnp.arange(Tb, dtype=jnp.int32)[None, :] + a.lengths[:, None]
    t_idx = jnp.where(mb, t_idx, out.shape[1])  # invalid b-slots scatter off-range (dropped)
    b_rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, Tb))
    out = out.at[b_rows, t_idx].set(pb, mode="drop")
    return SequenceBatch.from_padded(out, lengths, capacity=a.capacity + b.capacity)


def seq_reshape(sb: SequenceBatch, new_dim: int) -> SequenceBatch:
    """Reshape each sequence's [len, d] to [len*d/new_dim, new_dim]
    (reference: SequenceReshapeLayer.cpp). Requires contiguous tokens."""
    d = sb.data.shape[-1]
    cap = sb.capacity * d // new_dim
    data = sb.data.reshape(cap, new_dim)
    new_lengths = (sb.lengths * d) // new_dim
    from paddle_tpu.sequence import lengths_to_segment_ids
    seg = lengths_to_segment_ids(new_lengths, cap)
    new_max = None if sb.max_len is None else max(1, sb.max_len * d // new_dim)
    return SequenceBatch(data=data, segment_ids=seg, lengths=new_lengths,
                         max_len=new_max)

def seq_slice(sb: SequenceBatch, starts: jax.Array, ends: jax.Array) -> SequenceBatch:
    """Keep tokens with start<=pos<end per sequence (reference: SeqSliceLayer).

    Returns the same capacity with a new mask/lengths (tokens compacted left
    per-sequence is not required by downstream segment ops)."""
    pos = position_in_sequence(sb.segment_ids)
    seg = jnp.clip(sb.segment_ids, 0, sb.num_seqs - 1)
    keep = sb.valid_mask & (pos >= starts[seg]) & (pos < ends[seg])
    new_lengths = jnp.clip(jnp.minimum(ends, sb.lengths) - starts, 0, None)
    seg_ids = jnp.where(keep, sb.segment_ids, sb.num_seqs)
    mask = keep.reshape((-1,) + (1,) * (sb.data.ndim - 1))
    return SequenceBatch(data=jnp.where(mask, sb.data, 0), segment_ids=seg_ids,
                         lengths=new_lengths.astype(jnp.int32),
                         max_len=sb.max_len)


def kmax_seq_score(sb: SequenceBatch, k: int) -> jax.Array:
    """Indices (positions within each sequence) of the top-k scores
    (reference: KmaxSeqScoreLayer.cpp). data: [capacity] or [capacity,1].
    Returns [num_seqs, k] int32 positions (padded with -1)."""
    scores, mask = sb.with_data(
        sb.data[..., 0] if sb.data.ndim > 1 else sb.data).to_padded()
    scores = jnp.where(mask, scores, -jnp.inf)
    _, idx = jax.lax.top_k(scores, k)
    valid = jnp.take_along_axis(mask, idx, axis=1)
    return jnp.where(valid, idx, -1).astype(jnp.int32)


def max_id(x: jax.Array) -> jax.Array:
    """Argmax along the last dim (reference: MaxIdLayer.cpp)."""
    return jnp.argmax(x, axis=-1).astype(jnp.int32)


def sub_nested_seq(sb: SequenceBatch, selected: jax.Array) -> SequenceBatch:
    """Select inner sequences from a nested sequence batch (reference:
    SubNestedSequenceLayer.cpp). `selected`: [num_seqs, k] inner indices
    (-1 = none). Tokens of unselected inner seqs are masked out."""
    if sb.sub_segment_ids is None:
        raise ValueError("sub_nested_seq requires nested SequenceBatch")
    seg = jnp.clip(sb.segment_ids, 0, sb.num_seqs - 1)
    sel = selected[seg]  # [capacity, k]
    keep = jnp.any(sel == sb.sub_segment_ids[:, None], axis=-1) & sb.valid_mask
    seg_ids = jnp.where(keep, sb.segment_ids, sb.num_seqs)
    n = sb.num_seqs + 1
    new_lengths = jax.ops.segment_sum(keep.astype(jnp.int32),
                                      jnp.where(keep, seg, sb.num_seqs),
                                      num_segments=n)[: sb.num_seqs]
    mask = keep.reshape((-1,) + (1,) * (sb.data.ndim - 1))
    return SequenceBatch(data=jnp.where(mask, sb.data, 0), segment_ids=seg_ids,
                         lengths=new_lengths, max_len=sb.max_len)
