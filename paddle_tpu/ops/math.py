"""Dense math kernels — the paddle/math Matrix::mul / hl_matrix_mul analog.

Reference: paddle/math/Matrix.cpp:502-536 (GpuMatrix::mul → cublasSgemm via
cuda/src/hl_cuda_cublas.cc:225). On TPU the gemm is ``jnp.dot`` lowered to the
MXU; the framework-wide policy is bfloat16 inputs with float32 accumulation
(``preferred_element_type``), which is both faster and the TPU-idiomatic
equivalent of the reference's float32 SGEMM.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.platform.flags import FLAGS


def compute_dtype(x: jax.Array) -> jnp.dtype:
    """Matmul/conv INPUT dtype under the global policy (bf16 when
    FLAGS.use_bf16; accumulation stays f32 via preferred_element_type)."""
    if FLAGS.use_bf16 and x.dtype in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        return jnp.dtype(jnp.bfloat16)
    return x.dtype


def matmul(a: jax.Array, b: jax.Array, *, trans_a: bool = False,
           trans_b: bool = False, out_dtype=jnp.float32) -> jax.Array:
    """MXU matmul with bf16 inputs / f32 accumulation under the global policy."""
    if trans_a:
        a = jnp.swapaxes(a, -1, -2)
    if trans_b:
        b = jnp.swapaxes(b, -1, -2)
    ct = compute_dtype(a)
    return jnp.matmul(a.astype(ct), b.astype(ct),
                      preferred_element_type=jnp.dtype(out_dtype))


def dense_activation_dtype() -> jnp.dtype:
    """Storage dtype for dense/sequence layer outputs (fc, embedding,
    attention — the transformer residual stream). The dense analog of
    ops/conv.py activation_dtype: bf16 halves residual-stream HBM traffic;
    norm statistics and losses still reduce in f32 (ops/norm.py layer_norm,
    ops/losses.py softmax_cross_entropy upcast internally)."""
    if FLAGS.use_bf16 and FLAGS.bf16_dense_activations:
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(jnp.float32)


def fc(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None) -> jax.Array:
    """y = x @ w (+ b) — FullyConnectedLayer::forward analog
    (reference: gserver/layers/FullyConnectedLayer.cpp:69-88)."""
    y = matmul(x, w)
    if b is not None:
        y = y + b
    return y


def outer_product_update(x, y):
    """Rank-1 accumulate helper (reference Matrix::mul with trans variants)."""
    return matmul(x, y, trans_a=True)


def dropout(x: jax.Array, rate: float, key: jax.Array, train: bool) -> jax.Array:
    """Inverted dropout (reference: dropout in ExtraLayerAttribute/Layer.cpp)."""
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
