"""Shared helpers for pallas TPU kernels."""

from __future__ import annotations

import jax


def interpret_default() -> bool:
    """Run pallas kernels in interpret mode on CPU (tests, virtual CPU
    meshes). Anything else — 'tpu' or a TPU-relay platform like 'axon' —
    compiles natively."""
    return jax.default_backend() == "cpu"
