"""Pooling kernels — the PoolLayer/CudnnPoolLayer/hl_cnn pooling analog.

Reference: paddle/gserver/layers/PoolLayer.cpp, SpatialPyramidPoolLayer.cpp,
MaxOutLayer.cpp, PoolProjection; Gen-2 paddle/operators/pool_op.cc.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

IntOr2 = Union[int, Tuple[int, int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def max_pool2d(x: jax.Array, window: IntOr2, stride: IntOr2 = None,
               padding: IntOr2 = 0) -> jax.Array:
    """x: [N,H,W,C]."""
    kh, kw = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    ph, pw = _pair(padding)
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max, (1, kh, kw, 1), (1, sh, sw, 1),
        ((0, 0), (ph, ph), (pw, pw), (0, 0)))


def avg_pool2d(x: jax.Array, window: IntOr2, stride: IntOr2 = None,
               padding: IntOr2 = 0, *, exclude_padding: bool = True) -> jax.Array:
    kh, kw = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    ph, pw = _pair(padding)
    # accumulate in f32: bf16 activations (FLAGS.bf16_activations) would lose
    # mantissa bits summing kh*kw values; cast back to the input dtype after
    summed = lax.reduce_window(
        x.astype(jnp.float32), 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1),
        ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    if exclude_padding and (ph or pw):
        ones = jnp.ones(x.shape[:3] + (1,), jnp.float32)
        counts = lax.reduce_window(
            ones, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1),
            ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        return (summed / counts).astype(x.dtype)
    return (summed / float(kh * kw)).astype(x.dtype)


def max_pool2d_with_index(x: jax.Array, window: IntOr2, stride: IntOr2 = None,
                          padding: IntOr2 = 0):
    """Returns (pooled, argmax flat index within each window's source map).

    Reference: paddle/operators/pool_with_index_op (used by unpool).
    """
    n, h, w, c = x.shape
    flat_idx = jnp.broadcast_to(
        (jnp.arange(h)[:, None] * w + jnp.arange(w)[None, :])[None, :, :, None],
        x.shape).astype(jnp.int32)
    kh, kw = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    ph, pw = _pair(padding)

    def reducer(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    init = (jnp.array(-jnp.inf, x.dtype), jnp.array(-1, jnp.int32))
    vals, idxs = lax.reduce_window(
        (x, flat_idx), init, reducer, (1, kh, kw, 1), (1, sh, sw, 1),
        ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    return vals, idxs


def spatial_pyramid_pool(x: jax.Array, pyramid_height: int,
                         pool_type: str = "max") -> jax.Array:
    """SPP (reference: SpatialPyramidPoolLayer.cpp): concat pooled bins at
    scales 1,2,4,...  x: [N,H,W,C] -> [N, sum(4^l)*C]."""
    n, h, w, c = x.shape
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        # adaptive pooling: split H/W into `bins` regions via reshape-trick on
        # padded maps (pad up to a multiple of bins).
        hh = -(-h // bins) * bins
        ww = -(-w // bins) * bins
        if pool_type == "max":
            xp = jnp.pad(x, ((0, 0), (0, hh - h), (0, ww - w), (0, 0)),
                         constant_values=-jnp.inf)
            r = xp.reshape(n, bins, hh // bins, bins, ww // bins, c).max((2, 4))
        else:
            # accumulate bins in f32: bf16 activations would round away
            # terms once the partial sum is large (same fix as avg_pool2d)
            xp = jnp.pad(x.astype(jnp.float32),
                         ((0, 0), (0, hh - h), (0, ww - w), (0, 0)))
            cnt = jnp.pad(jnp.ones((1, h, w, 1), jnp.float32),
                          ((0, 0), (0, hh - h), (0, ww - w), (0, 0)))
            s = xp.reshape(n, bins, hh // bins, bins, ww // bins, c).sum((2, 4))
            d = cnt.reshape(1, bins, hh // bins, bins, ww // bins, 1).sum((2, 4))
            r = (s / d).astype(x.dtype)
        outs.append(r.reshape(n, -1))
    return jnp.concatenate(outs, axis=-1)


def maxout(x: jax.Array, groups: int) -> jax.Array:
    """Maxout over channel groups (reference: MaxOutLayer.cpp).

    x: [N,H,W,C] with C divisible by groups -> [N,H,W,C/groups].
    """
    n, h, w, c = x.shape
    return x.reshape(n, h, w, c // groups, groups).max(-1)


def unpool2d(pooled: jax.Array, indices: jax.Array, out_hw: Tuple[int, int]) -> jax.Array:
    """Scatter pooled values back to argmax positions (max_pool inverse)."""
    n, oh, ow, c = pooled.shape
    h, w = out_hw
    flat = jnp.zeros((n, h * w, c), pooled.dtype)
    idx = indices.reshape(n, oh * ow, c)
    src = pooled.reshape(n, oh * ow, c)
    ni = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, None, :]
    flat = flat.at[ni, idx, ci].add(src)
    return flat.reshape(n, h, w, c)
