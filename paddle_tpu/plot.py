"""Training curve plotter (reference: python/paddle/v2/plot/plot.py
Ploter). Collects (step, value) series; renders with matplotlib when
available, else prints — so headless training loops can use it
unconditionally."""

from __future__ import annotations

from typing import Dict, List, Tuple


class Ploter:
    def __init__(self, *titles: str):
        self.titles = list(titles)
        self.data: Dict[str, Tuple[List[float], List[float]]] = {
            t: ([], []) for t in titles}

    def append(self, title: str, step: float, value: float) -> None:
        xs, ys = self.data[title]
        xs.append(float(step))
        ys.append(float(value))

    def reset(self) -> None:
        for xs, ys in self.data.values():
            xs.clear()
            ys.clear()

    def plot(self, path: str = None) -> None:
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            for t, (xs, ys) in self.data.items():
                tail = ys[-1] if ys else float("nan")
                print(f"[plot] {t}: {len(xs)} points, last={tail:.5f}")
            return
        plt.figure()
        for t, (xs, ys) in self.data.items():
            plt.plot(xs, ys, label=t)
        plt.legend()
        plt.xlabel("step")
        if path:
            plt.savefig(path)
        plt.close()
