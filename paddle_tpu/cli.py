"""Command-line driver: ``python -m paddle_tpu <cmd>``.

Reference analog: the ``paddle`` wrapper script and its subcommands
(paddle/scripts/submit_local.sh.in:96-104 — train / pserver /
merge_model / dump_config / version; TrainerMain.cpp).

Config convention (the config_parser analog): ``--config`` names a
python file that, when executed, defines at module level:

- ``cost``       — the cost LayerOutput (required for train/dump/merge)
- ``outputs``    — inference output LayerOutput(s) (merge_model; falls
                   back to ``cost``'s inputs[0])
- ``reader``     — a no-arg callable yielding sample tuples (train)
- ``optimizer``  — a paddle_tpu.optimizer.Optimizer (train; default Adam)
- ``batch_size`` — int (default 32)

The pserver subcommand maps to the elastic-input master service (the
pserver's parameter-hosting role is absorbed by mesh sharding; what
remains centralized is task dispatch — go/master)."""

from __future__ import annotations

import argparse
import json
import runpy
import sys
from typing import Optional


def _load_config(path: str) -> dict:
    import paddle_tpu as paddle

    paddle.topology.reset_name_scope()
    return runpy.run_path(path)


def _load_errors():
    """Exception classes meaning "the model artifact on disk is missing or
    corrupt" — a config mistake worth a one-line exit-2 message. Deliberately
    narrow: failures AFTER a successful disk read (mesh placement, shape
    mismatch in update_from) must keep their traceback."""
    import tarfile

    from paddle_tpu.platform.enforce import EnforceError

    return (OSError, tarfile.ReadError, EnforceError, EOFError, KeyError,
            ValueError)


def cmd_train(args) -> int:
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt_mod
    from paddle_tpu import trainer

    cfg = _load_config(args.config)
    cost = cfg["cost"]
    optimizer = cfg.get("optimizer") or opt_mod.Adam(learning_rate=1e-3)
    batch_size = int(cfg.get("batch_size", 32))
    # cheap config guards BEFORE init/parameter construction: a missing
    # reader must not pay a full random init of a large model first
    if getattr(args, "job", "train") == "train" and cfg.get("reader") is None:
        print("config must define reader() for train", file=sys.stderr)
        return 2

    paddle.init()
    params = paddle.Parameters.from_topology(
        paddle.topology.Topology([cost]))
    sgd = trainer.SGD(cost=cost, parameters=params,
                      update_equation=optimizer)

    if getattr(args, "job", "train") == "test":
        # `paddle train --job=test` analog (Tester.cpp): evaluate a saved
        # model on the config's test_reader (falls back to reader)
        _LOAD_ERRORS = _load_errors()
        reader = cfg.get("test_reader") or cfg.get("reader")
        if reader is None:
            print("config must define test_reader()/reader() for --job=test",
                  file=sys.stderr)
            return 2
        if args.init_model_tar:
            try:
                with open(args.init_model_tar, "rb") as f:
                    sgd.parameters.init_from_tar(f)
            except _LOAD_ERRORS as e:  # missing/corrupt tar is a config
                print(f"cannot load model tar {args.init_model_tar}: {e}",
                      file=sys.stderr)  # mistake, not a crash
                return 2
        elif args.save_dir:
            # the canonical resume path: restores model state too and
            # re-places params on the mesh. Only the disk read is guarded;
            # apply_checkpoint failures keep their traceback.
            from paddle_tpu import checkpoint as ckpt
            try:
                loaded = ckpt.load_checkpoint(args.save_dir)
            except _LOAD_ERRORS as e:
                print(f"cannot load checkpoint from {args.save_dir}: {e}",
                      file=sys.stderr)
                return 2
            sgd.apply_checkpoint(loaded)
        else:
            print("--job=test needs --save_dir or --init_model_tar",
                  file=sys.stderr)
            return 2
        result = sgd.test(paddle.batch(reader, batch_size))
        metrics = " ".join(f"{k}={v:.6g}" for k, v in
                           sorted(result.metrics.items()))
        print(f"Test cost={result.cost:.6g}" + (f" {metrics}" if metrics
                                                else ""))
        return 0

    reader = cfg["reader"]
    sgd.train(paddle.batch(reader, batch_size),
              num_passes=args.num_passes,
              save_dir=args.save_dir, start_pass=args.start_pass,
              saving_period=args.saving_period)
    return 0


def cmd_dump_config(args) -> int:
    from paddle_tpu import utils
    from paddle_tpu.topology import Topology

    cfg = _load_config(args.config)
    topo = Topology([cfg["cost"]])
    if args.format == "dot":
        print(utils.make_model_diagram(topo))
    else:
        print(utils.dump_config(topo))
    return 0


def cmd_merge_model(args) -> int:
    import paddle_tpu as paddle
    from paddle_tpu import checkpoint as ckpt
    from paddle_tpu import export as pexport

    cfg = _load_config(args.config)
    outputs = cfg.get("outputs") or cfg["cost"].inputs[0]
    _LOAD_ERRORS = _load_errors()
    try:
        if args.model_dir:
            params, _, _, _ = ckpt.load_checkpoint(args.model_dir)
        elif args.model_tar:
            with open(args.model_tar, "rb") as f:
                params = paddle.Parameters.from_tar(f)
        else:
            print("need --model_dir or --model_tar", file=sys.stderr)
            return 2
    except _LOAD_ERRORS as e:
        print(f"cannot load model from "
              f"{args.model_dir or args.model_tar}: {e}", file=sys.stderr)
        return 2
    pexport.merge_model(outputs, params, args.output)
    print(f"wrote {args.output}")
    return 0


def cmd_master(args) -> int:
    from paddle_tpu.master.server import MasterServer

    srv = MasterServer(host=args.host, port=args.port)
    srv.start()
    print(f"master serving on {srv.address}", flush=True)
    if args.dataset:
        srv.service.set_dataset(args.dataset)
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def cmd_version(args) -> int:
    import jax

    import paddle_tpu

    print(f"paddle_tpu {paddle_tpu.__version__} "
          f"(jax {jax.__version__}, backend "
          f"{jax.default_backend()})")
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle_tpu",
        description="TPU-native trainer CLI (the `paddle` script analog)")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train or evaluate a config")
    t.add_argument("--config", required=True)
    t.add_argument("--job", choices=("train", "test"), default="train",
                   help="test = evaluate a saved model (Tester analog)")
    t.add_argument("--num_passes", type=int, default=1)
    t.add_argument("--save_dir", default=None)
    t.add_argument("--init_model_tar", default=None,
                   help="parameter tar to evaluate with --job=test")
    t.add_argument("--start_pass", type=int, default=0)
    t.add_argument("--saving_period", type=int, default=1)
    t.set_defaults(fn=cmd_train)

    d = sub.add_parser("dump_config", help="print the model config")
    d.add_argument("--config", required=True)
    d.add_argument("--format", choices=["json", "dot"], default="json")
    d.set_defaults(fn=cmd_dump_config)

    m = sub.add_parser("merge_model",
                       help="pack config+weights into one inference file")
    m.add_argument("--config", required=True)
    m.add_argument("--model_dir", default=None,
                   help="checkpoint dir (latest pass)")
    m.add_argument("--model_tar", default=None, help="params tar file")
    m.add_argument("--output", required=True)
    m.set_defaults(fn=cmd_merge_model)

    s = sub.add_parser("master", help="run the elastic-input master")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=0)
    s.add_argument("--dataset", nargs="*", default=None,
                   help="recordio paths to partition")
    s.set_defaults(fn=cmd_master)

    v = sub.add_parser("version")
    v.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:   # `paddle_tpu dump_config | head` etc.
        return 0


if __name__ == "__main__":
    sys.exit(main())
