"""Parameter initializers.

Reference: parameter init in paddle/parameter/Parameter.cpp (randomize per
initial_strategy/initial_mean/initial_std/initial_smart in ParameterConfig.proto:34)
— uniform, normal, and the "smart" fan-in scaled uniform default. Expressed here
as pure functions ``(key, shape, dtype) -> array`` so layers stay functional.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp


def _fan_in_out(shape: Sequence[int]):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [h, w, cin, cout] (HWIO layout used throughout ops/conv.py)
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype=jnp.float32, minval=self.low,
                                  maxval=self.high).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype=jnp.float32):
        return (self.mean + self.std * jax.random.normal(key, shape)).astype(dtype)


class XavierUniform(Initializer):
    """The reference's 'smart' default: scale by fan-in (Parameter.cpp randomize)."""

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, fan_out = _fan_in_out(shape)
        limit = math.sqrt(6.0 / max(1, fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype=jnp.float32, minval=-limit,
                                  maxval=limit).astype(dtype)


class FanInNormal(Initializer):
    """std = 1/sqrt(fan_in) normal — matches initial_smart for std-based init."""

    def __call__(self, key, shape, dtype=jnp.float32):
        fan_in, _ = _fan_in_out(shape)
        std = 1.0 / math.sqrt(max(1, fan_in))
        return (std * jax.random.normal(key, shape)).astype(dtype)


def default_weight_init() -> Initializer:
    return XavierUniform()


def default_bias_init() -> Initializer:
    return Constant(0.0)


def to_initializer(arg) -> Initializer:
    if arg is None:
        return default_weight_init()
    if isinstance(arg, Initializer):
        return arg
    if callable(arg):
        wrapped = arg

        class _Wrapped(Initializer):
            def __call__(self, key, shape, dtype=jnp.float32):
                return wrapped(key, shape, dtype)

        return _Wrapped()
    if isinstance(arg, (int, float)):
        return Constant(float(arg))
    raise TypeError(f"cannot convert {arg!r} to Initializer")
