"""DataFeeder: python sample batches -> device arrays / SequenceBatch.

Reference: python/paddle/v2/data_feeder.py + py_paddle
dataprovider_converter.py:247 (numpy -> Arguments with sequence start
positions per slot kind).

TPU-native twist: sequence slots are packed into the flat segment-ids form
with a *bucketed* static capacity (next power of two over the batch's token
count) so XLA compiles a small number of shapes instead of one per batch —
the replacement for truly-dynamic Argument shapes.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from paddle_tpu.data_type import InputType, SeqKind, SlotKind
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.sequence import SequenceBatch


def _bucket(n: int, minimum: int = 64) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


class DataFeeder:
    """feeding: {data_layer_name: index-in-sample} or list of names."""

    def __init__(self, data_types: List[Tuple[str, InputType]], feeding=None):
        self.data_types = data_types
        if feeding is None:
            feeding = {name: i for i, (name, _) in enumerate(data_types)}
        elif isinstance(feeding, (list, tuple)):
            feeding = {name: i for i, name in enumerate(feeding)}
        self.feeding = feeding

    def __call__(self, batch_data) -> Dict[str, object]:
        return self.feed(batch_data)

    def feed(self, batch_data) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for name, itype in self.data_types:
            col = [sample[self.feeding[name]] for sample in batch_data]
            out[name] = self._convert(name, itype, col)
        return out

    # ------------------------------------------------------------------

    def _dense_row(self, itype: InputType, row) -> np.ndarray:
        if itype.slot == SlotKind.DENSE:
            arr = np.asarray(row, dtype=np.float32)
            enforce_that(arr.size == itype.dim or arr.ndim > 1,
                         f"dense slot expects dim {itype.dim}, got shape "
                         f"{arr.shape}", context="feeder")
            return arr.reshape(-1) if arr.ndim <= 1 else arr
        if itype.slot == SlotKind.INDEX:
            return np.asarray(row, dtype=np.int32)
        if itype.slot == SlotKind.SPARSE_BINARY:
            dense = np.zeros((itype.dim,), np.float32)
            dense[np.asarray(row, dtype=np.int64)] = 1.0
            return dense
        if itype.slot == SlotKind.SPARSE_FLOAT:
            dense = np.zeros((itype.dim,), np.float32)
            for idx, val in row:
                dense[idx] = val
            return dense
        raise EnforceError(f"unsupported slot {itype.slot}", context="feeder")

    def _convert(self, name: str, itype: InputType, col):
        if itype.seq == SeqKind.NO_SEQUENCE:
            rows = [self._dense_row(itype, r) for r in col]
            arr = np.stack(rows)
            if itype.slot == SlotKind.INDEX:
                arr = arr.reshape(len(rows), -1)
                if arr.shape[1] == 1:
                    arr = arr[:, 0]
            return jnp.asarray(arr)

        if itype.seq == SeqKind.SEQUENCE:
            seqs = []
            for sample_seq in col:
                tokens = [self._dense_row(itype, tok) for tok in sample_seq]
                if itype.slot == SlotKind.INDEX:
                    seqs.append(np.asarray(sample_seq, np.int32).reshape(-1, 1))
                else:
                    seqs.append(np.stack(tokens) if tokens else
                                np.zeros((0, itype.dim), np.float32))
            total = sum(s.shape[0] for s in seqs)
            cap = _bucket(total)
            dtype = jnp.int32 if itype.slot == SlotKind.INDEX else jnp.float32
            sb = SequenceBatch.from_list(seqs, dtype=dtype, capacity=cap)
            # bucket the static max_len so scan lengths hit few jit cache keys
            import dataclasses
            sb = dataclasses.replace(
                sb, max_len=min(cap, _bucket(sb.max_len or 1, minimum=16)))
            if itype.slot == SlotKind.INDEX:
                sb = sb.with_data(sb.data[..., 0])  # ids as [capacity]
            return sb

        # SUB_SEQUENCE: list of list of tokens per sample
        flat_seqs = []
        sub_ids = []
        for sample in col:
            toks = []
            for j, inner in enumerate(sample):
                inner_arr = (np.asarray(inner, np.int32).reshape(-1, 1)
                             if itype.slot == SlotKind.INDEX
                             else np.stack([self._dense_row(itype, t) for t in inner]))
                toks.append(inner_arr)
                sub_ids.extend([j] * inner_arr.shape[0])
            flat_seqs.append(np.concatenate(toks, axis=0) if toks
                             else np.zeros((0, itype.dim), np.float32))
        total = sum(s.shape[0] for s in flat_seqs)
        cap = _bucket(total)
        dtype = jnp.int32 if itype.slot == SlotKind.INDEX else jnp.float32
        sb = SequenceBatch.from_list(flat_seqs, dtype=dtype, capacity=cap)
        sub = np.full((cap,), 0, np.int32)
        sub[: len(sub_ids)] = sub_ids
        sb = SequenceBatch(data=sb.data if itype.slot != SlotKind.INDEX else sb.data[..., 0],
                           segment_ids=sb.segment_ids, lengths=sb.lengths,
                           sub_segment_ids=jnp.asarray(sub),
                           max_len=min(cap, _bucket(sb.max_len or 1, minimum=16)))
        return sb
