"""Topology: the layer graph and its compilation to a pure jax function.

Reference analog: the ModelConfig protobuf built by config_parser.py plus the
C++ NeuralNetwork layer-graph executor (gserver/gradientmachines/
NeuralNetwork.cpp:245-295) and paddle.v2.topology.Topology
(python/paddle/v2/topology.py:33).

TPU-native design: layer functions build a DAG of ``LayerOutput`` nodes; a
``Topology`` freezes the transitive closure of requested outputs into a
topologically-ordered node list and exposes ``forward(params, state, feeds)``
— a *pure function* executed under ``jax.jit``. There is no interpreter at
runtime: the whole graph is traced once and compiled by XLA, so "layers" cost
nothing at step time (the reference pays a C++ virtual call + kernel launch
per layer; here XLA fuses across layer boundaries).

Backward pass: none is built by hand — ``jax.grad`` of ``forward`` replaces
the reference's per-layer ``backward()`` methods and Gen-2 AppendBackward
(framework/backward.cc:434).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddle_tpu.attr import ParamAttr
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.sequence import SequenceBatch

# ---------------------------------------------------------------------------
# Graph nodes
# ---------------------------------------------------------------------------

_name_counters: Dict[str, int] = {}


def unique_name(prefix: str) -> str:
    idx = _name_counters.get(prefix, 0)
    _name_counters[prefix] = idx + 1
    return f"{prefix}_{idx}"


def reset_name_scope() -> None:
    _name_counters.clear()


# ---------------------------------------------------------------------------
# Remat (activation checkpointing) scopes
# ---------------------------------------------------------------------------

_remat_stack: List[str] = []


class remat_scope:
    """Tag every layer created inside with a remat group.

    The classic TPU memory/compute trade: nodes sharing a group are executed
    as ONE ``jax.checkpoint``-wrapped segment by ``Topology.forward``, so the
    backward pass recomputes the segment's activations from its boundary
    inputs instead of keeping them in HBM. Wrapping each transformer block
    buys O(n_layers) activation memory for ~1 extra forward of FLOPs — the
    lever that lets the bench run bigger batch/sequence tiers.

    Reference analog: none — the reference keeps every layer's output alive
    for backward (gserver NeuralNetwork keeps per-layer Arguments); remat is
    the XLA-era replacement.

    Usage::

        with topology.remat_scope("blk0"):
            x = layer.fc(...)
    """

    def __init__(self, group: str):
        self.group = group

    def __enter__(self):
        _remat_stack.append(self.group)
        return self

    def __exit__(self, *exc):
        _remat_stack.pop()
        return False


@dataclass
class ParamSpec:
    """Declared parameter of a layer node."""

    shape: Tuple[int, ...]
    attr: ParamAttr = field(default_factory=ParamAttr)
    dtype: Any = jnp.float32


@dataclass
class StateSpec:
    """Non-trainable state slot (e.g. batch-norm moving stats)."""

    shape: Tuple[int, ...]
    init_value: float = 0.0
    dtype: Any = jnp.float32


class Context:
    """Per-forward execution context handed to each node's compute fn.

    ``mesh`` (when set) enables per-layer activation sharding constraints
    (ExtraAttr.sharding — the ParallelNeuralNetwork layer-placement
    analog, see paddle_tpu.parallel.placement)."""

    def __init__(self, train: bool, rng: Optional[jax.Array],
                 state: Dict[str, Dict[str, jax.Array]], mesh=None):
        self.train = train
        self._rng = rng
        self.state_in = state
        self.state_out: Dict[str, Dict[str, jax.Array]] = {}
        self._current: Optional[str] = None
        self.mesh = mesh

    def rng_for(self, node_name: str) -> jax.Array:
        if self._rng is None:
            return jax.random.PRNGKey(0)
        # stable per-node stream derived from the step key
        h = int.from_bytes(hashlib.md5(node_name.encode()).digest()[:4], "little")
        return jax.random.fold_in(self._rng, h)

    def get_state(self, node_name: str, key: str) -> jax.Array:
        return self.state_in[node_name][key]

    def set_state(self, node_name: str, key: str, value: jax.Array) -> None:
        self.state_out.setdefault(node_name, {})[key] = value


@dataclass
class LayerOutput:
    """A node in the layer graph; also the user-facing handle (v2 LayerOutput
    analog, python/paddle/v2/layer.py)."""

    name: str
    layer_type: str
    inputs: List["LayerOutput"]
    # fn(ctx, params: dict, inputs: list of values) -> value
    fn: Callable[[Context, Dict[str, jax.Array], List[Any]], Any]
    params: Dict[str, ParamSpec] = field(default_factory=dict)
    state: Dict[str, StateSpec] = field(default_factory=dict)
    # State slots this node manages under OTHER namespaces (sub-layer names
    # of a hosted step graph). Keyed namespace -> slot -> spec. Lets a
    # training recurrent_group and a beam_search generator built from the
    # same step SHARE stateful slots (batch-norm moving stats) the same way
    # pinned param names share weights.
    foreign_state: Dict[str, Dict[str, StateSpec]] = field(default_factory=dict)
    size: Optional[int] = None          # feature dimension, v2-API compatible
    is_sequence: bool = False           # value is a SequenceBatch
    is_cost: bool = False               # per-example loss output
    remat_group: Optional[str] = None   # set by the enclosing remat_scope

    def __post_init__(self):
        enforce_that(self.name is not None, "layer needs a name")
        if self.remat_group is None and _remat_stack and self.fn is not None:
            self.remat_group = _remat_stack[-1]

    # Graph sugar: l1 + l2 = addto
    def __add__(self, other: "LayerOutput") -> "LayerOutput":
        from paddle_tpu import layer as L

        return L.addto(input=[self, other])

    def __repr__(self):
        return f"LayerOutput({self.name!r}, type={self.layer_type!r}, size={self.size})"


def topological_order(outputs: Sequence[LayerOutput]) -> List[LayerOutput]:
    seen: Dict[str, LayerOutput] = {}
    order: List[LayerOutput] = []

    def visit(node: LayerOutput, stack: Tuple[int, ...]):
        if node.name in seen:
            enforce_that(seen[node.name] is node,
                         f"two different layers named {node.name!r}", context="topology")
            return
        if id(node) in stack:
            raise EnforceError(f"cycle through layer {node.name!r}", context="topology")
        for inp in node.inputs:
            visit(inp, stack + (id(node),))
        # a transitively-visited input may have claimed this name already
        enforce_that(seen.get(node.name, node) is node,
                     f"two different layers named {node.name!r}", context="topology")
        seen[node.name] = node
        order.append(node)

    for out in outputs:
        visit(out, ())
    return order


# ---------------------------------------------------------------------------
# Topology
# ---------------------------------------------------------------------------


class Topology:
    """Frozen graph over the transitive closure of ``outputs``.

    ``forward`` is pure: (params, state, feeds, train, rng) -> (outputs, new_state).
    """

    def __init__(self, outputs: Union[LayerOutput, Sequence[LayerOutput]]):
        if isinstance(outputs, LayerOutput):
            outputs = [outputs]
        self.outputs: List[LayerOutput] = list(outputs)
        self.nodes: List[LayerOutput] = topological_order(self.outputs)
        self.by_name: Dict[str, LayerOutput] = {n.name: n for n in self.nodes}
        self.data_nodes: List[LayerOutput] = sorted(
            (n for n in self.nodes if n.layer_type == "data"),
            key=lambda n: getattr(n, "declare_idx", 0))

    # ---- specs -----------------------------------------------------------

    def param_specs(self) -> Dict[str, ParamSpec]:
        """Flat parameter table: '<layer>.<param>' -> spec. Explicit
        ParamAttr.name aliases share storage (the reference's parameter
        sharing via param names)."""
        specs: Dict[str, ParamSpec] = {}
        for node in self.nodes:
            for pname, spec in node.params.items():
                full = spec.attr.name or f"{node.name}.{pname}"
                if full in specs:
                    enforce_that(tuple(specs[full].shape) == tuple(spec.shape),
                                 f"shared parameter {full!r} shape mismatch "
                                 f"{specs[full].shape} vs {spec.shape}", context="topology")
                else:
                    specs[full] = spec
        return specs

    def param_key(self, node: LayerOutput, pname: str) -> str:
        spec = node.params[pname]
        return spec.attr.name or f"{node.name}.{pname}"

    def state_specs(self) -> Dict[str, Dict[str, StateSpec]]:
        out: Dict[str, Dict[str, StateSpec]] = {}
        for n in self.nodes:
            if n.state:
                out.setdefault(n.name, {}).update(n.state)
            for ns, slots in n.foreign_state.items():
                have = out.setdefault(ns, {})
                for k, spec in slots.items():
                    if k in have:
                        enforce_that(
                            tuple(have[k].shape) == tuple(spec.shape),
                            f"shared state slot {ns}/{k} shape mismatch "
                            f"{have[k].shape} vs {spec.shape}", context="topology")
                    else:
                        have[k] = spec
        return out

    def init_state(self) -> Dict[str, Dict[str, jax.Array]]:
        out: Dict[str, Dict[str, jax.Array]] = {}
        for lname, slots in self.state_specs().items():
            out[lname] = {
                k: jnp.full(s.shape, s.init_value, dtype=s.dtype) for k, s in slots.items()
            }
        return out

    # ---- execution -------------------------------------------------------

    def forward(self, params: Dict[str, jax.Array],
                state: Dict[str, Dict[str, jax.Array]],
                feeds: Dict[str, Any], *, train: bool = False,
                rng: Optional[jax.Array] = None,
                outputs: Optional[Sequence[LayerOutput]] = None,
                mesh=None
                ) -> Tuple[List[Any], Dict[str, Dict[str, jax.Array]]]:
        wanted = list(outputs) if outputs is not None else self.outputs
        ctx = Context(train=train, rng=rng, state=state, mesh=mesh)
        values: Dict[str, Any] = {}
        order = topological_order(wanted)
        done_groups: set = set()
        for node in order:
            if node.fn is None:  # data layers and frame/memory placeholders
                if node.name not in feeds:
                    raise EnforceError(f"missing feed for data layer {node.name!r}",
                                       context="forward")
                values[node.name] = feeds[node.name]
                continue
            if node.remat_group is not None:
                if node.remat_group not in done_groups:
                    done_groups.add(node.remat_group)
                    self._run_remat_group(node.remat_group, order, values,
                                          params, ctx,
                                          {w.name for w in wanted})
                continue
            node_params = {p: params[self.param_key(node, p)] for p in node.params}
            ins = [values[i.name] for i in node.inputs]
            ctx._current = node.name
            # named_scope: layer names show up in xplane/profiler traces
            # (the REGISTER_TIMER-per-layer analog, NeuralNetwork.cpp:259)
            try:
                with jax.named_scope(node.name):
                    values[node.name] = node.fn(ctx, node_params, ins)
            except Exception as e:
                # the CustomStackTrace analog (utils/CustomStackTrace.h,
                # pushed per layer NeuralNetwork.cpp:260-262): name the
                # failing layer so shape/dtype errors point at the config
                e.add_note(
                    f"[paddle_tpu] while computing layer {node.name!r} "
                    f"(type={node.layer_type}, "
                    f"inputs={[i.name for i in node.inputs]})")
                raise
        new_state = dict(state)
        for ns, slots in ctx.state_out.items():
            # per-slot merge: a node updating one slot must not drop the
            # namespace's other slots
            new_state[ns] = {**new_state.get(ns, {}), **slots}
        return [values[w.name] for w in wanted], new_state

    def _run_remat_group(self, group: str, order: List[LayerOutput],
                         values: Dict[str, Any],
                         params: Dict[str, jax.Array], ctx: Context,
                         wanted_names: set) -> None:
        """Execute one remat group as a single jax.checkpoint segment.

        The segment is a pure function of (its params, the step rng, its
        boundary inputs) -> (boundary outputs, state updates); XLA drops
        the segment's internal activations after forward and recomputes
        them during backward.
        """
        nodes = [n for n in order if n.remat_group == group]
        in_group = {n.name for n in nodes}
        ext_in: List[str] = []
        for n in nodes:
            for i in n.inputs:
                if i.name not in in_group and i.name not in ext_in:
                    ext_in.append(i.name)
                    enforce_that(
                        i.name in values,
                        f"remat group {group!r} input {i.name!r} is not "
                        f"available yet — the group is not a contiguous "
                        f"segment of the graph", context="remat")
        consumed_outside = set(wanted_names)
        for n in order:
            if n.remat_group != group:
                consumed_outside.update(i.name for i in n.inputs)
        ext_out = [n.name for n in nodes if n.name in consumed_outside]
        enforce_that(ext_out,
                     f"remat group {group!r} has no outputs used outside it",
                     context="remat")
        pkeys = sorted({self.param_key(n, p) for n in nodes for p in n.params})
        # rng=None must stay None inside the segment so per-node streams
        # derive exactly as in the un-rematted graph (rng_for's fallback)
        has_rng = ctx._rng is not None
        rng_arg = ctx._rng if has_rng else jax.random.PRNGKey(0)

        def segment(seg_params, seg_rng, ext_vals):
            local = dict(zip(ext_in, ext_vals))
            sub = Context(train=ctx.train, rng=seg_rng if has_rng else None,
                          state=ctx.state_in, mesh=ctx.mesh)
            for n in nodes:
                node_params = {p: seg_params[self.param_key(n, p)]
                               for p in n.params}
                ins = [local[i.name] for i in n.inputs]
                sub._current = n.name
                try:
                    with jax.named_scope(n.name):
                        local[n.name] = n.fn(sub, node_params, ins)
                except Exception as e:
                    e.add_note(
                        f"[paddle_tpu] while computing layer {n.name!r} "
                        f"(type={n.layer_type}, remat group {group!r}, "
                        f"inputs={[i.name for i in n.inputs]})")
                    raise
            return [local[nm] for nm in ext_out], sub.state_out

        with jax.named_scope(f"remat_{group}"):
            outs, state_out = jax.checkpoint(segment)(
                {k: params[k] for k in pkeys}, rng_arg,
                [values[nm] for nm in ext_in])
        for nm, v in zip(ext_out, outs):
            values[nm] = v
        for ns, slots in state_out.items():
            ctx.state_out.setdefault(ns, {}).update(slots)

    def __repr__(self):
        return f"Topology({len(self.nodes)} nodes, outputs={[o.name for o in self.outputs]})"
