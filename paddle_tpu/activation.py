"""Activation descriptors — the 14-activation inventory.

Reference: paddle/gserver/activations/ActivationFunction.cpp
(BEGIN_DEFINE_ACTIVATION list: sigmoid, softmax, sequence_softmax, relu, brelu,
tanh, stanh, softrelu, abs, square, exponential, reciprocal, sqrt, log) and
python/paddle/trainer_config_helpers/activations.py. Each descriptor carries a
pure jax fn; sequence_softmax needs segment metadata and is resolved inside the
sequence ops (ops/sequence_ops.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class BaseActivation:
    name = "base"
    fn = None  # staticmethod (x) -> x

    def __repr__(self):
        return f"{type(self).__name__}()"


class LinearActivation(BaseActivation):
    name = "linear"
    fn = staticmethod(lambda x: x)


class SigmoidActivation(BaseActivation):
    name = "sigmoid"
    fn = staticmethod(jax.nn.sigmoid)


class TanhActivation(BaseActivation):
    name = "tanh"
    fn = staticmethod(jnp.tanh)


class STanhActivation(BaseActivation):
    """Scaled tanh: 1.7159 * tanh(2x/3) (reference STanhActivation)."""

    name = "stanh"
    fn = staticmethod(lambda x: 1.7159 * jnp.tanh(2.0 * x / 3.0))


class ReluActivation(BaseActivation):
    name = "relu"
    fn = staticmethod(jax.nn.relu)


class BReluActivation(BaseActivation):
    """Bounded relu: min(max(x, 0), 24) (reference BReluActivation)."""

    name = "brelu"
    fn = staticmethod(lambda x: jnp.clip(x, 0.0, 24.0))


class SoftReluActivation(BaseActivation):
    """log(1 + e^x), input clipped to ±40 like the reference."""

    name = "softrelu"
    fn = staticmethod(lambda x: jnp.log1p(jnp.exp(jnp.clip(x, -40.0, 40.0))))


class SoftmaxActivation(BaseActivation):
    name = "softmax"
    fn = staticmethod(lambda x: jax.nn.softmax(x, axis=-1))


class SequenceSoftmaxActivation(BaseActivation):
    """Softmax over each variable-length sequence (resolved by sequence ops)."""

    name = "sequence_softmax"
    fn = None  # needs segment ids; see ops.sequence_ops.sequence_softmax


class AbsActivation(BaseActivation):
    name = "abs"
    fn = staticmethod(jnp.abs)


class SquareActivation(BaseActivation):
    name = "square"
    fn = staticmethod(jnp.square)


class ExpActivation(BaseActivation):
    name = "exponential"
    fn = staticmethod(jnp.exp)


class ReciprocalActivation(BaseActivation):
    name = "reciprocal"
    fn = staticmethod(jnp.reciprocal)


class SqrtActivation(BaseActivation):
    name = "sqrt"
    fn = staticmethod(jnp.sqrt)


class LogActivation(BaseActivation):
    name = "log"
    fn = staticmethod(jnp.log)


class GeluActivation(BaseActivation):
    """Gaussian error linear unit (tanh form) — transformer-era extension
    beyond the reference's 14 (ActivationFunction.cpp); the FFN activation
    of the transformer LM family (models/transformer.py)."""
    name = "gelu"
    fn = staticmethod(jax.nn.gelu)


_REGISTRY = {
    cls.name: cls
    for cls in [
        LinearActivation, SigmoidActivation, TanhActivation, STanhActivation,
        ReluActivation, BReluActivation, SoftReluActivation, SoftmaxActivation,
        SequenceSoftmaxActivation, AbsActivation, SquareActivation, ExpActivation,
        ReciprocalActivation, SqrtActivation, LogActivation, GeluActivation,
    ]
}


def get(name_or_act):
    """Resolve an activation descriptor from a name, class, or instance."""
    if name_or_act is None:
        return LinearActivation()
    if isinstance(name_or_act, BaseActivation):
        return name_or_act
    if isinstance(name_or_act, type) and issubclass(name_or_act, BaseActivation):
        return name_or_act()
    if isinstance(name_or_act, str):
        if name_or_act not in _REGISTRY:
            raise KeyError(f"unknown activation {name_or_act!r}")
        return _REGISTRY[name_or_act]()
    raise TypeError(f"cannot resolve activation from {name_or_act!r}")
