"""Checkpoint/resume with optimizer state.

Reference analog: per-pass parameter dirs ``pass-%05d`` written by
ParamUtil::saveParameters (trainer/ParamUtil.h:77-96), resume via
--start_pass/--init_model_path (ParamUtil.h:108-111), and the Gen-cloud
optimizer-state-inclusive checkpoints with md5+meta written atomically
(go/pserver/service.go:76-152, OptimizerConfig.proto *OptimizerState).

Layout per pass::

    <dir>/pass-00007/
        params.tar      # weights (v2 Parameters tar format)
        state.pkl       # optimizer slots + model state (np arrays)
        meta.json       # pass id, md5 of both blobs, timestamp

Writes are atomic (tmp + rename) like the Go pserver's checkpoint path.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import tempfile
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.parameters import Parameters
from paddle_tpu.platform.enforce import EnforceError, enforce_that

_PASS_RE = re.compile(r"^pass-(\d{5})$")


def _to_numpy_tree(tree):
    import jax
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write(path: str, writer) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def pass_dir(root: str, pass_id: int) -> str:
    return os.path.join(root, f"pass-{pass_id:05d}")


def save_checkpoint(root: str, pass_id: int, parameters: Parameters,
                    opt_state: Any = None, model_state: Any = None,
                    extra_meta: Optional[Dict] = None,
                    shard_plan: Any = None) -> str:
    """``shard_plan`` (a ``parallel.zero.ZeroPlan``): when the trainer runs
    ZeRO-1, slot state lives as padded 1/N flat shards per replica; the
    plan gathers them back to full tensor shapes before pickling so the
    artifact stays layout-independent — a zero_stage=1 save loads under
    zero_stage=0 (or a different mesh size) and vice versa."""
    if shard_plan is not None and opt_state is not None:
        opt_state = shard_plan.gather_state(opt_state)
    d = pass_dir(root, pass_id)
    os.makedirs(d, exist_ok=True)
    params_path = os.path.join(d, "params.tar")
    state_path = os.path.join(d, "state.pkl")
    _atomic_write(params_path, parameters.to_tar)
    _atomic_write(state_path, lambda f: pickle.dump(
        {"opt_state": _to_numpy_tree(opt_state),
         "model_state": _to_numpy_tree(model_state)}, f))
    meta = {"pass_id": pass_id,
            "params_md5": _md5(params_path),
            "state_md5": _md5(state_path),
            "timestamp": time.time()}
    meta.update(extra_meta or {})
    _atomic_write(os.path.join(d, "meta.json"),
                  lambda f: f.write(json.dumps(meta).encode()))
    return d


def latest_pass(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    best = None
    for name in os.listdir(root):
        m = _PASS_RE.match(name)
        if m and os.path.exists(os.path.join(root, name, "meta.json")):
            p = int(m.group(1))
            best = p if best is None else max(best, p)
    return best


def prune_checkpoints(root: str, keep: int = 2) -> None:
    """Delete all but the ``keep`` newest checkpoints. Crash-resume only
    needs the latest; one older is kept as insurance while the newest is
    young (the Go pserver similarly overwrites its single checkpoint)."""
    import shutil

    if not os.path.isdir(root):
        return
    ids = sorted(int(m.group(1)) for name in os.listdir(root)
                 if (m := _PASS_RE.match(name)))
    for pid in ids[:-keep] if keep > 0 else ids:
        shutil.rmtree(pass_dir(root, pid), ignore_errors=True)


def load_checkpoint(root: str, pass_id: Optional[int] = None
                    ) -> Tuple[Parameters, Any, Any, Dict]:
    """Returns (parameters, opt_state, model_state, meta). Verifies md5
    integrity (the etcd-meta check of the Go pserver)."""
    if pass_id is None:
        pass_id = latest_pass(root)
        enforce_that(pass_id is not None, f"no checkpoints under {root}",
                     context="checkpoint")
    d = pass_dir(root, pass_id)
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    params_path = os.path.join(d, "params.tar")
    state_path = os.path.join(d, "state.pkl")
    if _md5(params_path) != meta["params_md5"]:
        raise EnforceError(f"corrupt checkpoint params {params_path}",
                           context="checkpoint")
    if _md5(state_path) != meta["state_md5"]:
        raise EnforceError(f"corrupt checkpoint state {state_path}",
                           context="checkpoint")
    with open(params_path, "rb") as f:
        params = Parameters.from_tar(f)
    with open(state_path, "rb") as f:
        st = pickle.load(f)
    return params, st["opt_state"], st["model_state"], meta
