"""Checkpoint/resume with optimizer state.

Reference analog: per-pass parameter dirs ``pass-%05d`` written by
ParamUtil::saveParameters (trainer/ParamUtil.h:77-96), resume via
--start_pass/--init_model_path (ParamUtil.h:108-111), and the Gen-cloud
optimizer-state-inclusive checkpoints with md5+meta written atomically
(go/pserver/service.go:76-152, OptimizerConfig.proto *OptimizerState).

Layout per pass::

    <dir>/pass-00007/
        params.tar      # weights (v2 Parameters tar format)
        state.pkl       # optimizer slots + model state (np arrays)
        meta.json       # pass id, md5 of both blobs, timestamp, cursor

Commit protocol (the Go pserver's tmp+rename path, made kill-precise):

1. ``params.tar`` is written to a tempfile and renamed into place;
2. ``state.pkl`` likewise;
3. ``meta.json`` — carrying the md5 of both blobs — is written LAST,
   again tmp+rename.

A checkpoint exists only once its meta commits: a kill at any earlier
point leaves a meta-less dir that every reader skips (the previous
checkpoint stays ``latest``), and a kill mid-prune or a torn blob is
caught by the md5 verify and rejected with a grep-able ``CKPT-CORRUPT``
line instead of crashing the resume.

The save is split in two halves so a background writer can own the slow
one (:class:`paddle_tpu.resilience.AsyncCheckpointer`):

- :func:`snapshot_checkpoint` — device -> host copy (the only part that
  must stall training; ZeRO shard plans gather through the compiled
  ``zero.replicate`` identity);
- :func:`write_checkpoint` — pure disk I/O over the host snapshot,
  thread-safe, honoring the commit protocol above.

``extra_meta`` may carry a ``cursor`` dict (pass id, step-in-pass,
global step, rng state, task-queue position) — the step-granular resume
contract ``trainer.SGD.train(resume=True)`` reads back.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import tempfile
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from paddle_tpu.parameters import Parameters
from paddle_tpu.platform.enforce import EnforceError, enforce_that

_PASS_RE = re.compile(r"^pass-(\d{5})$")

# write_checkpoint announces these phases to its commit_hook, in order;
# a fault plan killing at "meta" simulates the classic torn save: both
# blobs durable, meta missing, previous checkpoint still latest
COMMIT_PHASES = ("params", "state", "meta", "done")


def _to_numpy_tree(tree):
    from paddle_tpu.parallel.zero import host_tree

    return host_tree(tree)


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write(path: str, writer) -> None:
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def pass_dir(root: str, pass_id: int) -> str:
    return os.path.join(root, f"pass-{pass_id:05d}")


# ---------------------------------------------------------------------------
# snapshot (device -> host) / write (host -> disk) split
# ---------------------------------------------------------------------------


@dataclass
class HostCheckpoint:
    """A fully host-resident checkpoint payload: everything
    :func:`write_checkpoint` needs, holding NO device buffers — safe to
    hand to a background writer thread while the training loop keeps
    donating its device state."""

    params: Dict[str, np.ndarray]
    opt_state: Any = None
    model_state: Any = None


def snapshot_checkpoint(parameters, opt_state: Any = None,
                        model_state: Any = None,
                        shard_plan: Any = None) -> HostCheckpoint:
    """Device -> host copy of the full training state (the only phase of
    an async save that stalls the train loop).  ``shard_plan`` (a
    ``parallel.zero.ZeroPlan``): ZeRO-1 flat slot shards gather back to
    full tensor shapes through the plan's compiled-identity path so the
    artifact stays layout-independent — a zero_stage=1 save loads under
    zero_stage=0 (or a different mesh size) and vice versa."""
    if shard_plan is not None and opt_state is not None:
        opt_state = shard_plan.gather_state(opt_state)
    params = parameters.as_dict() if hasattr(parameters, "as_dict") \
        else dict(parameters)
    return HostCheckpoint(params=_to_numpy_tree(params),
                          opt_state=_to_numpy_tree(opt_state),
                          model_state=_to_numpy_tree(model_state))


def write_checkpoint(root: str, pass_id: int, host: HostCheckpoint,
                     extra_meta: Optional[Dict] = None,
                     commit_hook: Optional[Callable[[str], None]] = None
                     ) -> str:
    """Write a host snapshot to ``pass_dir(root, pass_id)`` under the
    tmp+rename+md5 commit protocol (meta.json LAST — see module doc).
    Pure disk I/O: thread-safe against a training loop that keeps
    running, and re-entrant over a torn dir from an earlier kill (the
    same pass id simply overwrites the debris).

    ``commit_hook`` is called with each :data:`COMMIT_PHASES` name just
    BEFORE that phase's write ("done" fires after the meta commit) — the
    fault-injection seam ``TrainFaultPlan.save_hook`` uses to kill a
    save at a chosen point."""
    hook = commit_hook if commit_hook is not None else (lambda phase: None)
    d = pass_dir(root, pass_id)
    os.makedirs(d, exist_ok=True)
    params_path = os.path.join(d, "params.tar")
    state_path = os.path.join(d, "state.pkl")
    hook("params")
    _atomic_write(params_path, lambda f: _params_to_tar(host.params, f))
    hook("state")
    _atomic_write(state_path, lambda f: pickle.dump(
        {"opt_state": host.opt_state,
         "model_state": host.model_state}, f))
    meta = {"pass_id": pass_id,
            "params_md5": _md5(params_path),
            "state_md5": _md5(state_path),
            "timestamp": time.time()}
    meta.update(extra_meta or {})
    hook("meta")
    _atomic_write(os.path.join(d, "meta.json"),
                  lambda f: f.write(json.dumps(meta).encode()))
    hook("done")
    return d


def _params_to_tar(host_params: Dict[str, np.ndarray], f) -> None:
    """Write a host param dict in the v2 Parameters tar format (one
    writer: delegates to Parameters.to_tar so the on-disk shape cannot
    diverge between the sync and async save paths)."""
    p = Parameters()
    p._values.update(host_params)
    p.to_tar(f)


def save_checkpoint(root: str, pass_id: int, parameters: Parameters,
                    opt_state: Any = None, model_state: Any = None,
                    extra_meta: Optional[Dict] = None,
                    shard_plan: Any = None,
                    commit_hook: Optional[Callable[[str], None]] = None
                    ) -> str:
    """Synchronous save: snapshot + write in one call (the original
    entry point; the AsyncCheckpointer calls the two halves itself)."""
    return write_checkpoint(
        root, pass_id,
        snapshot_checkpoint(parameters, opt_state=opt_state,
                            model_state=model_state, shard_plan=shard_plan),
        extra_meta=extra_meta, commit_hook=commit_hook)


# ---------------------------------------------------------------------------
# verify / load / prune
# ---------------------------------------------------------------------------


def _pass_ids(root: str) -> List[int]:
    if not os.path.isdir(root):
        return []
    return sorted(int(m.group(1)) for name in os.listdir(root)
                  if (m := _PASS_RE.match(name)))


# committed checkpoint dirs are immutable (same-id rewrites go through
# tmp+rename, changing inode mtimes), so a successful verify is cached
# by the three files' stat signature — repeat prunes/loads over the
# same artifacts skip the full md5 read-back.  Only SUCCESS is cached:
# failures are cheap to recompute and may be fixed by an overwrite.
_VERIFY_OK_CACHE: Dict[str, Tuple] = {}


def _stat_sig(d: str) -> Optional[Tuple]:
    try:
        sig = []
        for name in ("meta.json", "params.tar", "state.pkl"):
            st = os.stat(os.path.join(d, name))
            sig.append((name, st.st_size, st.st_mtime_ns))
        return tuple(sig)
    except OSError:
        return None


def verify_pass_dir(root: str, pass_id: int) -> Optional[str]:
    """Integrity check of one checkpoint dir (the etcd-meta md5 check of
    the Go pserver, runnable without loading).  Returns None when the
    artifact is intact, else a short reason string: missing/corrupt
    meta.json (a kill before the meta commit), or a missing/torn blob
    (a torn prune, a partially-synced copy)."""
    d = pass_dir(root, pass_id)
    sig = _stat_sig(d)
    if sig is not None and _VERIFY_OK_CACHE.get(d) == sig:
        return None
    meta_path = os.path.join(d, "meta.json")
    if not os.path.exists(meta_path):
        return "missing meta.json"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return "corrupt meta.json"
    for blob, key in (("params.tar", "params_md5"),
                      ("state.pkl", "state_md5")):
        path = os.path.join(d, blob)
        if key not in meta:
            return f"meta.json missing {key}"
        if not os.path.exists(path):
            return f"missing {blob}"
        if _md5(path) != meta[key]:
            return f"md5 mismatch on {blob}"
    if sig is not None:
        if len(_VERIFY_OK_CACHE) > 256:
            _VERIFY_OK_CACHE.clear()
        _VERIFY_OK_CACHE[d] = sig
    return None


def _report_corrupt(d: str, reason: str) -> None:
    # grep-able, same contract as OBS-POSTMORTEM: the resilience checker
    # (python -m paddle_tpu.resilience check) counts these lines and
    # tools_tier1.sh turns its findings into ladder exit 10
    print(f"CKPT-CORRUPT: {d} ({reason})", flush=True)


def latest_pass(root: str) -> Optional[int]:
    if not os.path.isdir(root):
        return None
    best = None
    for name in os.listdir(root):
        m = _PASS_RE.match(name)
        if m and os.path.exists(os.path.join(root, name, "meta.json")):
            p = int(m.group(1))
            best = p if best is None else max(best, p)
    return best


def prune_checkpoints(root: str, keep: int = 2) -> None:
    """Delete old checkpoints, never the newest VERIFIED one: only dirs
    that pass :func:`verify_pass_dir` count toward ``keep``, so corrupt
    young dirs (a torn prune, a kill-during-save) cannot cause the only
    good artifact to be reaped.  Unverified dirs NEWER than the oldest
    kept verified checkpoint are left alone too (they may be saves in
    flight); older debris is swept.  With no verified dir at all the old
    id-order rule applies (nothing is provably better than anything
    else)."""
    import shutil

    ids = _pass_ids(root)
    if not ids:
        return
    if keep <= 0:
        victims = ids
    else:
        # newest-first with early stop: verification (an md5 read-back,
        # though cached for immutable committed dirs) runs only until
        # `keep` intact dirs are found — old dirs below the cut are
        # deleted without ever being hashed
        kept: List[int] = []
        for pid in reversed(ids):
            if verify_pass_dir(root, pid) is None:
                kept.append(pid)
                if len(kept) >= keep:
                    break
        if not kept:
            victims = ids[:-keep]
        else:
            cut = kept[-1]
            victims = [pid for pid in ids if pid < cut]
    for pid in victims:
        _VERIFY_OK_CACHE.pop(pass_dir(root, pid), None)
        shutil.rmtree(pass_dir(root, pid), ignore_errors=True)


def _read_checkpoint(d: str) -> Tuple[Parameters, Any, Any, Dict]:
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    with open(os.path.join(d, "params.tar"), "rb") as f:
        params = Parameters.from_tar(f)
    with open(os.path.join(d, "state.pkl"), "rb") as f:
        st = pickle.load(f)
    return params, st["opt_state"], st["model_state"], meta


def load_latest(root: str) -> Optional[Tuple[Parameters, Any, Any, Dict]]:
    """Newest INTACT checkpoint under ``root``, or None when no usable
    one exists.  Walks newest -> oldest: a dir whose meta never
    committed (kill-during-save) is skipped silently — that is the
    commit protocol working as designed — while a meta-bearing dir with
    missing/torn blobs is rejected with a ``CKPT-CORRUPT`` line and the
    walk falls back to the next-older artifact instead of crashing the
    resume."""
    for pid in reversed(_pass_ids(root)):
        reason = verify_pass_dir(root, pid)
        if reason is None:
            return _read_checkpoint(pass_dir(root, pid))
        if reason != "missing meta.json":
            _report_corrupt(pass_dir(root, pid), reason)
    return None


def load_checkpoint(root: str, pass_id: Optional[int] = None
                    ) -> Tuple[Parameters, Any, Any, Dict]:
    """Returns (parameters, opt_state, model_state, meta), md5-verified
    (the etcd-meta check of the Go pserver).  With ``pass_id=None`` the
    newest intact checkpoint wins — corrupt dirs are rejected with a
    ``CKPT-CORRUPT`` line and the next-older artifact is used.  An
    EXPLICIT ``pass_id`` that fails verification raises (the caller
    asked for that artifact specifically; silently substituting another
    would resume from the wrong state)."""
    if pass_id is None:
        got = load_latest(root)
        enforce_that(got is not None,
                     f"no intact checkpoints under {root}",
                     context="checkpoint")
        return got
    d = pass_dir(root, pass_id)
    reason = verify_pass_dir(root, pass_id)
    if reason is not None:
        _report_corrupt(d, reason)
        raise EnforceError(f"CKPT-CORRUPT: corrupt checkpoint {d} "
                           f"({reason})", context="checkpoint")
    return _read_checkpoint(d)
