"""SGD trainer with events — the paddle.v2.trainer analog.

Reference: python/paddle/v2/trainer.py:124-202 (SGD.train event loop over a
reader), paddle/trainer/TrainerInternal.cpp:66-158 (per-batch
forwardBackward + update + stats), Tester.cpp.

TPU-native: one jitted ``train_step`` fuses forward+backward+optimizer into a
single XLA program (the reference pays a python→SWIG→C++ transition and one
kernel launch per layer per batch; here the whole step is one device
execution with buffer donation). Gradients come from ``jax.grad`` — there is
no hand-written backward graph. Data parallelism: pass ``mesh=`` and dense
feeds are sharded over the 'data' axis; XLA inserts the psum (the
MultiGradientMachine ring / pserver addGradient analog).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import event as v2_event
from paddle_tpu.analysis.retrace import SiteContract, audit_jit
from paddle_tpu.obs.registry import default_registry
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.optimizer import Optimizer
from paddle_tpu.parameters import Parameters
from paddle_tpu.platform import plog, stats
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import LayerOutput, Topology


def _reduce_cost(value) -> jax.Array:
    """Total cost over the batch / num examples (reference divides summed cost
    by batch size, TrainerInternal.cpp trainOneBatch)."""
    if isinstance(value, SequenceBatch):
        total = jnp.sum(jnp.where(value.valid_mask, value.data.reshape(value.capacity, -1).sum(-1)
                                  if value.data.ndim > 1 else value.data, 0.0))
        return total / jnp.maximum(value.num_seqs, 1)
    return jnp.mean(value)


def _metric_scalar(value) -> jax.Array:
    """Mean of a metric layer's output over valid examples/tokens."""
    if isinstance(value, SequenceBatch):
        d = value.data.reshape(value.capacity, -1).sum(-1) if value.data.ndim > 1 else value.data
        total = jnp.sum(jnp.where(value.valid_mask, d, 0.0))
        count = jnp.sum(value.valid_mask)
        return total / jnp.maximum(count, 1)
    return jnp.mean(value)


class SGD:
    """v2-compatible trainer: SGD(cost, parameters, update_equation).train(...).

    ``metrics`` maps display names to metric LayerOutputs (the evaluator
    analog — see paddle_tpu.evaluator); they are computed in-graph per batch
    and averaged across the pass for EndPass events.
    """

    def __init__(self, cost, parameters: Parameters, update_equation: Optimizer,
                 extra_layers: Optional[Sequence[LayerOutput]] = None,
                 is_local: bool = True, mesh=None,
                 metrics: Optional[Dict[str, LayerOutput]] = None,
                 zero_axis: Optional[str] = None,
                 zero: Optional[int] = None):
        costs = [cost] if isinstance(cost, LayerOutput) else list(cost)
        self.metrics = dict(metrics or {})
        # auto-collect evaluator nodes passed via extra_layers
        for n in (extra_layers or []):
            self.metrics.setdefault(n.name, n)
        outputs = costs + list(self.metrics.values())
        self.topology = Topology(outputs)
        self._n_costs = len(costs)
        self.parameters = parameters
        self.optimizer = update_equation
        self.optimizer.set_param_specs(self.topology.param_specs())
        self.model_state = self.topology.init_state()
        self.mesh = mesh
        self._zero_axis = zero_axis
        # commit params to their declared shardings (ParamAttr.sharding;
        # replicated by default, ZeRO-style largest-dim sharding with
        # zero_axis=) BEFORE optimizer slots are created: zeros_like slots
        # then inherit the committed shardings, so no device ever
        # materializes a full slot replica of a sharded weight
        self._place_on_mesh(slots_too=False)
        # ZeRO-1 (zero= arg, default FLAGS.zero_stage): shard optimizer
        # state 1/N over the 'data' axis while params stay replicated —
        # the plan threads through init_state so slots are sharded from
        # step 0, and through apply for the per-step reduce-scatter /
        # all-gather pair (parallel/zero.py)
        self._zero_plan = None
        stage = int(FLAGS.zero_stage if zero is None else zero)
        if stage:
            enforce_that(stage == 1, f"zero_stage={stage} not implemented "
                         "(0 = off, 1 = optimizer-state sharding)",
                         context="trainer")
            usable = mesh is not None and "data" in mesh.axis_names
            # an EXPLICIT zero= request that cannot take effect is an
            # error (silently training replicated would fake the N x
            # memory claim); the process-wide FLAGS.zero_stage stays
            # permissive so single-device tools keep working
            enforce_that(usable or zero is None,
                         "zero=1 needs mesh= with a 'data' axis (got "
                         + ("no mesh" if mesh is None else
                            f"axes {tuple(mesh.axis_names)}") + ")",
                         context="trainer")
            if usable:
                from paddle_tpu.parallel.zero import build_zero_plan

                self._zero_plan = build_zero_plan(
                    mesh, parameters.as_dict(),
                    specs=self.topology.param_specs(),
                    zero_axis=self._zero_axis)
        # unconditional (including None): a reused optimizer instance must
        # not carry a previous trainer's plan into this one
        self.optimizer.set_zero_plan(self._zero_plan)
        self.opt_state = self.optimizer.init_state(parameters.as_dict())
        self._rng = jax.random.PRNGKey(FLAGS.seed or 0)
        self._step_fn = None
        self._test_fn = None

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------

    def _build_step(self):
        topo = self.topology
        optimizer = self.optimizer
        n_costs = self._n_costs
        metric_names = list(self.metrics.keys())
        mesh = self.mesh

        # grad stats ride in the same compiled step (TrainerInternal.cpp:
        # 80-110 computes avgAbsGrad/maxAbsGrad in the update callback).
        # captured once at build time: the compiled step and the logging
        # cadence must agree even if the flag changes later
        self._stats_period = int(FLAGS.show_parameter_stats_period or 0)
        stats_on = self._stats_period > 0

        def step(params, opt_state, model_state, rng, feeds):
            def loss_fn(p):
                outs, new_state = topo.forward(p, model_state, feeds,
                                               train=True, rng=rng, mesh=mesh)
                cost_vals = [_reduce_cost(o) for o in outs[:n_costs]]
                total = functools.reduce(jnp.add, cost_vals)
                metric_vals = {name: _metric_scalar(o) for name, o in
                               zip(metric_names, outs[n_costs:])}
                return total, (new_state, metric_vals)

            (loss, (new_mstate, metric_vals)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = optimizer.apply(params, grads, opt_state)
            if stats_on:
                metric_vals = dict(metric_vals)
                metric_vals["__param_stats__"] = {
                    k: (jnp.mean(jnp.abs(g)), jnp.max(jnp.abs(g)))
                    for k, g in grads.items()}
            return loss, new_params, new_opt, new_mstate, metric_vals

        # With mesh-sharded (NamedSharding) inputs, jit partitions the whole
        # step SPMD automatically — XLA inserts the grad psum (the
        # MultiGradientMachine ring / pserver addGradient analog).
        return audit_jit(step, site="trainer.train_step",
                         donate_argnums=(0, 1, 2),
                         xla_contract=self._step_contract())

    def _step_contract(self, donate=(0, 1, 2),
                       test: bool = False) -> SiteContract:
        """Compiled-path contract for the train/test steps, checked by
        the jaxpr auditor: params/opt-state/model-state must actually
        ride the requested donation (verified from the REQUESTED jit
        kwargs, so CPU tier-1 runs still check the TPU contract);
        collectives are the point of a sharded step (grad psum, ZeRO
        reduce-scatter/all-gather); bf16 operands deliberately reduce
        losses/norm statistics in f32 (the repo's precision model, see
        MIGRATION "The bf16 precision model").  The peak-bytes budget
        is a guardrail — activations scale with the batch, which the
        trainer cannot see at build time, so the budget is a generous
        multiple of the weights plus fixed slack, catching only
        duplicated-state-sized regressions.

        Sharding contract (the `analysis sharding` gate): on a mesh,
        feeds shard their batch dim over ``data`` (matching
        ``_shard_feeds``), params/model-state/rng replicate, and under
        ZeRO the flat optimizer slots arrive 1/N-sharded —
        ``expect_sharded`` pins that the plan actually reached them.
        The comm budget covers the worst of the two layouts: a full
        replicated-DP gradient psum (2x param bytes over the ring) or
        ZeRO's reduce-scatter + all-gather pair, with fixed slack for
        the loss/metric scalar reductions."""
        param_bytes = 0
        for v in self.parameters.as_dict().values():
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                n = int(np.prod(v.shape)) if v.shape else 1
                param_bytes += n * jnp.dtype(v.dtype).itemsize
        mesh = self.mesh
        mesh_axes: tuple = ()
        in_specs = None
        expect: tuple = ()
        if mesh is not None:
            mesh_axes = tuple(
                (str(a), int(s))
                for a, s in zip(mesh.axis_names, mesh.devices.shape))
            feed = ("data",) if "data" in mesh.axis_names else ()
            plan = getattr(self, "_zero_plan", None)
            opt = (plan.axis,) if plan is not None else ()
            if test:
                in_specs = ((), (), feed)        # params, mstate, feeds
            else:
                # params, opt_state, model_state, rng, feeds
                in_specs = ((), opt, (), (), feed)
                if plan is not None:
                    expect = (1,)
        return SiteContract(
            donate=tuple(donate), allow_collectives=True,
            allow_upcast=("bfloat16",),
            peak_bytes=16 * param_bytes + (1 << 28),
            in_specs=in_specs, mesh_axes=mesh_axes,
            expect_sharded=expect,
            comm_bytes=6.0 * param_bytes + (1 << 20))

    def _build_test(self):
        topo = self.topology
        n_costs = self._n_costs
        metric_names = list(self.metrics.keys())
        mesh = self.mesh

        def test_step(params, model_state, feeds):
            outs, _ = topo.forward(params, model_state, feeds, train=False,
                                   mesh=mesh)
            cost_vals = [_reduce_cost(o) for o in outs[:n_costs]]
            total = functools.reduce(jnp.add, cost_vals)
            metric_vals = {name: _metric_scalar(o) for name, o in
                           zip(metric_names, outs[n_costs:])}
            return total, metric_vals

        return audit_jit(test_step, site="trainer.test_step",
                         xla_contract=self._step_contract(donate=(),
                                                          test=True))

    def _place_on_mesh(self, slots_too: bool = True) -> None:
        """(Re)commit params — and optimizer state mirroring them — to
        their mesh shardings. Called at init and after ANY checkpoint
        load: load_checkpoint hands back host arrays, and without
        re-placement a resume would replicate 'too big to replicate'
        weights on every device."""
        if self.mesh is None:
            return
        from paddle_tpu.parallel.api import param_sharding

        shardings = param_sharding(self.mesh, self.parameters.as_dict(),
                                   specs=self.topology.param_specs(),
                                   zero_axis=self._zero_axis)
        self.parameters.update_from(
            {k: _put_global(v, shardings[k])
             for k, v in self.parameters.as_dict().items()})
        if not slots_too or not isinstance(self.opt_state, dict):
            return
        plan = getattr(self, "_zero_plan", None)
        if plan is not None:
            # ZeRO: planned params' slots (and avg/prune masks) live as
            # flat 1/N shards; checkpoint loads hand back full-shape host
            # arrays, which shard_state flattens/pads/places. Passthrough
            # params fall to the declared shardings below.
            self.opt_state = plan.shard_state(self.opt_state)

        def _slot_put(k, v):
            if plan is not None and plan.is_sharded(k):
                return v  # already placed by shard_state
            return _put_global(v, shardings[k]) if k in shardings else v

        new_state = dict(self.opt_state)
        for key in ("slots",):
            if key in new_state:
                new_state[key] = {
                    s: {k: _slot_put(k, v) for k, v in d.items()}
                    for s, d in new_state[key].items()}
        for key in ("avg", "prune_masks"):
            if key in new_state:
                new_state[key] = {
                    k: _slot_put(k, v) for k, v in new_state[key].items()}
        self.opt_state = new_state

    def _shard_feeds(self, feeds):
        if self.mesh is None:
            return feeds
        from jax.sharding import NamedSharding, PartitionSpec as P

        # batch shards ONLY over the 'data' axis; on a model-parallel-only
        # mesh feeds replicate (sharding the batch over 'model' would both
        # break on non-divisible trailing batches and force a per-step
        # all-gather against the stage constraints)
        axis = "data" if "data" in self.mesh.axis_names else None
        nproc = jax.process_count()
        out = {}
        for k, v in feeds.items():
            if isinstance(v, SequenceBatch):
                out[k] = v  # ragged feeds stay replicated (see parallel/)
            elif axis is None:
                out[k] = _put_global(v, NamedSharding(self.mesh, P()))
            elif nproc > 1:
                # multi-host DP: each process feeds its LOCAL rows; the
                # global batch is the concatenation over processes (every
                # process must feed the same local batch size — the
                # reference's fixed num_gradient_servers contract)
                sh = NamedSharding(self.mesh,
                                   P(axis, *([None] * (v.ndim - 1))))
                out[k] = jax.make_array_from_process_local_data(
                    sh, np.asarray(v))
            else:
                out[k] = jax.device_put(
                    v, NamedSharding(self.mesh, P(axis, *([None] * (v.ndim - 1)))))
        return out

    # ------------------------------------------------------------------
    # public API (reference: v2 trainer.py)
    # ------------------------------------------------------------------

    def train(self, reader=None, num_passes: int = 1, event_handler=None,
              feeding=None, test_reader=None, save_dir: Optional[str] = None,
              start_pass: int = 0, saving_period: int = 1, master=None,
              record_parser=None, heartbeat_ttl_s: Optional[float] = None,
              prefetch: int = 0) -> None:
        """``save_dir``/``start_pass``/``saving_period`` are the
        --save_dir/--start_pass/--saving_period flags of the reference
        trainer (ParamUtil.h:77-111): checkpoints (params + optimizer
        state) land in save_dir/pass-%05d every ``saving_period`` passes,
        and ``start_pass`` resumes from an existing one if present.

        With ``master=MasterClient(...)`` training is elastic/task-driven
        instead of reader-driven (reference: cloud_reader + etcd
        registration, go/pserver/etcd_client.go:67-166): batches come from
        master tasks (``record_parser`` maps each record's bytes to a
        sample tuple), the lease is heartbeat per batch, and a lapsed
        lease triggers re-register + auto-resume from the latest
        checkpoint in ``save_dir``."""
        if master is not None:
            enforce_that(record_parser is not None,
                         "master= training needs record_parser=",
                         context="trainer")
            enforce_that(start_pass == 0, "start_pass is reader-path only; "
                         "elastic training resumes from save_dir "
                         "automatically", context="trainer")
            return self._train_elastic(master, record_parser, num_passes,
                                       event_handler, feeding, save_dir,
                                       heartbeat_ttl_s, saving_period,
                                       test_reader)
        enforce_that(reader is not None, "train() needs a reader "
                     "(or master=)", context="trainer")
        if event_handler is None:
            event_handler = _default_event_handler
        feeder = self._make_feeder(feeding)
        if self._step_fn is None:
            self._step_fn = self._build_step()

        if save_dir is not None and start_pass > 0:
            import os

            from paddle_tpu import checkpoint as ckpt
            # resume from exactly pass start_pass-1 (newer checkpoints may
            # exist when re-branching; silently training from fresh init
            # would overwrite them with garbage)
            want = start_pass - 1
            enforce_that(os.path.isdir(ckpt.pass_dir(save_dir, want)),
                         f"start_pass={start_pass} but no checkpoint "
                         f"pass-{want:05d} under {save_dir}",
                         context="trainer")
            self.load_checkpoint(save_dir, want)

        params = self.parameters.as_dict()
        opt_state = self.opt_state
        mstate = self.model_state
        log = plog.logger()

        # reference flag semantics (ParamUtil.h): num_passes is the TOTAL
        # pass count; resuming at start_pass runs passes [start_pass,
        # num_passes), not num_passes additional ones
        for pass_id in range(start_pass, num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            # host-side floats; device scalars buffer in `pending` and flush
            # with ONE stacked transfer per stream per log window
            pass_costs: List[float] = []
            pass_metrics: Dict[str, List[float]] = {n: [] for n in self.metrics}
            pending: List = []
            pending_metrics: Dict[str, List] = {n: [] for n in self.metrics}

            def flush():
                if pending:
                    pass_costs.extend(np.asarray(jnp.stack(pending)).tolist())
                    pending.clear()
                for k, buf in pending_metrics.items():
                    if buf:
                        pass_metrics[k].extend(np.asarray(jnp.stack(buf)).tolist())
                        buf.clear()

            if prefetch > 0:
                # device-resident double buffering: feed conversion + the
                # host->device transfer of batch k+1 overlap batch k's
                # compute (the async DataProvider pool analog)
                from paddle_tpu.reader.prefetch import device_prefetch

                feed_it = device_prefetch(
                    reader(), size=prefetch, transform=feeder.feed,
                    place=self._shard_feeds if self.mesh is not None
                    else None)
            else:
                feed_it = (self._shard_feeds(feeder.feed(b))
                           for b in reader())
            for batch_id, feeds in enumerate(feed_it):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                self._rng, key = jax.random.split(self._rng)
                with stats.timer("trainOneBatch"):
                    loss, params, opt_state, mstate, metric_vals = self._step_fn(
                        params, opt_state, mstate, key, feeds)
                pstats = metric_vals.pop("__param_stats__", None)
                period = getattr(self, "_stats_period", 0)
                if pstats is not None and period > 0 \
                        and (batch_id + 1) % period == 0:
                    for k in sorted(pstats):
                        avg_abs, max_abs = pstats[k]
                        log.info("Param %s avgAbsGrad=%.6g maxAbsGrad=%.6g",
                                 k, float(avg_abs), float(max_abs))
                # no host sync per batch (the device round-trip costs more
                # than the step); events convert lazily via properties
                pending.append(loss)
                for k, v in metric_vals.items():
                    pending_metrics[k].append(v)
                event_handler(v2_event.EndIteration(pass_id, batch_id, loss,
                                                    metric_vals))
                if FLAGS.log_period and (batch_id + 1) % FLAGS.log_period == 0:
                    flush()
                    mtxt = " ".join(f"{k}={np.mean(v[-FLAGS.log_period:]):.5f}"
                                    for k, v in pass_metrics.items())
                    log.info("Pass %d, Batch %d, Cost %.5f %s", pass_id,
                             batch_id, np.mean(pass_costs[-FLAGS.log_period:]), mtxt)
            # pass end: sync back, fire event (with test if reader given)
            flush()
            self.parameters.update_from(params)
            self.opt_state = opt_state
            self.model_state = mstate
            result_metrics = {k: float(np.mean(v)) if v else 0.0
                              for k, v in pass_metrics.items()}
            if test_reader is not None:
                tr = self.test(test_reader, feeding)
                event_handler(v2_event.EndPass(pass_id, tr.metrics, self.parameters))
            else:
                event_handler(v2_event.EndPass(pass_id, result_metrics, self.parameters))
            if save_dir is not None and (pass_id + 1) % saving_period == 0:
                self.save_checkpoint(save_dir, pass_id)
            # scrape surface for the per-batch timers: publish the
            # StatSet into the obs registry each pass instead of ad-hoc
            # report() prints — training timings land next to serving
            # metrics on ONE export (obs.default_registry().to_text()).
            # Wrap event_handler with obs.trainer_event_bridge(tracer)
            # to additionally put every pass/iteration on a trace
            # timeline.
            stats.timer_stats().publish(default_registry(),
                                        prefix="trainer_")

        self.parameters.update_from(params)
        self.opt_state = opt_state
        self.model_state = mstate

    def _train_elastic(self, master, record_parser, num_passes: int,
                       event_handler, feeding, save_dir: Optional[str],
                       ttl_s: Optional[float], saving_period: int,
                       test_reader) -> None:
        """Task-driven elastic training (the kill/resume e2e productized).

        One SGD step per master task; the step counter (== applied task
        count along this trainer lineage) drives the rng stream and is
        persisted in checkpoint meta, so a replacement trainer resumes
        the SAME stream — final params equal an uninterrupted run (the
        test_TrainerOnePass determinism bar extended to the crash path;
        single-lineage guarantee — with several concurrent trainers a
        requeued task may be re-run by a peer, the reference's async
        tolerance).

        Ack protocol: tasks are acked ONLY after a checkpoint covering
        them is durable (``saving_period`` = tasks per checkpoint; every
        task when save_dir is unset). The checkpoint meta records the
        covered-but-possibly-unacked (task_id, epoch) set plus the
        in-progress pass and next rng step, so a crash in ANY window —
        before the step, or after the checkpoint but before the acks —
        resumes without losing or double-applying a task. Old
        checkpoints are pruned (crash-resume only needs the latest; the
        previous one is kept as insurance while the newest is young).
        """
        import time as _time

        from paddle_tpu import checkpoint as ckpt

        if event_handler is None:
            event_handler = _default_event_handler
        feeder = self._make_feeder(feeding)
        if self._step_fn is None:
            self._step_fn = self._build_step()
        log = plog.logger()
        saving_period = max(1, int(saving_period))

        def resume_state():
            """-> (next_step, skip_set, pass_id, next_ckpt_id)."""
            latest = ckpt.latest_pass(save_dir) if save_dir else None
            if latest is None:
                return 0, set(), 0, 0
            p, opt, mst, meta = ckpt.load_checkpoint(save_dir)
            self.parameters.update_from(p.as_dict())
            if opt is not None:
                self.opt_state = opt
            if mst is not None:
                self.model_state = mst
            self._place_on_mesh()
            log.info("elastic: resumed from checkpoint %d (pass %d, "
                     "next step %d)", latest, meta.get("pass_id", 0),
                     meta.get("next_step", latest + 1))
            skip = {(tid, meta.get("epoch", 0))
                    for tid in meta.get("task_ids", [])}
            return (meta.get("next_step", latest + 1), skip,
                    meta.get("pass_id", 0), latest + 1)

        if getattr(master, "_slot", None) is None:
            master.register(ttl_s=ttl_s)
        step, skip_set, pass_id, ck_id = resume_state()

        params = self.parameters.as_dict()
        opt_state = self.opt_state
        mstate = self.model_state
        unacked: List[int] = []

        def sync_back():
            self.parameters.update_from(params)
            self.opt_state = opt_state
            self.model_state = mstate

        def flush(meta_pass: int, epoch: int) -> None:
            """Checkpoint the current state, then ack everything the
            checkpoint covers. Ack strictly AFTER the write: the reverse
            order could lose acked-but-not-durable updates."""
            nonlocal ck_id
            if save_dir is not None:
                sync_back()
                ckpt.save_checkpoint(
                    save_dir, ck_id, self.parameters,
                    opt_state=self.opt_state, model_state=self.model_state,
                    extra_meta={"next_step": step, "pass_id": meta_pass,
                                "epoch": epoch, "task_ids": list(unacked)},
                    shard_plan=self._zero_plan)
                ckpt.prune_checkpoints(save_dir, keep=2)
                ck_id += 1
            for tid in unacked:
                master.ack_task(tid)
            unacked.clear()

        while pass_id < num_passes:
            master.begin_pass()
            event_handler(v2_event.BeginPass(pass_id))
            pending_costs: List = []
            batch_id = 0
            epoch = 0
            rejoined = False
            resumed_acks = False
            while True:
                if not master.heartbeat(ttl_s=ttl_s):
                    # declared dead (long GC/preemption): durable state is
                    # required to rejoin — silently restarting the rng
                    # stream from scratch would corrupt training
                    enforce_that(save_dir is not None,
                                 "elastic lease lost with no save_dir: "
                                 "cannot resume; pass save_dir= to "
                                 "train(master=...)", context="trainer")
                    log.info("elastic: lease lost, re-registering")
                    master.register(ttl_s=ttl_s)
                    unacked.clear()
                    step, skip_set, pass_id, ck_id = resume_state()
                    params = self.parameters.as_dict()
                    opt_state = self.opt_state
                    mstate = self.model_state
                    rejoined = True
                    break
                status, got = master.try_next_task()
                if status == "done":
                    if resumed_acks and batch_id == 0:
                        # the only thing this pass did was ack stale tasks
                        # from the PREVIOUS pass (crash at a pass
                        # boundary): the queue just drained, so recycle it
                        # and actually train this pass
                        master.begin_pass()
                        resumed_acks = False
                        continue
                    break
                if status == "empty":
                    # possibly blocked on our own unacked tasks: flush
                    if unacked:
                        flush(pass_id, epoch)
                    else:
                        master.poll_wait()   # jittered backoff, not a
                    continue                 # fixed-interval hammer
                task_id, epoch, records = got
                master.poll_reset()
                if skip_set:
                    if (task_id, epoch) in skip_set:
                        # already applied inside the restored checkpoint
                        # (crash hit between write and ack): ack, skip
                        skip_set.discard((task_id, epoch))
                        log.info("elastic: task %d already in checkpoint, "
                                 "skipping", task_id)
                        master.ack_task(task_id)
                        resumed_acks = True
                        continue
                    # requeued tasks come back FIRST; a non-match means
                    # the remaining skip entries are stale
                    skip_set.clear()
                batch = [record_parser(r) for r in records]
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                feeds = self._shard_feeds(feeder.feed(batch))
                with stats.timer("trainOneBatch"):
                    loss, params, opt_state, mstate, metric_vals = \
                        self._step_fn(params, opt_state, mstate,
                                      jax.random.PRNGKey(step), feeds)
                metric_vals.pop("__param_stats__", None)
                step += 1
                unacked.append(task_id)
                if len(unacked) >= saving_period:
                    flush(pass_id, epoch)
                batch_id += 1
                pending_costs.append(loss)  # device scalar, no sync
                event_handler(v2_event.EndIteration(pass_id, batch_id - 1,
                                                    loss, metric_vals))
                if FLAGS.log_period and batch_id % FLAGS.log_period == 0:
                    window = pending_costs[-FLAGS.log_period:]
                    log.info("Elastic pass %d, Batch %d, Cost %.5f", pass_id,
                             batch_id - 1,
                             float(np.mean(np.asarray(jnp.stack(window)))))
            if rejoined:
                continue  # restart the (possibly different) resumed pass
            # pass complete: flush leftovers, mark the NEXT pass durable so
            # a crash right here doesn't re-run this pass on resume
            pass_id += 1
            flush(pass_id, epoch)
            sync_back()
            # same registry publish as the reader path: elastic passes
            # expose their trainOneBatch timings through obs too
            stats.timer_stats().publish(default_registry(),
                                        prefix="trainer_")
            if test_reader is not None:
                tr = self.test(test_reader, feeding)
                event_handler(v2_event.EndPass(pass_id - 1, tr.metrics,
                                               self.parameters))
            else:
                event_handler(v2_event.EndPass(pass_id - 1, {},
                                               self.parameters))
        sync_back()

    def test(self, reader, feeding=None) -> v2_event.TestResult:
        feeder = self._make_feeder(feeding)
        if self._test_fn is None:
            self._test_fn = self._build_test()
        params = self.parameters.as_dict()
        costs: List[float] = []
        metrics: Dict[str, List[float]] = {n: [] for n in self.metrics}
        for data_batch in reader():
            feeds = feeder.feed(data_batch)
            loss, metric_vals = self._test_fn(params, self.model_state, feeds)
            costs.append(float(loss))
            for k, v in metric_vals.items():
                metrics[k].append(float(v))
        result = {k: float(np.mean(v)) if v else 0.0 for k, v in metrics.items()}
        return v2_event.TestResult(float(np.mean(costs)) if costs else 0.0, result)

    # ------------------------------------------------------------------

    def _make_feeder(self, feeding) -> DataFeeder:
        data_types = [(n.name, n.input_type) for n in self.topology.data_nodes]
        return DataFeeder(data_types, feeding)

    def save_parameter_to_tar(self, f) -> None:
        self.parameters.to_tar(f)

    # ------------------------------------------------------------------
    # checkpoint/resume incl. optimizer state (ParamUtil + go/pserver
    # checkpoint analogs — see paddle_tpu/checkpoint.py)
    # ------------------------------------------------------------------

    def save_checkpoint(self, root: str, pass_id: int) -> str:
        from paddle_tpu import checkpoint as ckpt
        return ckpt.save_checkpoint(root, pass_id, self.parameters,
                                    opt_state=self.opt_state,
                                    model_state=self.model_state,
                                    shard_plan=self._zero_plan)

    def load_checkpoint(self, root: str, pass_id: Optional[int] = None) -> None:
        from paddle_tpu import checkpoint as ckpt
        self.apply_checkpoint(ckpt.load_checkpoint(root, pass_id))

    def apply_checkpoint(self, loaded) -> None:
        """Apply an already-read ``checkpoint.load_checkpoint`` result.

        Split from :meth:`load_checkpoint` so callers can separate disk-read
        failures (missing/corrupt artifact) from apply failures (shape or
        mesh-placement bugs that deserve a traceback)."""
        params, opt_state, model_state, meta = loaded
        self.parameters.update_from(params.as_dict())
        if opt_state is not None:
            self.opt_state = opt_state
        if model_state is not None:
            self.model_state = model_state
        self._place_on_mesh()


def _put_global(v, sharding) -> jax.Array:
    """Multi-process-safe placement — see parallel.api.put_global."""
    from paddle_tpu.parallel.api import put_global

    return put_global(v, sharding)


def _default_event_handler(ev) -> None:
    pass


# ---------------------------------------------------------------------------
# Multi-task / alternating training (the GAN capability)
# ---------------------------------------------------------------------------


class TaskSpec:
    """One optimization task: a cost node, its optimizer, and a predicate
    naming which parameters it updates (v1_api_demo/gan/gan_trainer.py
    analog — two networks, alternating training)."""

    def __init__(self, name: str, cost, update_equation: Optimizer,
                 trainable=None):
        self.name = name
        self.cost = cost
        self.optimizer = update_equation
        if trainable is None:
            self.trainable = lambda pname: True
        elif isinstance(trainable, str):
            prefix = trainable
            self.trainable = lambda pname: pname.startswith(prefix)
        elif isinstance(trainable, (list, tuple, set, frozenset)):
            names = set(trainable)
            self.trainable = lambda pname: pname in names
        else:
            self.trainable = trainable


class MultiTaskTrainer:
    """Alternating training of several cost graphs over ONE shared
    parameter store — the reference's GAN loop (gan_trainer.py: generator
    and discriminator configs trained alternately against shared
    parameters) without its separate GradientMachines: each task is its
    own jitted step that masks gradients to its parameter subset.

    Usage::

        t = MultiTaskTrainer([
            TaskSpec("d", d_cost, Adam(2e-4), trainable="dis_"),
            TaskSpec("g", g_cost, Adam(2e-4), trainable="gen_"),
        ], parameters)
        d_loss = t.step("d", {"pixel": real, "noise": z})
        g_loss = t.step("g", {"noise": z})
    """

    def __init__(self, tasks: Sequence[TaskSpec], parameters: Parameters,
                 mesh=None):
        enforce_that(len(tasks) > 0, "need at least one task",
                     context="MultiTaskTrainer")
        self.tasks = {t.name: t for t in tasks}
        self.parameters = parameters
        self.mesh = mesh
        self._topos: Dict[str, Topology] = {}
        self._opt_states: Dict[str, Any] = {}
        self._model_states: Dict[str, Any] = {}
        self._step_fns: Dict[str, Any] = {}
        self._rng = jax.random.PRNGKey(FLAGS.seed or 0)
        self._counts: Dict[str, int] = {}
        for t in tasks:
            topo = Topology([t.cost])
            self._topos[t.name] = topo
            t.optimizer.set_param_specs(topo.param_specs())
            subset = {k: v for k, v in parameters.as_dict().items()
                      if t.trainable(k)}
            enforce_that(len(subset) > 0,
                         f"task {t.name!r} trains no parameters",
                         context="MultiTaskTrainer")
            self._opt_states[t.name] = t.optimizer.init_state(subset)
            self._model_states[t.name] = topo.init_state()
            self._counts[t.name] = 0

    def _build(self, name: str):
        task = self.tasks[name]
        topo = self._topos[name]
        optimizer = task.optimizer
        trainable = task.trainable
        mesh = self.mesh

        def step(params, opt_state, model_state, rng, feeds):
            def loss_fn(p):
                outs, new_state = topo.forward(p, model_state, feeds,
                                               train=True, rng=rng, mesh=mesh)
                return _reduce_cost(outs[0]), new_state

            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            sub_p = {k: v for k, v in params.items() if trainable(k)}
            sub_g = {k: grads[k] for k in sub_p}
            new_sub, new_opt = optimizer.apply(sub_p, sub_g, opt_state)
            new_params = dict(params)
            new_params.update(new_sub)
            return loss, new_params, new_opt, new_mstate

        # only the task's opt-state is donated (params fan into every
        # task's graph, so the caller keeps them); same collective /
        # f32-reduction allowances as the SGD step
        return audit_jit(step, site=f"trainer.task.{name}",
                         donate_argnums=(1,),
                         xla_contract=SiteContract(
                             donate=(1,), allow_collectives=True,
                             allow_upcast=("bfloat16",)))

    def step(self, name: str, feeds: Dict[str, Any]) -> float:
        """Run one optimization step of the named task; other tasks'
        parameters flow through the graph but are not updated."""
        enforce_that(name in self.tasks, f"unknown task {name!r}",
                     context="MultiTaskTrainer")
        fn = self._step_fns.get(name)
        if fn is None:
            fn = self._step_fns[name] = self._build(name)
        self._rng, sub = jax.random.split(self._rng)
        loss, new_params, new_opt, new_mstate = fn(
            self.parameters.as_dict(), self._opt_states[name],
            self._model_states[name], sub, feeds)
        self.parameters.update_from(new_params)
        self._opt_states[name] = new_opt
        self._model_states[name] = new_mstate
        # stateful slots (batch-norm stats) shared across task graphs by
        # node name: propagate updates into the other tasks' state maps
        for other, st in self._model_states.items():
            if other != name:
                for node_name, slots in new_mstate.items():
                    if node_name in st:
                        st[node_name] = slots
        self._counts[name] += 1
        return float(loss)

    def steps_run(self, name: str) -> int:
        return self._counts[name]
