"""SGD trainer with events — the paddle.v2.trainer analog.

Reference: python/paddle/v2/trainer.py:124-202 (SGD.train event loop over a
reader), paddle/trainer/TrainerInternal.cpp:66-158 (per-batch
forwardBackward + update + stats), Tester.cpp.

TPU-native: one jitted ``train_step`` fuses forward+backward+optimizer into a
single XLA program (the reference pays a python→SWIG→C++ transition and one
kernel launch per layer per batch; here the whole step is one device
execution with buffer donation). Gradients come from ``jax.grad`` — there is
no hand-written backward graph. Data parallelism: pass ``mesh=`` and dense
feeds are sharded over the 'data' axis; XLA inserts the psum (the
MultiGradientMachine ring / pserver addGradient analog).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import event as v2_event
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.optimizer import Optimizer
from paddle_tpu.parameters import Parameters
from paddle_tpu.platform import plog, stats
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import LayerOutput, Topology


def _reduce_cost(value) -> jax.Array:
    """Total cost over the batch / num examples (reference divides summed cost
    by batch size, TrainerInternal.cpp trainOneBatch)."""
    if isinstance(value, SequenceBatch):
        total = jnp.sum(jnp.where(value.valid_mask, value.data.reshape(value.capacity, -1).sum(-1)
                                  if value.data.ndim > 1 else value.data, 0.0))
        return total / jnp.maximum(value.num_seqs, 1)
    return jnp.mean(value)


def _metric_scalar(value) -> jax.Array:
    """Mean of a metric layer's output over valid examples/tokens."""
    if isinstance(value, SequenceBatch):
        d = value.data.reshape(value.capacity, -1).sum(-1) if value.data.ndim > 1 else value.data
        total = jnp.sum(jnp.where(value.valid_mask, d, 0.0))
        count = jnp.sum(value.valid_mask)
        return total / jnp.maximum(count, 1)
    return jnp.mean(value)


class SGD:
    """v2-compatible trainer: SGD(cost, parameters, update_equation).train(...).

    ``metrics`` maps display names to metric LayerOutputs (the evaluator
    analog — see paddle_tpu.evaluator); they are computed in-graph per batch
    and averaged across the pass for EndPass events.
    """

    def __init__(self, cost, parameters: Parameters, update_equation: Optimizer,
                 extra_layers: Optional[Sequence[LayerOutput]] = None,
                 is_local: bool = True, mesh=None,
                 metrics: Optional[Dict[str, LayerOutput]] = None):
        costs = [cost] if isinstance(cost, LayerOutput) else list(cost)
        self.metrics = dict(metrics or {})
        # auto-collect evaluator nodes passed via extra_layers
        for n in (extra_layers or []):
            self.metrics.setdefault(n.name, n)
        outputs = costs + list(self.metrics.values())
        self.topology = Topology(outputs)
        self._n_costs = len(costs)
        self.parameters = parameters
        self.optimizer = update_equation
        self.optimizer.set_param_specs(self.topology.param_specs())
        self.model_state = self.topology.init_state()
        self.opt_state = self.optimizer.init_state(parameters.as_dict())
        self.mesh = mesh
        self._rng = jax.random.PRNGKey(FLAGS.seed or 0)
        self._step_fn = None
        self._test_fn = None

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------

    def _build_step(self):
        topo = self.topology
        optimizer = self.optimizer
        n_costs = self._n_costs
        metric_names = list(self.metrics.keys())

        def step(params, opt_state, model_state, rng, feeds):
            def loss_fn(p):
                outs, new_state = topo.forward(p, model_state, feeds,
                                               train=True, rng=rng)
                cost_vals = [_reduce_cost(o) for o in outs[:n_costs]]
                total = functools.reduce(jnp.add, cost_vals)
                metric_vals = {name: _metric_scalar(o) for name, o in
                               zip(metric_names, outs[n_costs:])}
                return total, (new_state, metric_vals)

            (loss, (new_mstate, metric_vals)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            new_params, new_opt = optimizer.apply(params, grads, opt_state)
            return loss, new_params, new_opt, new_mstate, metric_vals

        # With mesh-sharded (NamedSharding) inputs, jit partitions the whole
        # step SPMD automatically — XLA inserts the grad psum (the
        # MultiGradientMachine ring / pserver addGradient analog).
        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _build_test(self):
        topo = self.topology
        n_costs = self._n_costs
        metric_names = list(self.metrics.keys())

        def test_step(params, model_state, feeds):
            outs, _ = topo.forward(params, model_state, feeds, train=False)
            cost_vals = [_reduce_cost(o) for o in outs[:n_costs]]
            total = functools.reduce(jnp.add, cost_vals)
            metric_vals = {name: _metric_scalar(o) for name, o in
                           zip(metric_names, outs[n_costs:])}
            return total, metric_vals

        return jax.jit(test_step)

    def _shard_feeds(self, feeds):
        if self.mesh is None:
            return feeds
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = self.mesh.axis_names[0]
        out = {}
        for k, v in feeds.items():
            if isinstance(v, SequenceBatch):
                out[k] = v  # ragged feeds stay replicated (see parallel/)
            else:
                out[k] = jax.device_put(
                    v, NamedSharding(self.mesh, P(axis, *([None] * (v.ndim - 1)))))
        return out

    # ------------------------------------------------------------------
    # public API (reference: v2 trainer.py)
    # ------------------------------------------------------------------

    def train(self, reader, num_passes: int = 1, event_handler=None,
              feeding=None, test_reader=None) -> None:
        if event_handler is None:
            event_handler = _default_event_handler
        feeder = self._make_feeder(feeding)
        if self._step_fn is None:
            self._step_fn = self._build_step()

        params = self.parameters.as_dict()
        opt_state = self.opt_state
        mstate = self.model_state
        log = plog.logger()

        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            # host-side floats; device scalars buffer in `pending` and flush
            # with ONE stacked transfer per stream per log window
            pass_costs: List[float] = []
            pass_metrics: Dict[str, List[float]] = {n: [] for n in self.metrics}
            pending: List = []
            pending_metrics: Dict[str, List] = {n: [] for n in self.metrics}

            def flush():
                if pending:
                    pass_costs.extend(np.asarray(jnp.stack(pending)).tolist())
                    pending.clear()
                for k, buf in pending_metrics.items():
                    if buf:
                        pass_metrics[k].extend(np.asarray(jnp.stack(buf)).tolist())
                        buf.clear()

            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                feeds = self._shard_feeds(feeder.feed(data_batch))
                self._rng, key = jax.random.split(self._rng)
                with stats.timer("trainOneBatch"):
                    loss, params, opt_state, mstate, metric_vals = self._step_fn(
                        params, opt_state, mstate, key, feeds)
                # no host sync per batch (the device round-trip costs more
                # than the step); events convert lazily via properties
                pending.append(loss)
                for k, v in metric_vals.items():
                    pending_metrics[k].append(v)
                event_handler(v2_event.EndIteration(pass_id, batch_id, loss,
                                                    metric_vals))
                if FLAGS.log_period and (batch_id + 1) % FLAGS.log_period == 0:
                    flush()
                    mtxt = " ".join(f"{k}={np.mean(v[-FLAGS.log_period:]):.5f}"
                                    for k, v in pass_metrics.items())
                    log.info("Pass %d, Batch %d, Cost %.5f %s", pass_id,
                             batch_id, np.mean(pass_costs[-FLAGS.log_period:]), mtxt)
            # pass end: sync back, fire event (with test if reader given)
            flush()
            self.parameters.update_from(params)
            self.opt_state = opt_state
            self.model_state = mstate
            result_metrics = {k: float(np.mean(v)) if v else 0.0
                              for k, v in pass_metrics.items()}
            if test_reader is not None:
                tr = self.test(test_reader, feeding)
                event_handler(v2_event.EndPass(pass_id, tr.metrics, self.parameters))
            else:
                event_handler(v2_event.EndPass(pass_id, result_metrics, self.parameters))

        self.parameters.update_from(params)
        self.opt_state = opt_state
        self.model_state = mstate

    def test(self, reader, feeding=None) -> v2_event.TestResult:
        feeder = self._make_feeder(feeding)
        if self._test_fn is None:
            self._test_fn = self._build_test()
        params = self.parameters.as_dict()
        costs: List[float] = []
        metrics: Dict[str, List[float]] = {n: [] for n in self.metrics}
        for data_batch in reader():
            feeds = feeder.feed(data_batch)
            loss, metric_vals = self._test_fn(params, self.model_state, feeds)
            costs.append(float(loss))
            for k, v in metric_vals.items():
                metrics[k].append(float(v))
        result = {k: float(np.mean(v)) if v else 0.0 for k, v in metrics.items()}
        return v2_event.TestResult(float(np.mean(costs)) if costs else 0.0, result)

    # ------------------------------------------------------------------

    def _make_feeder(self, feeding) -> DataFeeder:
        data_types = [(n.name, n.input_type) for n in self.topology.data_nodes]
        return DataFeeder(data_types, feeding)

    def save_parameter_to_tar(self, f) -> None:
        self.parameters.to_tar(f)


def _default_event_handler(ev) -> None:
    pass
