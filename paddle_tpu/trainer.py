"""SGD trainer with events — the paddle.v2.trainer analog.

Reference: python/paddle/v2/trainer.py:124-202 (SGD.train event loop over a
reader), paddle/trainer/TrainerInternal.cpp:66-158 (per-batch
forwardBackward + update + stats), Tester.cpp.

TPU-native: one jitted ``train_step`` fuses forward+backward+optimizer into a
single XLA program (the reference pays a python→SWIG→C++ transition and one
kernel launch per layer per batch; here the whole step is one device
execution with buffer donation). Gradients come from ``jax.grad`` — there is
no hand-written backward graph. Data parallelism: pass ``mesh=`` and dense
feeds are sharded over the 'data' axis; XLA inserts the psum (the
MultiGradientMachine ring / pserver addGradient analog).
"""

from __future__ import annotations

import functools
import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import event as v2_event
from paddle_tpu.analysis.retrace import SiteContract, audit_jit
from paddle_tpu.obs.registry import default_registry
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.optimizer import Optimizer
from paddle_tpu.parameters import Parameters
from paddle_tpu.platform import plog, stats
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import LayerOutput, Topology


def _reduce_cost(value) -> jax.Array:
    """Total cost over the batch / num examples (reference divides summed cost
    by batch size, TrainerInternal.cpp trainOneBatch)."""
    if isinstance(value, SequenceBatch):
        total = jnp.sum(jnp.where(value.valid_mask, value.data.reshape(value.capacity, -1).sum(-1)
                                  if value.data.ndim > 1 else value.data, 0.0))
        return total / jnp.maximum(value.num_seqs, 1)
    return jnp.mean(value)


def _metric_scalar(value) -> jax.Array:
    """Mean of a metric layer's output over valid examples/tokens."""
    if isinstance(value, SequenceBatch):
        d = value.data.reshape(value.capacity, -1).sum(-1) if value.data.ndim > 1 else value.data
        total = jnp.sum(jnp.where(value.valid_mask, d, 0.0))
        count = jnp.sum(value.valid_mask)
        return total / jnp.maximum(count, 1)
    return jnp.mean(value)


class SGD:
    """v2-compatible trainer: SGD(cost, parameters, update_equation).train(...).

    ``metrics`` maps display names to metric LayerOutputs (the evaluator
    analog — see paddle_tpu.evaluator); they are computed in-graph per batch
    and averaged across the pass for EndPass events.
    """

    def __init__(self, cost, parameters: Parameters, update_equation: Optimizer,
                 extra_layers: Optional[Sequence[LayerOutput]] = None,
                 is_local: bool = True, mesh=None,
                 metrics: Optional[Dict[str, LayerOutput]] = None,
                 zero_axis: Optional[str] = None,
                 zero: Optional[int] = None,
                 pipeline=None,
                 faults=None, guard=None, tracer=None):
        costs = [cost] if isinstance(cost, LayerOutput) else list(cost)
        self.metrics = dict(metrics or {})
        # auto-collect evaluator nodes passed via extra_layers
        for n in (extra_layers or []):
            self.metrics.setdefault(n.name, n)
        outputs = costs + list(self.metrics.values())
        self.topology = Topology(outputs)
        self._n_costs = len(costs)
        self.parameters = parameters
        self.optimizer = update_equation
        self.optimizer.set_param_specs(self.topology.param_specs())
        self.model_state = self.topology.init_state()
        # pipeline-parallel training (pipeline=PipelineConfig): repack the
        # transformer body into stacked [L, ...] stage weights, build (or
        # validate) a (data, stage) mesh, and swap the compiled step for
        # the GPipe fill+drain schedule (parallel/pipeline.py). Placement
        # composes through ONE plan: stage weights shard their stacked
        # layer dim over 'stage' (placement.pipeline_param_attrs), and the
        # replicated remainder (embeddings, head) still ZeRO-shards its
        # optimizer state over 'data' when zero=1.
        self._pipeline = None
        self._pipe_specs: Dict[str, Any] = {}
        if pipeline is not None:
            mesh = self._setup_pipeline(pipeline, mesh)
        self.mesh = mesh
        self._zero_axis = zero_axis
        # commit params to their declared shardings (ParamAttr.sharding;
        # replicated by default, ZeRO-style largest-dim sharding with
        # zero_axis=) BEFORE optimizer slots are created: zeros_like slots
        # then inherit the committed shardings, so no device ever
        # materializes a full slot replica of a sharded weight
        self._place_on_mesh(slots_too=False)
        # ZeRO-1 (zero= arg, default FLAGS.zero_stage): shard optimizer
        # state 1/N over the 'data' axis while params stay replicated —
        # the plan threads through init_state so slots are sharded from
        # step 0, and through apply for the per-step reduce-scatter /
        # all-gather pair (parallel/zero.py)
        self._zero_plan = None
        stage = int(FLAGS.zero_stage if zero is None else zero)
        if stage:
            enforce_that(stage == 1, f"zero_stage={stage} not implemented "
                         "(0 = off, 1 = optimizer-state sharding)",
                         context="trainer")
            usable = mesh is not None and "data" in mesh.axis_names
            # an EXPLICIT zero= request that cannot take effect is an
            # error (silently training replicated would fake the N x
            # memory claim); the process-wide FLAGS.zero_stage stays
            # permissive so single-device tools keep working
            enforce_that(usable or zero is None,
                         "zero=1 needs mesh= with a 'data' axis (got "
                         + ("no mesh" if mesh is None else
                            f"axes {tuple(mesh.axis_names)}") + ")",
                         context="trainer")
            if usable:
                from paddle_tpu.parallel.zero import build_zero_plan

                # merged specs: pipeline stage weights carry explicit
                # stage sharding and are therefore EXCLUDED from ZeRO —
                # "the ZeRO-sharded remainder" resolves through the same
                # placement plan as everything else
                self._zero_plan = build_zero_plan(
                    mesh, parameters.as_dict(),
                    specs=self._param_specs(),
                    zero_axis=self._zero_axis)
        # unconditional (including None): a reused optimizer instance must
        # not carry a previous trainer's plan into this one
        self.optimizer.set_zero_plan(self._zero_plan)
        self.opt_state = self.optimizer.init_state(parameters.as_dict())
        self._rng = jax.random.PRNGKey(FLAGS.seed or 0)
        self._step_fn = None
        self._test_fn = None
        # fault-tolerant runtime (paddle_tpu.resilience): a seedable
        # TrainFaultPlan drives injected deaths/NaNs/slow steps, the
        # BadStepGuard fuses the skip-or-rollback policy into the jitted
        # step, and the tracer puts guard/checkpoint edges on the obs
        # timeline.  guard=None falls back to FLAGS.train_bad_step_policy
        # ("off" by default, so the unguarded step signature — and every
        # existing compiled program — is unchanged).
        self._faults = faults
        if guard is None:
            policy = str(FLAGS.train_bad_step_policy or "off")
            if policy != "off":
                from paddle_tpu.resilience.guard import BadStepGuard

                guard = BadStepGuard(
                    policy=policy,
                    max_norm=float(FLAGS.train_bad_step_max_norm),
                    rollback_after=int(FLAGS.train_bad_step_window))
        if faults is not None and faults.injects_grads():
            enforce_that(guard is not None,
                         "TrainFaultPlan injects non-finite gradients "
                         "but no bad-step guard is set — pass "
                         "SGD(guard=BadStepGuard()) (or set "
                         "FLAGS.train_bad_step_policy) so the poison "
                         "is screened instead of corrupting optimizer "
                         "slots", context="trainer")
        self._guard = guard
        if tracer is None:
            from paddle_tpu.obs.trace import NULL_TRACER

            tracer = NULL_TRACER
        self._tracer = tracer
        self._global_step = 0
        self._bad_steps_seen = 0   # per-train()-call device-counter mark
        self.bad_steps_total = 0   # lifetime skipped-step count
        self._async_ckpt = None

    # ------------------------------------------------------------------
    # pipeline parallelism (4D composition: stage x data/zero [x model])
    # ------------------------------------------------------------------

    def _param_specs(self):
        """Topology specs merged with the pipeline placement plan — the
        ONE spec dict both ``param_sharding`` and ``build_zero_plan``
        consume, so stacked stage weights (leading-dim 'stage'), stacked
        expert weights, TP-sharded weights and the ZeRO-sharded
        remainder all resolve through the same placement layer
        (parallel/placement.py)."""
        specs = dict(self.topology.param_specs())
        specs.update(self._pipe_specs)
        return specs

    def _setup_pipeline(self, cfg, mesh):
        """Resolve the pipeline geometry, build/validate the (data,
        stage) mesh, and repack the transformer body ``blk{i}_*`` params
        into stacked ``pipe_body.*`` [L, ...] stage weights.

        The stacked layout is LAYOUT-INDEPENDENT: checkpoints carry the
        full [L, ...] stack (gather-on-save), which reloads into any
        stage count dividing L (scatter-on-load happens in
        ``_place_on_mesh``) — the cross-layout resume contract."""
        import re

        from paddle_tpu.parallel import placement
        from paddle_tpu.parallel.pipeline import PipelineConfig

        enforce_that(isinstance(cfg, PipelineConfig),
                     "pipeline= takes a parallel.PipelineConfig, got "
                     f"{type(cfg).__name__}", context="trainer")
        enforce_that(not self.metrics and self._n_costs == 1,
                     "pipeline= supports a single cost and no metric "
                     "layers (the loss rides the last-stage boundary "
                     "hook, not topology.forward)", context="trainer")
        axis = str(cfg.axis)
        pat = re.compile(r"^blk(\d+)_(.+)$")
        groups: Dict[str, Dict[int, str]] = {}
        for name in self.parameters.names():
            mt = pat.match(name)
            if mt:
                groups.setdefault(mt.group(2), {})[int(mt.group(1))] = name
        enforce_that(bool(groups),
                     "pipeline= found no blk{i}_* body parameters — the "
                     "pipeline trainer partitions the model-zoo "
                     "transformer naming convention "
                     "(models/transformer.build)", context="trainer")
        n_layers = int(cfg.n_layers) or (
            max(i for d in groups.values() for i in d) + 1)
        for suffix, d in groups.items():
            enforce_that(sorted(d) == list(range(n_layers)),
                         f"blk*_{suffix} layer ids {sorted(d)} do not "
                         f"cover 0..{n_layers - 1}", context="trainer")
        # stage count: config > flag > the mesh's stage axis > all devices
        s = int(cfg.num_stages) or int(FLAGS.pipeline_stages)
        if not s:
            s = (int(mesh.shape[axis])
                 if mesh is not None and axis in mesh.axis_names
                 else jax.device_count())
        m = int(cfg.microbatches) or int(FLAGS.pipeline_microbatches)
        enforce_that(m >= 1, f"pipeline_microbatches={m} must be >= 1",
                     context="trainer")
        enforce_that(n_layers % s == 0,
                     f"n_layers={n_layers} does not divide into "
                     f"num_stages={s}", context="trainer")
        if mesh is None:
            from paddle_tpu.parallel.mesh import make_mesh

            ndev = jax.device_count()
            enforce_that(ndev % s == 0,
                         f"{ndev} devices do not divide into "
                         f"num_stages={s}", context="trainer")
            # the (data, stage) mesh: 'data' is the ZeRO/optimizer-state
            # sharding domain (feeds stay replicated — SequenceBatch)
            mesh = make_mesh((ndev // s, s), ("data", axis))
        enforce_that(axis in mesh.axis_names
                     and int(mesh.shape[axis]) == s,
                     f"mesh axes {dict(mesh.shape)} lack {axis!r}={s}",
                     context="trainer")
        # repack blk{i}_<suffix> -> pipe_body.<suffix> [L, ...] stacks;
        # their placement plan shards the stacked layer dim over 'stage'
        stacked = {}
        for suffix, d in sorted(groups.items()):
            vals = [self.parameters.pop(d[i]) for i in range(n_layers)]
            stacked[f"pipe_body.{suffix}"] = jnp.stack(vals)
        for k, v in stacked.items():
            self.parameters[k] = v
        self._pipe_specs = placement.pipeline_param_attrs(stacked, axis=axis)
        self._pipeline = cfg
        self._pipe_axis = axis
        self._pipe_stages = s
        self._pipe_m = m
        self._pipe_layers = n_layers
        self._pipe_heads = int(cfg.n_heads)
        self._pipe_remat = bool(cfg.remat)
        return mesh

    def _pipeline_forward_backward(self):
        """The pipeline replacement for the topology forward/backward:
        pad the packed feeds, split them into M microbatches, and run
        the GPipe fill+drain schedule (parallel.pipeline.pipeline_apply)
        with the embed as the first-stage hook and final-LN + vocab head
        + xent as the last-stage hook.  ``jax.grad`` differentiates
        through scan + ppermute, so the backward schedule is free.

        Loss semantics match ``_reduce_cost`` on a SequenceBatch cost
        exactly: each microbatch emits the SUM of its valid-token
        cross-entropies and the step divides by the global sequence
        count (per-SEQUENCE mean) — the loss-trajectory parity pin.
        With causal attention, trailing pad positions cannot leak into
        valid positions, so parity holds for ragged batches too."""
        from paddle_tpu.models import transformer as _tf
        from paddle_tpu.ops.losses import softmax_cross_entropy
        from paddle_tpu.parallel.pipeline import pipeline_apply

        mesh = self.mesh
        axis = self._pipe_axis
        s, m = self._pipe_stages, self._pipe_m
        n_heads = self._pipe_heads
        per_stage = self._pipe_layers // s
        remat = self._pipe_remat

        def stage_fn(stk, x):
            # stk: this stage's [L/S, ...] stacks — scan its blocks;
            # vmap the per-sequence block over the microbatch rows
            def one_block(h, blk):
                h = jax.vmap(
                    lambda seq: _tf.block_apply(blk, seq, n_heads=n_heads))(h)
                return h, None

            h, _ = jax.lax.scan(one_block, x, stk)
            return h

        def first_fn(fp, mb):
            return (fp["tok_embed.w"][mb["tokens"]]
                    + fp["pos_embed.w"][mb["pos"]])

        def last_fn(lp, y, mb):
            h = _tf._ln(y, lp["final_ln.gamma"], lp["final_ln.beta"])
            logits = h @ lp["lm_head.w0"] + lp["lm_head.b"]
            xe = softmax_cross_entropy(logits, mb["target"])
            return jnp.sum(jnp.where(mb["mask"], xe, 0.0))

        def microbatch_split(feeds):
            tok, mask = feeds["tokens"].to_padded()
            pos, _ = feeds["pos"].to_padded()
            tgt, _ = feeds["target"].to_padded()
            b = int(tok.shape[0])
            enforce_that(b % m == 0,
                         f"batch of {b} sequences does not divide into "
                         f"pipeline_microbatches={m}", context="trainer")

            def split(a):
                return a.reshape((m, b // m) + a.shape[1:])

            return {"tokens": split(tok), "pos": split(pos),
                    "target": split(tgt), "mask": split(mask)}, b

        def forward_backward(params, model_state, rng, feeds):
            mbs, b = microbatch_split(feeds)

            def loss_fn(p):
                body = {k[len("pipe_body."):]: v for k, v in p.items()
                        if k.startswith("pipe_body.")}
                # [L, ...] -> [S, L/S, ...]: a leading-dim split, so the
                # stage sharding carries over without resharding comm
                stk = {k: v.reshape((s, per_stage) + v.shape[1:])
                       for k, v in body.items()}
                first_p = {k: p[k] for k in ("tok_embed.w", "pos_embed.w")}
                last_p = {k: p[k] for k in ("final_ln.gamma",
                                            "final_ln.beta",
                                            "lm_head.w0", "lm_head.b")}
                sums = pipeline_apply(mesh, stage_fn, stk, mbs, axis=axis,
                                      first_fn=first_fn, first_params=first_p,
                                      last_fn=last_fn, last_params=last_p,
                                      remat=remat)
                return jnp.sum(sums) / float(b), (model_state, {})

            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        return forward_backward

    # ------------------------------------------------------------------
    # compiled steps
    # ------------------------------------------------------------------

    def _build_step(self):
        topo = self.topology
        optimizer = self.optimizer
        n_costs = self._n_costs
        metric_names = list(self.metrics.keys())
        mesh = self.mesh

        # grad stats ride in the same compiled step (TrainerInternal.cpp:
        # 80-110 computes avgAbsGrad/maxAbsGrad in the update callback).
        # captured once at build time: the compiled step and the logging
        # cadence must agree even if the flag changes later
        self._stats_period = int(FLAGS.show_parameter_stats_period or 0)
        stats_on = self._stats_period > 0
        guard = self._guard

        def forward_backward(params, model_state, rng, feeds):
            def loss_fn(p):
                outs, new_state = topo.forward(p, model_state, feeds,
                                               train=True, rng=rng, mesh=mesh)
                cost_vals = [_reduce_cost(o) for o in outs[:n_costs]]
                total = functools.reduce(jnp.add, cost_vals)
                metric_vals = {name: _metric_scalar(o) for name, o in
                               zip(metric_names, outs[n_costs:])}
                return total, (new_state, metric_vals)

            return jax.value_and_grad(loss_fn, has_aux=True)(params)

        if self._pipeline is not None:
            # same step/guard/stats wrapper, different forward/backward:
            # the GPipe schedule replaces topology.forward wholesale
            forward_backward = self._pipeline_forward_backward()

        def grad_stats(metric_vals, grads):
            if not stats_on:
                return metric_vals
            metric_vals = dict(metric_vals)
            metric_vals["__param_stats__"] = {
                k: (jnp.mean(jnp.abs(g)), jnp.max(jnp.abs(g)))
                for k, g in grads.items()}
            return metric_vals

        def step(params, opt_state, model_state, rng, feeds):
            (loss, (new_mstate, metric_vals)), grads = forward_backward(
                params, model_state, rng, feeds)
            new_params, new_opt = optimizer.apply(params, grads, opt_state)
            return (loss, new_params, new_opt, new_mstate,
                    grad_stats(metric_vals, grads))

        def guarded_step(params, opt_state, model_state, rng, feeds,
                         guard_state):
            # bad-step guard (paddle_tpu.resilience.guard): screen the
            # gradients with ONE fused f32 sq-norm reduction (also the
            # fault plan's poison seam — `inject` is 0.0 outside
            # injection windows), run the usual update, and select every
            # params/slot/model-state leaf back to its old value when
            # the step is bad.  The counters stay on device; the host
            # reads them on the same lazy cadence as .cost — no new
            # per-step sync, no extra compile (the inject scalar is a
            # same-shape argument, not a trace constant).
            from paddle_tpu.resilience.guard import (guard_outputs,
                                                     screen_grads,
                                                     select_good)

            (loss, (new_mstate, metric_vals)), grads = forward_backward(
                params, model_state, rng, feeds)
            grads, good, _ = screen_grads(grads, guard_state["inject"],
                                          guard.max_norm)
            new_params, new_opt = optimizer.apply(params, grads, opt_state)
            new_params = select_good(good, new_params, params)
            new_opt = select_good(good, new_opt, opt_state)
            new_mstate = select_good(good, new_mstate, model_state)
            return (loss, new_params, new_opt, new_mstate,
                    grad_stats(metric_vals, grads),
                    guard_outputs(good, guard_state))

        # With mesh-sharded (NamedSharding) inputs, jit partitions the whole
        # step SPMD automatically — XLA inserts the grad psum (the
        # MultiGradientMachine ring / pserver addGradient analog).
        return audit_jit(guarded_step if guard is not None else step,
                         site="trainer.train_step",
                         donate_argnums=(0, 1, 2),
                         xla_contract=self._step_contract())

    def _step_contract(self, donate=(0, 1, 2),
                       test: bool = False) -> SiteContract:
        """Compiled-path contract for the train/test steps, checked by
        the jaxpr auditor: params/opt-state/model-state must actually
        ride the requested donation (verified from the REQUESTED jit
        kwargs, so CPU tier-1 runs still check the TPU contract);
        collectives are the point of a sharded step (grad psum, ZeRO
        reduce-scatter/all-gather); bf16 operands deliberately reduce
        losses/norm statistics in f32 (the repo's precision model, see
        MIGRATION "The bf16 precision model").  The peak-bytes budget
        is a guardrail — activations scale with the batch, which the
        trainer cannot see at build time, so the budget is a generous
        multiple of the weights plus fixed slack, catching only
        duplicated-state-sized regressions.

        Sharding contract (the `analysis sharding` gate): on a mesh,
        feeds shard their batch dim over ``data`` (matching
        ``_shard_feeds``), params/model-state/rng replicate, and under
        ZeRO the flat optimizer slots arrive 1/N-sharded —
        ``expect_sharded`` pins that the plan actually reached them.
        The comm budget covers the worst of the two layouts: a full
        replicated-DP gradient psum (2x param bytes over the ring) or
        ZeRO's reduce-scatter + all-gather pair, with fixed slack for
        the loss/metric scalar reductions."""
        param_bytes = 0
        for v in self.parameters.as_dict().values():
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                n = int(np.prod(v.shape)) if v.shape else 1
                param_bytes += n * jnp.dtype(v.dtype).itemsize
        mesh = self.mesh
        mesh_axes: tuple = ()
        in_specs = None
        expect: tuple = ()
        if mesh is not None:
            mesh_axes = tuple(
                (str(a), int(s))
                for a, s in zip(mesh.axis_names, mesh.devices.shape))
            # pipeline feeds are SequenceBatches (replicated); otherwise
            # dense feeds shard their batch dim over 'data'
            feed = (("data",) if "data" in mesh.axis_names
                    and self._pipeline is None else ())
            plan = getattr(self, "_zero_plan", None)
            opt = (plan.axis,) if plan is not None else ()
            if test:
                in_specs = ((), (), feed)        # params, mstate, feeds
            else:
                # params, opt_state, model_state, rng, feeds
                # (+ the replicated guard-state scalars when guarded)
                in_specs = ((), opt, (), (), feed)
                if self._guard is not None:
                    in_specs = in_specs + ((),)
                if plan is not None:
                    expect = (1,)
        # Under pipeline the step's comm scales with ticks x activation
        # bytes — batch-shaped, invisible at build time — so the
        # trainer-level budget stays unset (INFO); the inner
        # parallel.pipeline site carries the EXACT closed-form budget.
        comm = (None if self._pipeline is not None
                else 6.0 * param_bytes + (1 << 20))
        return SiteContract(
            donate=tuple(donate), allow_collectives=True,
            allow_upcast=("bfloat16",),
            peak_bytes=16 * param_bytes + (1 << 28),
            in_specs=in_specs, mesh_axes=mesh_axes,
            expect_sharded=expect,
            comm_bytes=comm)

    def _build_test(self):
        enforce_that(self._pipeline is None,
                     "test() is not supported under pipeline= (the "
                     "repacked body has no topology.forward view) — "
                     "evaluate with a sequential trainer sharing the "
                     "checkpoint", context="trainer")
        topo = self.topology
        n_costs = self._n_costs
        metric_names = list(self.metrics.keys())
        mesh = self.mesh

        def test_step(params, model_state, feeds):
            outs, _ = topo.forward(params, model_state, feeds, train=False,
                                   mesh=mesh)
            cost_vals = [_reduce_cost(o) for o in outs[:n_costs]]
            total = functools.reduce(jnp.add, cost_vals)
            metric_vals = {name: _metric_scalar(o) for name, o in
                           zip(metric_names, outs[n_costs:])}
            return total, metric_vals

        return audit_jit(test_step, site="trainer.test_step",
                         xla_contract=self._step_contract(donate=(),
                                                          test=True))

    def _place_on_mesh(self, slots_too: bool = True) -> None:
        """(Re)commit params — and optimizer state mirroring them — to
        their mesh shardings. Called at init and after ANY checkpoint
        load: load_checkpoint hands back host arrays, and without
        re-placement a resume would replicate 'too big to replicate'
        weights on every device."""
        if self.mesh is None:
            return
        from paddle_tpu.parallel.api import param_sharding

        shardings = param_sharding(self.mesh, self.parameters.as_dict(),
                                   specs=self._param_specs(),
                                   zero_axis=self._zero_axis)
        self.parameters.update_from(
            {k: _put_global(v, shardings[k])
             for k, v in self.parameters.as_dict().items()})
        if not slots_too or not isinstance(self.opt_state, dict):
            return
        plan = getattr(self, "_zero_plan", None)
        if plan is not None:
            # ZeRO: planned params' slots (and avg/prune masks) live as
            # flat 1/N shards; checkpoint loads hand back full-shape host
            # arrays, which shard_state flattens/pads/places. Passthrough
            # params fall to the declared shardings below.
            self.opt_state = plan.shard_state(self.opt_state)

        def _slot_put(k, v):
            if plan is not None and plan.is_sharded(k):
                return v  # already placed by shard_state
            return _put_global(v, shardings[k]) if k in shardings else v

        new_state = dict(self.opt_state)
        for key in ("slots",):
            if key in new_state:
                new_state[key] = {
                    s: {k: _slot_put(k, v) for k, v in d.items()}
                    for s, d in new_state[key].items()}
        for key in ("avg", "prune_masks"):
            if key in new_state:
                new_state[key] = {
                    k: _slot_put(k, v) for k, v in new_state[key].items()}
        self.opt_state = new_state

    def _shard_feeds(self, feeds):
        if self.mesh is None:
            return feeds
        from jax.sharding import NamedSharding, PartitionSpec as P

        # batch shards ONLY over the 'data' axis; on a model-parallel-only
        # mesh feeds replicate (sharding the batch over 'model' would both
        # break on non-divisible trailing batches and force a per-step
        # all-gather against the stage constraints)
        axis = "data" if "data" in self.mesh.axis_names else None
        nproc = jax.process_count()
        out = {}
        for k, v in feeds.items():
            if isinstance(v, SequenceBatch):
                out[k] = v  # ragged feeds stay replicated (see parallel/)
            elif axis is None:
                out[k] = _put_global(v, NamedSharding(self.mesh, P()))
            elif nproc > 1:
                # multi-host DP: each process feeds its LOCAL rows; the
                # global batch is the concatenation over processes (every
                # process must feed the same local batch size — the
                # reference's fixed num_gradient_servers contract)
                sh = NamedSharding(self.mesh,
                                   P(axis, *([None] * (v.ndim - 1))))
                out[k] = jax.make_array_from_process_local_data(
                    sh, np.asarray(v))
            else:
                out[k] = jax.device_put(
                    v, NamedSharding(self.mesh, P(axis, *([None] * (v.ndim - 1)))))
        return out

    # ------------------------------------------------------------------
    # public API (reference: v2 trainer.py)
    # ------------------------------------------------------------------

    def train(self, reader=None, num_passes: int = 1, event_handler=None,
              feeding=None, test_reader=None, save_dir: Optional[str] = None,
              start_pass: int = 0, saving_period: int = 1, master=None,
              record_parser=None, heartbeat_ttl_s: Optional[float] = None,
              prefetch: int = 0, save_period_steps: int = 0,
              resume: bool = False, async_save: Optional[bool] = None,
              keep: Optional[int] = None) -> None:
        """``save_dir``/``start_pass``/``saving_period`` are the
        --save_dir/--start_pass/--saving_period flags of the reference
        trainer (ParamUtil.h:77-111): checkpoints (params + optimizer
        state) land in save_dir/pass-%05d every ``saving_period`` passes,
        and ``start_pass`` resumes from an existing one if present.

        Fault-tolerant mode (paddle_tpu.resilience): with
        ``save_period_steps=N`` checkpoints are STEP-granular — every N
        steps (and at each pass end) a checkpoint carrying a ``cursor``
        (pass id, step-in-pass, global step, rng state) is written under
        a monotonically increasing id; ``resume=True`` restores the
        newest INTACT checkpoint (corrupt dirs are rejected with a
        CKPT-CORRUPT line and the next-older one wins) and fast-forwards
        the data cursor, so a killed run re-joins mid-pass with the same
        rng stream — final params equal an uninterrupted run's.
        ``async_save=True`` (default ``FLAGS.train_ckpt_async``) writes
        blobs on a background thread (AsyncCheckpointer): training
        stalls only for the device->host snapshot.  ``keep`` bounds the
        checkpoint dir (verified-aware pruning; default
        ``FLAGS.train_ckpt_keep``).

        With ``master=MasterClient(...)`` training is elastic/task-driven
        instead of reader-driven (reference: cloud_reader + etcd
        registration, go/pserver/etcd_client.go:67-166): batches come from
        master tasks (``record_parser`` maps each record's bytes to a
        sample tuple), the lease is heartbeat per batch, and a lapsed
        lease triggers re-register + auto-resume from the latest
        checkpoint in ``save_dir``."""
        use_async = bool(FLAGS.train_ckpt_async) if async_save is None \
            else bool(async_save)
        keep = int(FLAGS.train_ckpt_keep) if keep is None else int(keep)
        if master is not None:
            enforce_that(record_parser is not None,
                         "master= training needs record_parser=",
                         context="trainer")
            enforce_that(start_pass == 0, "start_pass is reader-path only; "
                         "elastic training resumes from save_dir "
                         "automatically", context="trainer")
            enforce_that(save_period_steps == 0,
                         "save_period_steps is reader-path only; elastic "
                         "training checkpoints per saving_period tasks",
                         context="trainer")
            return self._train_elastic(master, record_parser, num_passes,
                                       event_handler, feeding, save_dir,
                                       heartbeat_ttl_s, saving_period,
                                       test_reader, use_async, keep)
        enforce_that(reader is not None, "train() needs a reader "
                     "(or master=)", context="trainer")
        enforce_that(not (resume and start_pass > 0),
                     "resume= (step-granular, cursor-driven) and "
                     "start_pass= (pass-granular) are exclusive",
                     context="trainer")
        # silently no-opping these would make a supervised run restart
        # from scratch on every death — the elastic path already errors
        # on the same misuse ("lease lost with no save_dir")
        enforce_that(not (resume and save_dir is None),
                     "resume=True needs save_dir= (nothing to resume "
                     "from otherwise)", context="trainer")
        enforce_that(not (save_period_steps > 0 and save_dir is None),
                     "save_period_steps needs save_dir=",
                     context="trainer")
        if event_handler is None:
            event_handler = _default_event_handler
        feeder = self._make_feeder(feeding)
        if self._step_fn is None:
            self._step_fn = self._build_step()
        log = plog.logger()

        from paddle_tpu import checkpoint as ckpt

        if save_dir is not None and start_pass > 0:
            import os

            # resume from exactly pass start_pass-1 (newer checkpoints may
            # exist when re-branching; silently training from fresh init
            # would overwrite them with garbage)
            want = start_pass - 1
            enforce_that(os.path.isdir(ckpt.pass_dir(save_dir, want)),
                         f"start_pass={start_pass} but no checkpoint "
                         f"pass-{want:05d} under {save_dir}",
                         context="trainer")
            self.load_checkpoint(save_dir, want)

        resume_pass, resume_step = start_pass, 0
        if resume and save_dir is not None:
            loaded = ckpt.load_latest(save_dir)
            if loaded is not None:
                self.apply_checkpoint(loaded)
                meta = loaded[3]
                cur = meta.get("cursor") or {}
                # a cursor-less (legacy per-pass) artifact resumes at the
                # pass AFTER the one it closed
                resume_pass = int(cur.get("pass_id",
                                          meta.get("pass_id", -1) + 1))
                resume_step = int(cur.get("step_in_pass", 0))
                self._global_step = int(cur.get("global_step", 0))
                if cur.get("rng") is not None:
                    self._rng = jnp.asarray(
                        np.asarray(cur["rng"], dtype=np.uint32))
                log.info("resumed from checkpoint (pass %d, step-in-pass "
                         "%d, global step %d)", resume_pass, resume_step,
                         self._global_step)
                self._tracer.instant("train_resume", cat="train",
                                     pass_id=resume_pass,
                                     step=self._global_step)
        step_saves = save_dir is not None and save_period_steps > 0
        ck_next = 0
        if step_saves:
            # monotonic checkpoint counter above every existing dir
            # (id 0 is a real id — `or -1` would shift the numbering)
            lp = ckpt.latest_pass(save_dir)
            ck_next = (lp + 1) if lp is not None else 0
        # per-call checkpointer: a previous train() call's async writer
        # must neither leak into this call (async_save=False here would
        # silently stay async, with the OLD keep) nor race it — settle
        # and rebuild from this call's arguments
        if self._async_ckpt is not None:
            self._drain_async_writer("superseded by a new train() call")
            self._async_ckpt = None
        if step_saves and use_async:
            from paddle_tpu.resilience.checkpointer import AsyncCheckpointer

            self._async_ckpt = AsyncCheckpointer(keep=keep)

        params = self.parameters.as_dict()
        opt_state = self.opt_state
        mstate = self.model_state
        gstate = self._guard_init() if self._guard is not None else None
        self._bad_steps_seen = 0   # fresh device counter this train()
        faults = self._faults

        def sync_back():
            self.parameters.update_from(params)
            self.opt_state = opt_state
            self.model_state = mstate

        def save_cursor(pass_id: int, step_in_pass: int) -> None:
            """One step-granular checkpoint (sync or async) carrying the
            resume cursor; checkpoint ids are a monotonic counter, not
            pass ids, so mid-pass saves never collide."""
            nonlocal ck_next
            sync_back()
            self._save_with_cursor(save_dir, ck_next, pass_id,
                                   step_in_pass, keep)
            ck_next += 1

        try:
            # reference flag semantics (ParamUtil.h): num_passes is the
            # TOTAL pass count; resuming runs passes [resume_pass,
            # num_passes), not num_passes additional ones
            for pass_id in range(resume_pass, num_passes):
                skip = resume_step if pass_id == resume_pass else 0
                raw_it = reader()
                if skip:
                    # fast-forward the data cursor: the resumed pass
                    # consumed `skip` batches before the checkpoint, so
                    # drop them unconverted (no feed/transfer cost)
                    for _ in range(skip):
                        if next(raw_it, None) is None:
                            break
                    peek = next(raw_it, None)
                    if peek is None:
                        # the cursor sits exactly at the pass boundary
                        # (the pass-end save was torn): the pass already
                        # completed AND fired its events before the
                        # crash — repair the boundary cursor and move on
                        # without re-firing BeginPass/EndPass over an
                        # empty replay (a zero-metric duplicate EndPass
                        # would feed garbage to early-stopping handlers)
                        if step_saves:
                            save_cursor(pass_id + 1, 0)
                        continue
                    raw_it = itertools.chain([peek], raw_it)
                event_handler(v2_event.BeginPass(pass_id))
                # host-side floats; device scalars buffer in `pending` and
                # flush with ONE stacked transfer per stream per log window
                pass_costs: List[float] = []
                pass_metrics: Dict[str, List[float]] = {
                    n: [] for n in self.metrics}
                pending: List = []
                pending_metrics: Dict[str, List] = {
                    n: [] for n in self.metrics}

                def flush():
                    if pending:
                        pass_costs.extend(
                            np.asarray(jnp.stack(pending)).tolist())
                        pending.clear()
                    for k, buf in pending_metrics.items():
                        if buf:
                            pass_metrics[k].extend(
                                np.asarray(jnp.stack(buf)).tolist())
                            buf.clear()

                if prefetch > 0:
                    # device-resident double buffering: feed conversion +
                    # the host->device transfer of batch k+1 overlap batch
                    # k's compute (the async DataProvider pool analog)
                    from paddle_tpu.reader.prefetch import device_prefetch

                    feed_it = device_prefetch(
                        raw_it, size=prefetch, transform=feeder.feed,
                        place=self._shard_feeds if self.mesh is not None
                        else None)
                else:
                    feed_it = (self._shard_feeds(feeder.feed(b))
                               for b in raw_it)
                for batch_id, feeds in enumerate(feed_it, start=skip):
                    if faults is not None:
                        # injected clock tick + scheduled death, BEFORE
                        # the step runs (a killed step's work is lost and
                        # must replay from the last checkpoint)
                        faults.step_begin(self._global_step)
                    event_handler(v2_event.BeginIteration(pass_id, batch_id))
                    self._rng, key = jax.random.split(self._rng)
                    with stats.timer("trainOneBatch"):
                        if gstate is not None:
                            gstate["inject"] = np.float32(
                                faults.grad_inject(self._global_step)
                                if faults is not None else 0.0)
                            (loss, params, opt_state, mstate, metric_vals,
                             gout) = self._step_fn(params, opt_state,
                                                   mstate, key, feeds,
                                                   gstate)
                            gstate = {"inject": gstate["inject"], **gout}
                        else:
                            loss, params, opt_state, mstate, metric_vals = \
                                self._step_fn(params, opt_state, mstate,
                                              key, feeds)
                    self._global_step += 1
                    pstats = metric_vals.pop("__param_stats__", None)
                    period = getattr(self, "_stats_period", 0)
                    if pstats is not None and period > 0 \
                            and (batch_id + 1) % period == 0:
                        for k in sorted(pstats):
                            avg_abs, max_abs = pstats[k]
                            log.info("Param %s avgAbsGrad=%.6g "
                                     "maxAbsGrad=%.6g",
                                     k, float(avg_abs), float(max_abs))
                    # no host sync per batch (the device round-trip costs
                    # more than the step); events convert lazily
                    pending.append(loss)
                    for k, v in metric_vals.items():
                        pending_metrics[k].append(v)
                    event_handler(v2_event.EndIteration(pass_id, batch_id,
                                                        loss, metric_vals))
                    if step_saves and (batch_id + 1) % save_period_steps == 0:
                        save_cursor(pass_id, batch_id + 1)
                    if gstate is not None:
                        self._guard_check(gstate)
                    if FLAGS.log_period \
                            and (batch_id + 1) % FLAGS.log_period == 0:
                        flush()
                        mtxt = " ".join(
                            f"{k}={np.mean(v[-FLAGS.log_period:]):.5f}"
                            for k, v in pass_metrics.items())
                        log.info("Pass %d, Batch %d, Cost %.5f %s", pass_id,
                                 batch_id,
                                 np.mean(pass_costs[-FLAGS.log_period:]),
                                 mtxt)
                # pass end: sync back, fire event (+ test if reader given)
                flush()
                sync_back()
                result_metrics = {k: float(np.mean(v)) if v else 0.0
                                  for k, v in pass_metrics.items()}
                if test_reader is not None:
                    tr = self.test(test_reader, feeding)
                    event_handler(v2_event.EndPass(pass_id, tr.metrics,
                                                   self.parameters))
                else:
                    event_handler(v2_event.EndPass(pass_id, result_metrics,
                                                   self.parameters))
                if gstate is not None:
                    # before the pass-end save: a save-kill must not
                    # swallow this pass's bad-step accounting
                    self._flush_guard_stats(gstate)
                if step_saves:
                    # pass boundary in cursor terms: next pass, step 0
                    save_cursor(pass_id + 1, 0)
                elif save_dir is not None \
                        and (pass_id + 1) % saving_period == 0:
                    self.save_checkpoint(save_dir, pass_id)
                # scrape surface for the per-batch timers: publish the
                # StatSet into the obs registry each pass instead of
                # ad-hoc report() prints — training timings land next to
                # serving metrics on ONE export.  Wrap event_handler with
                # obs.trainer_event_bridge(tracer) to additionally put
                # every pass/iteration on a trace timeline.
                stats.timer_stats().publish(default_registry(),
                                            prefix="trainer_")
        except BaseException:
            # unwind (injected death, rollback, real error): let the
            # in-flight background write finish — deterministic, and a
            # half-written artifact would otherwise race the resume —
            # and loudly report (not raise) any recorded writer failure,
            # since the restart path builds a fresh trainer and would
            # otherwise drop it with this object
            self._drain_async_writer("train loop unwinding")
            raise

        sync_back()
        if self._async_ckpt is not None:
            # durability barrier: train() returning means the newest
            # checkpoint is committed (writer errors surface here)
            self._async_ckpt.wait()

    # ------------------------------------------------------------------
    # bad-step guard + cursor-checkpoint plumbing (paddle_tpu.resilience)
    # ------------------------------------------------------------------

    def _guard_init(self):
        from paddle_tpu.resilience.guard import guard_init

        return guard_init()

    def _guard_check(self, gstate) -> None:
        """Rollback-policy hysteresis check, amortized: the consecutive
        counter is a device scalar read back only every
        ``guard.cadence`` steps (healthy steps stay on the lazy .cost
        sync contract).  A streak of ``rollback_after`` bad steps dumps
        the flight recorder and raises BadStepRollback — the supervisor
        restarts from the newest verified checkpoint."""
        g = self._guard
        if g is None or g.policy != "rollback" \
                or self._global_step % g.cadence:
            return
        consec = int(gstate["bad_consec"])
        if consec < g.rollback_after:
            return
        from paddle_tpu.resilience.faults import BadStepRollback

        self._tracer.instant("bad_step_rollback", cat="train",
                             consec=consec, step=self._global_step)
        if getattr(self._tracer, "enabled", False):
            self._tracer.dump_postmortem("bad-step-rollback")
        default_registry().counter(
            "train_rollbacks_total",
            "bad-step guard rollbacks to the last good checkpoint").inc()
        raise BadStepRollback(
            f"{consec} consecutive bad steps (>= {g.rollback_after}) at "
            f"global step {self._global_step}: rolling back to the last "
            "verified checkpoint")

    def _flush_guard_stats(self, gstate) -> None:
        """Lazy bad-step accounting (one host read per pass): newly
        skipped steps land on the obs timeline and the unified registry,
        and ``self.bad_steps_total`` accumulates the lifetime count
        (the device counter restarts at 0 on every train() call; the
        watermark ``_bad_steps_seen`` is reset with it)."""
        total = int(gstate["bad_total"])
        new = total - self._bad_steps_seen
        if new > 0:
            self.bad_steps_total += new
            self._tracer.instant("bad_steps_skipped", cat="train",
                                 count=new, total=self.bad_steps_total,
                                 step=self._global_step)
            default_registry().counter(
                "train_bad_steps_total",
                "train steps skipped by the bad-step guard "
                "(non-finite or over-norm gradients)").inc(new)
        self._bad_steps_seen = total

    def _drain_async_writer(self, why: str) -> None:
        """Join the in-flight async write and LOUDLY report — never
        raise — a recorded writer failure.  Used wherever the
        checkpointer is being discarded or the loop is already
        unwinding: the failed artifact is uncommitted (resume falls
        back to the previous checkpoint), but the failure must not die
        silently with the object."""
        ck = self._async_ckpt
        if ck is None:
            return
        ck.drain()
        err = ck.take_error()
        if err is not None:
            plog.logger().warning(
                "async checkpoint writer failed (%s): %r — artifact "
                "left uncommitted; resume falls back to the previous "
                "checkpoint", why, err)
            self._tracer.instant("ckpt_write_failed", cat="train",
                                 why=why)

    def _save_with_cursor(self, root: str, ck_id: int, pass_id: int,
                          step_in_pass: int, keep: int) -> None:
        """One step-granular checkpoint under the tmp+rename+md5 commit
        protocol, sync or async (``self._async_ckpt``).  The cursor
        records everything a replacement trainer needs to continue the
        SAME run: pass id, step-in-pass (the data cursor), global step
        (the fault/metric clock) and the rng key (the dropout/shuffle
        stream)."""
        from paddle_tpu import checkpoint as ckpt

        extra = {"cursor": {"pass_id": int(pass_id),
                            "step_in_pass": int(step_in_pass),
                            "global_step": int(self._global_step),
                            "rng": np.asarray(self._rng).tolist()}}
        hook = self._faults.save_hook(ck_id) \
            if self._faults is not None else None
        with self._tracer.span("checkpoint_save", cat="train", ck=ck_id,
                               step=self._global_step):
            if self._async_ckpt is not None:
                self._async_ckpt.save(
                    root, ck_id, self.parameters, opt_state=self.opt_state,
                    model_state=self.model_state, extra_meta=extra,
                    shard_plan=self._zero_plan, commit_hook=hook)
            else:
                ckpt.save_checkpoint(
                    root, ck_id, self.parameters, opt_state=self.opt_state,
                    model_state=self.model_state, extra_meta=extra,
                    shard_plan=self._zero_plan, commit_hook=hook)
                if keep > 0:
                    ckpt.prune_checkpoints(root, keep=keep)

    def _train_elastic(self, master, record_parser, num_passes: int,
                       event_handler, feeding, save_dir: Optional[str],
                       ttl_s: Optional[float], saving_period: int,
                       test_reader, use_async: bool = False,
                       keep: int = 2) -> None:
        """Task-driven elastic training (the kill/resume e2e productized).

        One SGD step per master task; the step counter (== applied task
        count along this trainer lineage) drives the rng stream and is
        persisted in checkpoint meta, so a replacement trainer resumes
        the SAME stream — final params equal an uninterrupted run (the
        test_TrainerOnePass determinism bar extended to the crash path;
        single-lineage guarantee — with several concurrent trainers a
        requeued task may be re-run by a peer, the reference's async
        tolerance).

        Ack protocol: tasks are acked ONLY after a checkpoint covering
        them is durable (``saving_period`` = tasks per checkpoint; every
        task when save_dir is unset). The checkpoint meta records the
        covered-but-possibly-unacked (task_id, epoch) set plus the
        in-progress pass and next rng step, so a crash in ANY window —
        before the step, or after the checkpoint but before the acks —
        resumes without losing or double-applying a task. Old
        checkpoints are pruned (crash-resume only needs the latest; the
        previous one is kept as insurance while the newest is young).

        Async mode (``use_async``, an AsyncCheckpointer) PIPELINES the
        durability: flush N waits out write N-1, acks the tasks write
        N-1 covered, then submits write N and keeps training — the ack
        invariant ("ack strictly after durable") holds with the disk
        write off the step path.  A crash in any window still resumes
        exactly: write N's covered tasks are unacked, so they requeue
        and replay against checkpoint N-1 (or skip against N if its
        meta committed first).
        """
        import time as _time

        from paddle_tpu import checkpoint as ckpt

        if event_handler is None:
            event_handler = _default_event_handler
        feeder = self._make_feeder(feeding)
        if self._step_fn is None:
            self._step_fn = self._build_step()
        log = plog.logger()
        saving_period = max(1, int(saving_period))
        faults = self._faults
        # per-call checkpointer (same contract as the reader path); the
        # async prune budget keeps the sync path's >= 2 insurance floor,
        # or a keep=1 caller would lose the previous checkpoint the
        # elastic rejoin story depends on while the newest is young
        if self._async_ckpt is not None:
            self._drain_async_writer("superseded by a new train() call")
            self._async_ckpt = None
        if save_dir is not None and use_async:
            from paddle_tpu.resilience.checkpointer import AsyncCheckpointer

            # keep=0 stays "pruning disabled" (the documented flag
            # semantics); only a positive budget gets the >= 2 floor
            self._async_ckpt = AsyncCheckpointer(
                keep=keep if keep == 0 else max(2, keep))

        def resume_state():
            """-> (next_step, skip_set, pass_id, next_ckpt_id)."""
            latest = ckpt.latest_pass(save_dir) if save_dir else None
            if latest is None:
                return 0, set(), 0, 0
            p, opt, mst, meta = ckpt.load_checkpoint(save_dir)
            self.parameters.update_from(p.as_dict())
            if opt is not None:
                self.opt_state = opt
            if mst is not None:
                self.model_state = mst
            self._place_on_mesh()
            log.info("elastic: resumed from checkpoint %d (pass %d, "
                     "next step %d)", latest, meta.get("pass_id", 0),
                     meta.get("next_step", latest + 1))
            skip = {(tid, meta.get("epoch", 0))
                    for tid in meta.get("task_ids", [])}
            return (meta.get("next_step", latest + 1), skip,
                    meta.get("pass_id", 0), latest + 1)

        if getattr(master, "_slot", None) is None:
            master.register(ttl_s=ttl_s)
        step, skip_set, pass_id, ck_id = resume_state()

        params = self.parameters.as_dict()
        opt_state = self.opt_state
        mstate = self.model_state
        gstate = self._guard_init() if self._guard is not None else None
        self._bad_steps_seen = 0   # fresh device counter this train()
        unacked: List[int] = []
        # async pipelining: tasks covered by the in-flight (submitted,
        # not yet provably durable) checkpoint — acked at the NEXT flush
        # once that write has committed
        covered: List[int] = []

        def sync_back():
            self.parameters.update_from(params)
            self.opt_state = opt_state
            self.model_state = mstate

        def settle_covered() -> None:
            """The durability-then-ack invariant, in ONE place: wait the
            in-flight write durable (writer errors raise HERE, on the
            training thread), then — and only then — ack the tasks that
            write covered."""
            self._async_ckpt.wait()
            for tid in covered:
                master.ack_task(tid)
            covered.clear()

        def flush(meta_pass: int, epoch: int, final: bool = False) -> None:
            """Checkpoint the current state, then ack everything a
            DURABLE checkpoint covers. Ack strictly AFTER the write: the
            reverse order could lose acked-but-not-durable updates.  On
            the async path the write of flush N commits in the
            background while training continues; flush N+1 (or the
            ``final`` drain) waits it out and acks its tasks."""
            nonlocal ck_id
            if save_dir is None:
                for tid in unacked:
                    master.ack_task(tid)
                unacked.clear()
                return
            hook = faults.save_hook(ck_id) if faults is not None else None
            meta = {"next_step": step, "pass_id": meta_pass,
                    "epoch": epoch}
            if self._async_ckpt is not None:
                # NOTE the lease math: a task acks at the latest one
                # full flush window after its write submits, so the
                # master's timeout_s must cover saving_period steps +
                # one checkpoint write (the per-step idle() early-ack
                # usually settles much sooner)
                settle_covered()                 # previous write durable
                covered[:] = list(unacked)
                unacked.clear()
                meta["task_ids"] = list(covered)
                sync_back()
                with self._tracer.span("checkpoint_save", cat="train",
                                       ck=ck_id):
                    self._async_ckpt.save(
                        save_dir, ck_id, self.parameters,
                        opt_state=self.opt_state,
                        model_state=self.model_state, extra_meta=meta,
                        shard_plan=self._zero_plan, commit_hook=hook)
                ck_id += 1
                if final:
                    settle_covered()
                return
            meta["task_ids"] = list(unacked)
            sync_back()
            with self._tracer.span("checkpoint_save", cat="train",
                                   ck=ck_id):
                ckpt.save_checkpoint(
                    save_dir, ck_id, self.parameters,
                    opt_state=self.opt_state, model_state=self.model_state,
                    extra_meta=meta, shard_plan=self._zero_plan,
                    commit_hook=hook)
                if keep > 0:
                    ckpt.prune_checkpoints(save_dir, keep=max(2, keep))
            ck_id += 1
            for tid in unacked:
                master.ack_task(tid)
            unacked.clear()

        try:
            while pass_id < num_passes:
                master.begin_pass()
                event_handler(v2_event.BeginPass(pass_id))
                pending_costs: List = []
                batch_id = 0
                epoch = 0
                rejoined = False
                resumed_acks = False
                while True:
                    if not master.heartbeat(ttl_s=ttl_s):
                        # declared dead (long GC/preemption): durable state
                        # is required to rejoin — silently restarting the
                        # rng stream from scratch would corrupt training
                        enforce_that(save_dir is not None,
                                     "elastic lease lost with no save_dir: "
                                     "cannot resume; pass save_dir= to "
                                     "train(master=...)", context="trainer")
                        log.info("elastic: lease lost, re-registering")
                        # settle the in-flight write before reloading
                        # (racing it would read a half-commit); its
                        # outcome is superseded by the reload either
                        # way, so a writer error is reported, not raised
                        self._drain_async_writer("lease lost, rejoining")
                        master.register(ttl_s=ttl_s)
                        unacked.clear()
                        covered.clear()
                        step, skip_set, pass_id, ck_id = resume_state()
                        params = self.parameters.as_dict()
                        opt_state = self.opt_state
                        mstate = self.model_state
                        rejoined = True
                        break
                    status, got = master.try_next_task()
                    if status == "done":
                        if resumed_acks and batch_id == 0:
                            # the only thing this pass did was ack stale
                            # tasks from the PREVIOUS pass (crash at a
                            # pass boundary): the queue just drained, so
                            # recycle it and actually train this pass
                            master.begin_pass()
                            resumed_acks = False
                            continue
                        break
                    if status == "empty":
                        # possibly blocked on our own unacked tasks: flush
                        if unacked:
                            flush(pass_id, epoch)
                        elif covered and self._async_ckpt is not None:
                            # the queue tail: only the in-flight write's
                            # tasks are outstanding — wait it durable and
                            # ack them, or the poll would spin forever
                            settle_covered()
                        else:
                            master.poll_wait()   # jittered backoff, not a
                        continue                 # fixed-interval hammer
                    task_id, epoch, records = got
                    master.poll_reset()
                    if skip_set:
                        if (task_id, epoch) in skip_set:
                            # already applied inside the restored
                            # checkpoint (crash hit between write and
                            # ack): ack, skip
                            skip_set.discard((task_id, epoch))
                            log.info("elastic: task %d already in "
                                     "checkpoint, skipping", task_id)
                            master.ack_task(task_id)
                            resumed_acks = True
                            continue
                        # requeued tasks come back FIRST; a non-match means
                        # the remaining skip entries are stale
                        skip_set.clear()
                    if faults is not None:
                        # injected clock + scheduled death BEFORE the
                        # batch is parsed or BeginIteration fires (the
                        # reader path's ordering: a killed step leaves
                        # no dangling iteration span on the obs
                        # timeline); the task stays leased-but-unacked,
                        # so it requeues when the lease lapses
                        faults.step_begin(step)
                    batch = [record_parser(r) for r in records]
                    event_handler(v2_event.BeginIteration(pass_id, batch_id))
                    feeds = self._shard_feeds(feeder.feed(batch))
                    with stats.timer("trainOneBatch"):
                        if gstate is not None:
                            gstate["inject"] = np.float32(
                                faults.grad_inject(step)
                                if faults is not None else 0.0)
                            (loss, params, opt_state, mstate, metric_vals,
                             gout) = self._step_fn(
                                params, opt_state, mstate,
                                jax.random.PRNGKey(step), feeds, gstate)
                            gstate = {"inject": gstate["inject"], **gout}
                        else:
                            loss, params, opt_state, mstate, metric_vals = \
                                self._step_fn(params, opt_state, mstate,
                                              jax.random.PRNGKey(step),
                                              feeds)
                    metric_vals.pop("__param_stats__", None)
                    step += 1
                    self._global_step = step
                    unacked.append(task_id)
                    if len(unacked) >= saving_period:
                        flush(pass_id, epoch)
                    elif covered and self._async_ckpt is not None \
                            and self._async_ckpt.idle():
                        # opportunistic early ack: the background write
                        # already committed, so its tasks need not stay
                        # leased until the next flush — this keeps the
                        # unacked window near ONE saving_period (plus
                        # actual write time) instead of two, which is
                        # what the master's per-task timeout_s must
                        # cover to avoid requeuing work a live trainer
                        # already applied
                        settle_covered()
                    if gstate is not None:
                        self._guard_check(gstate)
                    batch_id += 1
                    pending_costs.append(loss)  # device scalar, no sync
                    event_handler(v2_event.EndIteration(
                        pass_id, batch_id - 1, loss, metric_vals))
                    if FLAGS.log_period and batch_id % FLAGS.log_period == 0:
                        window = pending_costs[-FLAGS.log_period:]
                        log.info("Elastic pass %d, Batch %d, Cost %.5f",
                                 pass_id, batch_id - 1,
                                 float(np.mean(np.asarray(
                                     jnp.stack(window)))))
                if rejoined:
                    continue  # restart the (possibly different) pass
                # pass complete: flush leftovers, mark the NEXT pass
                # durable so a crash right here doesn't re-run this pass
                # on resume (final=True drains the async pipeline — the
                # pass boundary is a full durability point)
                pass_id += 1
                flush(pass_id, epoch, final=True)
                sync_back()
                if gstate is not None:
                    self._flush_guard_stats(gstate)
                # same registry publish as the reader path: elastic passes
                # expose their trainOneBatch timings through obs too
                stats.timer_stats().publish(default_registry(),
                                            prefix="trainer_")
                if test_reader is not None:
                    tr = self.test(test_reader, feeding)
                    event_handler(v2_event.EndPass(pass_id - 1, tr.metrics,
                                                   self.parameters))
                else:
                    event_handler(v2_event.EndPass(pass_id - 1, {},
                                                   self.parameters))
        except BaseException:
            # unwind (injected death, rollback, real error): let the
            # in-flight write finish — its meta either commits (resume
            # skips its tasks) or not (they replay) — loudly reporting
            # any recorded writer failure instead of dropping it with
            # this trainer object
            self._drain_async_writer("elastic loop unwinding")
            raise
        sync_back()

    def test(self, reader, feeding=None) -> v2_event.TestResult:
        feeder = self._make_feeder(feeding)
        if self._test_fn is None:
            self._test_fn = self._build_test()
        params = self.parameters.as_dict()
        costs: List[float] = []
        metrics: Dict[str, List[float]] = {n: [] for n in self.metrics}
        for data_batch in reader():
            feeds = feeder.feed(data_batch)
            loss, metric_vals = self._test_fn(params, self.model_state, feeds)
            costs.append(float(loss))
            for k, v in metric_vals.items():
                metrics[k].append(float(v))
        result = {k: float(np.mean(v)) if v else 0.0 for k, v in metrics.items()}
        return v2_event.TestResult(float(np.mean(costs)) if costs else 0.0, result)

    # ------------------------------------------------------------------

    def _make_feeder(self, feeding) -> DataFeeder:
        data_types = [(n.name, n.input_type) for n in self.topology.data_nodes]
        return DataFeeder(data_types, feeding)

    def save_parameter_to_tar(self, f) -> None:
        self.parameters.to_tar(f)

    # ------------------------------------------------------------------
    # checkpoint/resume incl. optimizer state (ParamUtil + go/pserver
    # checkpoint analogs — see paddle_tpu/checkpoint.py)
    # ------------------------------------------------------------------

    def save_checkpoint(self, root: str, pass_id: int) -> str:
        from paddle_tpu import checkpoint as ckpt
        return ckpt.save_checkpoint(root, pass_id, self.parameters,
                                    opt_state=self.opt_state,
                                    model_state=self.model_state,
                                    shard_plan=self._zero_plan)

    def load_checkpoint(self, root: str, pass_id: Optional[int] = None) -> None:
        from paddle_tpu import checkpoint as ckpt
        self.apply_checkpoint(ckpt.load_checkpoint(root, pass_id))

    def apply_checkpoint(self, loaded) -> None:
        """Apply an already-read ``checkpoint.load_checkpoint`` result.

        Split from :meth:`load_checkpoint` so callers can separate disk-read
        failures (missing/corrupt artifact) from apply failures (shape or
        mesh-placement bugs that deserve a traceback)."""
        params, opt_state, model_state, meta = loaded
        self.parameters.update_from(params.as_dict())
        if opt_state is not None:
            self.opt_state = opt_state
        if model_state is not None:
            self.model_state = model_state
        self._place_on_mesh()


def _put_global(v, sharding) -> jax.Array:
    """Multi-process-safe placement — see parallel.api.put_global."""
    from paddle_tpu.parallel.api import put_global

    return put_global(v, sharding)


def _default_event_handler(ev) -> None:
    pass


# ---------------------------------------------------------------------------
# Multi-task / alternating training (the GAN capability)
# ---------------------------------------------------------------------------


class TaskSpec:
    """One optimization task: a cost node, its optimizer, and a predicate
    naming which parameters it updates (v1_api_demo/gan/gan_trainer.py
    analog — two networks, alternating training)."""

    def __init__(self, name: str, cost, update_equation: Optimizer,
                 trainable=None):
        self.name = name
        self.cost = cost
        self.optimizer = update_equation
        if trainable is None:
            self.trainable = lambda pname: True
        elif isinstance(trainable, str):
            prefix = trainable
            self.trainable = lambda pname: pname.startswith(prefix)
        elif isinstance(trainable, (list, tuple, set, frozenset)):
            names = set(trainable)
            self.trainable = lambda pname: pname in names
        else:
            self.trainable = trainable


class MultiTaskTrainer:
    """Alternating training of several cost graphs over ONE shared
    parameter store — the reference's GAN loop (gan_trainer.py: generator
    and discriminator configs trained alternately against shared
    parameters) without its separate GradientMachines: each task is its
    own jitted step that masks gradients to its parameter subset.

    Usage::

        t = MultiTaskTrainer([
            TaskSpec("d", d_cost, Adam(2e-4), trainable="dis_"),
            TaskSpec("g", g_cost, Adam(2e-4), trainable="gen_"),
        ], parameters)
        d_loss = t.step("d", {"pixel": real, "noise": z})
        g_loss = t.step("g", {"noise": z})
    """

    def __init__(self, tasks: Sequence[TaskSpec], parameters: Parameters,
                 mesh=None):
        enforce_that(len(tasks) > 0, "need at least one task",
                     context="MultiTaskTrainer")
        self.tasks = {t.name: t for t in tasks}
        self.parameters = parameters
        self.mesh = mesh
        self._topos: Dict[str, Topology] = {}
        self._opt_states: Dict[str, Any] = {}
        self._model_states: Dict[str, Any] = {}
        self._step_fns: Dict[str, Any] = {}
        self._rng = jax.random.PRNGKey(FLAGS.seed or 0)
        self._counts: Dict[str, int] = {}
        for t in tasks:
            topo = Topology([t.cost])
            self._topos[t.name] = topo
            t.optimizer.set_param_specs(topo.param_specs())
            subset = {k: v for k, v in parameters.as_dict().items()
                      if t.trainable(k)}
            enforce_that(len(subset) > 0,
                         f"task {t.name!r} trains no parameters",
                         context="MultiTaskTrainer")
            self._opt_states[t.name] = t.optimizer.init_state(subset)
            self._model_states[t.name] = topo.init_state()
            self._counts[t.name] = 0

    def _build(self, name: str):
        task = self.tasks[name]
        topo = self._topos[name]
        optimizer = task.optimizer
        trainable = task.trainable
        mesh = self.mesh

        def step(params, opt_state, model_state, rng, feeds):
            def loss_fn(p):
                outs, new_state = topo.forward(p, model_state, feeds,
                                               train=True, rng=rng, mesh=mesh)
                return _reduce_cost(outs[0]), new_state

            (loss, new_mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            sub_p = {k: v for k, v in params.items() if trainable(k)}
            sub_g = {k: grads[k] for k in sub_p}
            new_sub, new_opt = optimizer.apply(sub_p, sub_g, opt_state)
            new_params = dict(params)
            new_params.update(new_sub)
            return loss, new_params, new_opt, new_mstate

        # only the task's opt-state is donated (params fan into every
        # task's graph, so the caller keeps them); same collective /
        # f32-reduction allowances as the SGD step
        return audit_jit(step, site=f"trainer.task.{name}",
                         donate_argnums=(1,),
                         xla_contract=SiteContract(
                             donate=(1,), allow_collectives=True,
                             allow_upcast=("bfloat16",)))

    def step(self, name: str, feeds: Dict[str, Any]) -> float:
        """Run one optimization step of the named task; other tasks'
        parameters flow through the graph but are not updated."""
        enforce_that(name in self.tasks, f"unknown task {name!r}",
                     context="MultiTaskTrainer")
        fn = self._step_fns.get(name)
        if fn is None:
            fn = self._step_fns[name] = self._build(name)
        self._rng, sub = jax.random.split(self._rng)
        loss, new_params, new_opt, new_mstate = fn(
            self.parameters.as_dict(), self._opt_states[name],
            self._model_states[name], sub, feeds)
        self.parameters.update_from(new_params)
        self._opt_states[name] = new_opt
        self._model_states[name] = new_mstate
        # stateful slots (batch-norm stats) shared across task graphs by
        # node name: propagate updates into the other tasks' state maps
        for other, st in self._model_states.items():
            if other != name:
                for node_name, slots in new_mstate.items():
                    if node_name in st:
                        st[node_name] = slots
        self._counts[name] += 1
        return float(loss)

    def steps_run(self, name: str) -> int:
        return self._counts[name]
