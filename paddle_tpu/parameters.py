"""Parameters: the trainable-state container with tar save/load.

Reference: python/paddle/v2/parameters.py (Parameters dict keyed by name,
``to_tar``/``from_tar`` checkpoint format) and paddle/parameter/Parameter.h
(VALUE buffer + config). Optimizer slot buffers (MOMENTUM etc.) live in the
optimizer state pytree, not here — the functional split TPU training wants.
"""

from __future__ import annotations

import io
import json
import tarfile
from typing import Any, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.initializer import to_initializer, default_bias_init
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.topology import ParamSpec, Topology


class Parameters:
    """name -> jax.Array with attached specs. Behaves like a mapping."""

    def __init__(self):
        self._values: Dict[str, jax.Array] = {}
        self._specs: Dict[str, ParamSpec] = {}

    # ---- construction ----------------------------------------------------

    @staticmethod
    def from_topology(topology: Topology, *, seed: int = 0,
                      dtype=jnp.float32) -> "Parameters":
        specs = topology.param_specs()
        params = Parameters()
        key = jax.random.PRNGKey(seed)
        for i, (name, spec) in enumerate(sorted(specs.items())):
            sub = jax.random.fold_in(key, i)
            is_bias = name.endswith(".b") or name.endswith("bias")
            if spec.attr.initializer is not None:
                init = to_initializer(spec.attr.initializer)
            elif is_bias:
                init = default_bias_init()
            else:
                init = to_initializer(None)
            pdtype = spec.attr.dtype or spec.dtype or dtype
            params._values[name] = init(sub, tuple(spec.shape), pdtype)
            params._specs[name] = spec
        return params

    # ---- mapping surface -------------------------------------------------

    def __getitem__(self, name: str) -> jax.Array:
        if name not in self._values:
            raise EnforceError(f"no parameter named {name!r}", context="parameters")
        return self._values[name]

    def __setitem__(self, name: str, value) -> None:
        value = jnp.asarray(value)
        if name in self._specs:
            enforce_that(tuple(value.shape) == tuple(self._specs[name].shape),
                         f"shape mismatch for {name!r}: {value.shape} vs "
                         f"{self._specs[name].shape}", context="parameters")
        self._values[name] = value

    def __contains__(self, name: str) -> bool:
        return name in self._values

    def __iter__(self) -> Iterator[str]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def keys(self):
        return self._values.keys()

    def names(self):
        return list(self._values.keys())

    def items(self):
        return self._values.items()

    def get(self, name: str):
        """Parameter value as a host numpy array (reference:
        python/paddle/v2/parameters.py Parameters.get / __getitem__ —
        the accessor every v2 demo uses, e.g. parameters.get('embedding'))."""
        return np.asarray(self[name])

    def set(self, name: str, value) -> None:
        """Assign a parameter from host data (reference v2 Parameters.set)."""
        self[name] = value

    def get_spec(self, name: str) -> Optional[ParamSpec]:
        return self._specs.get(name)

    def pop(self, name: str) -> jax.Array:
        """Remove and return a parameter (and its spec) — the
        repacking seam trainer.SGD's pipeline path uses to swap the
        per-block ``blk{i}_*`` layout for stacked [L, ...] stage
        weights without leaving stale entries behind."""
        if name not in self._values:
            raise EnforceError(f"no parameter named {name!r}",
                               context="parameters")
        self._specs.pop(name, None)
        return self._values.pop(name)

    # ---- pytree bridge ---------------------------------------------------

    def as_dict(self) -> Dict[str, jax.Array]:
        """The pytree handed to jit/grad. Shares buffers, cheap."""
        return dict(self._values)

    def update_from(self, tree: Dict[str, jax.Array]) -> None:
        self._values.update(tree)

    # ---- checkpoint (to_tar/from_tar analog) -----------------------------

    def to_tar(self, f) -> None:
        """Write a tar with one .npy member per parameter + a manifest.

        Format intentionally simple and inspectable (the reference wrote
        raw parameter serialization + proto per member, v2/parameters.py).
        """
        with tarfile.open(fileobj=f, mode="w") as tar:
            manifest = {}
            for name, value in self._values.items():
                buf = io.BytesIO()
                np.save(buf, np.asarray(value), allow_pickle=False)
                data = buf.getvalue()
                member = name.replace("/", "__") + ".npy"
                info = tarfile.TarInfo(name=member)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
                manifest[name] = {"member": member,
                                  "shape": list(np.shape(value)),
                                  "dtype": str(np.asarray(value).dtype)}
            mdata = json.dumps(manifest).encode()
            info = tarfile.TarInfo(name="manifest.json")
            info.size = len(mdata)
            tar.addfile(info, io.BytesIO(mdata))

    @staticmethod
    def from_tar(f) -> "Parameters":
        params = Parameters()
        with tarfile.open(fileobj=f, mode="r") as tar:
            manifest = json.loads(tar.extractfile("manifest.json").read())
            for name, meta in manifest.items():
                arr = np.load(io.BytesIO(tar.extractfile(meta["member"]).read()),
                              allow_pickle=False)
                params._values[name] = jnp.asarray(arr)
        return params

    def init_from_tar(self, f) -> None:
        """Load values by name into existing parameters (shape-checked)."""
        loaded = Parameters.from_tar(f)
        for name, value in loaded.items():
            if name in self._values:
                self[name] = value

    def __repr__(self):
        total = sum(int(np.prod(v.shape)) for v in self._values.values())
        return f"Parameters({len(self._values)} tensors, {total:,} elements)"
