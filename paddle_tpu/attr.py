"""Parameter / layer extra attributes.

Reference: python/paddle/trainer_config_helpers/attrs.py (ParameterAttribute
with lr mult, l2 decay, sparse flags; ExtraLayerAttribute with drop_rate,
device placement). Device placement becomes a sharding annotation here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


@dataclass
class HookAttr:
    """Parameter updater hook (reference ParameterUpdaterHook.cpp:39-104,
    configured via ParameterConfig.proto update_hooks).

    ``type='pruning'``: a static mask is generated once from the initial
    weights (keep the largest (1 - sparsity_ratio) fraction by |value|)
    and applied to the value and every subsequent update."""

    type: str = "pruning"
    sparsity_ratio: float = 0.6

    @staticmethod
    def to_hooks(arg) -> "list[HookAttr]":
        if arg is None:
            return []
        if isinstance(arg, HookAttr):
            return [arg]
        if isinstance(arg, dict):
            return [HookAttr(**arg)]
        return [HookAttr(**h) if isinstance(h, dict) else h for h in arg]


# the reference's name for the same concept
HookAttribute = HookAttr


@dataclass
class ParamAttr:
    """Per-parameter attributes.

    ``sharding`` is the TPU-native replacement for the reference's
    device/sparse-remote placement: a PartitionSpec-like tuple naming mesh axes
    per dim (None = replicated).
    """

    name: Optional[str] = None
    initializer: Any = None          # paddle_tpu.initializer.* or callable
    learning_rate: float = 1.0       # per-parameter LR multiplier
    l1_decay: float = 0.0
    l2_decay: float = 0.0
    is_static: bool = False          # frozen parameter (no update)
    sparse_update: bool = False      # row-sparse gradient (embedding tables)
    gradient_clipping_threshold: float = 0.0
    sharding: Optional[Sequence[Optional[str]]] = None
    dtype: Any = None                # parameter dtype override
    update_hooks: Any = None         # HookAttr / list (pruning masks etc.)

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, dict):
            return ParamAttr(**arg)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


# The reference's name for the same concept.
ParameterAttribute = ParamAttr


@dataclass
class ExtraAttr:
    """Extra layer attributes (reference ExtraLayerAttribute): dropout,
    device placement.

    ``sharding``: PartitionSpec-like axis names per OUTPUT dim — the
    activation-sharding half of model parallelism (applied as a
    with_sharding_constraint when the trainer runs over a mesh).
    ``device``: the reference's per-layer device id
    (ParallelNeuralNetwork.h:15-70, --parallel_nn). On TPU meshes manual
    thread-per-device placement is replaced by SPMD sharding, so the id
    is kept as a stage LABEL (diagnostics/config parity; see
    parallel.placement for the sharding-based equivalent)."""

    drop_rate: float = 0.0
    sharding: Optional[Sequence[Optional[str]]] = None   # output sharding
    device: Optional[int] = None                         # v1 stage label
    error_clipping_threshold: float = 0.0                # clip activations' grad

    @staticmethod
    def to_attr(arg) -> "ExtraAttr":
        if arg is None:
            return ExtraAttr()
        if isinstance(arg, ExtraAttr):
            return arg
        if isinstance(arg, dict):
            return ExtraAttr(**arg)
        raise TypeError(f"cannot convert {arg!r} to ExtraAttr")


ExtraLayerAttribute = ExtraAttr
