"""The layer DSL — the ``paddle.v2.layer`` / trainer_config_helpers analog.

Reference: python/paddle/trainer_config_helpers/layers.py (131 functions → the
95 registered C++ layer types in paddle/gserver/layers) and
python/paddle/v2/layer.py. Each function here returns a ``LayerOutput`` graph
node whose compute fn is pure jax; the whole graph compiles to one XLA program
(see paddle_tpu/topology.py).

Values flowing through the graph are either dense ``jax.Array`` ([batch, ...])
or ``SequenceBatch`` (ragged). Cost layers return per-example losses; the
trainer applies masking/averaging.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from paddle_tpu import activation as act_mod
from paddle_tpu import pooling as pooling_mod
from paddle_tpu.attr import ExtraAttr, ParamAttr
from paddle_tpu.data_type import InputType, SeqKind, SlotKind
from paddle_tpu.initializer import Constant
from paddle_tpu.ops import conv as pconv
from paddle_tpu.ops import losses as ploss
from paddle_tpu.ops import math as pmath
from paddle_tpu.ops import norm as pnorm
from paddle_tpu.ops import pool as ppool
from paddle_tpu.ops import rnn as prnn
from paddle_tpu.ops import sequence_ops as pseq
from paddle_tpu.ops.embedding import embedding_lookup
from paddle_tpu.platform.enforce import EnforceError, enforce_that
from paddle_tpu.sequence import SequenceBatch
from paddle_tpu.topology import (Context, LayerOutput, ParamSpec, StateSpec,
                                 unique_name)

__all__: List[str] = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _as_list(x) -> list:
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _resolve_act(act):
    return act_mod.get(act)


def _cast_value(value, dtype):
    if isinstance(value, SequenceBatch):
        return value.with_data(value.data.astype(dtype))
    return value.astype(dtype)


def _act_then_cast(activation, value, dtype):
    """Apply an activation and cast the result to the storage dtype.

    Softmax-family activations normalize across a row — computing them in
    bf16 collapses small probabilities, so they run on the f32 pre-activation
    (the matmul accumulator dtype) and only the activated output is cast.
    Other activations are pointwise and monotone-precision, so the cheaper
    order (cast first, activate in storage dtype) is used.
    """
    if isinstance(activation, (act_mod.SoftmaxActivation,
                               act_mod.SequenceSoftmaxActivation)):
        return _cast_value(_apply_act(activation, value), dtype)
    return _apply_act(activation, _cast_value(value, dtype))


def _apply_act(activation, value):
    """Apply an activation to a dense array or tokenwise to a SequenceBatch."""
    if isinstance(activation, act_mod.SequenceSoftmaxActivation):
        enforce_that(isinstance(value, SequenceBatch),
                     "sequence_softmax needs a sequence input", context="layer")
        return pseq.sequence_softmax(value)
    fn = activation.fn
    if fn is None:
        return value
    if isinstance(value, SequenceBatch):
        return value.with_data(fn(value.data))
    return fn(value)


@jax.custom_vjp
def _clip_error(x, threshold):
    return x


def _clip_error_fwd(x, threshold):
    return x, threshold


def _clip_error_bwd(threshold, g):
    # identity forward, clipped backward: the reference's per-layer
    # error_clipping_threshold (Layer.cpp backwardActivation clips the
    # output-grad to [-t, t] before it propagates)
    return jnp.clip(g, -threshold, threshold), None


_clip_error.defvjp(_clip_error_fwd, _clip_error_bwd)


def _apply_extra(ctx: Context, name: str, value, layer_attr: Optional[ExtraAttr]):
    attr = ExtraAttr.to_attr(layer_attr)
    if attr.drop_rate > 0.0:
        key = ctx.rng_for(name)
        if isinstance(value, SequenceBatch):
            value = value.with_data(
                pmath.dropout(value.data, attr.drop_rate, key, ctx.train))
        else:
            value = pmath.dropout(value, attr.drop_rate, key, ctx.train)
    if attr.sharding is not None and getattr(ctx, "mesh", None) is not None:
        # activation half of model parallelism: constrain this layer's
        # output over the mesh; XLA inserts the collectives (the
        # ParallelNeuralNetwork dispatchByDeviceId analog)
        from jax.sharding import NamedSharding, PartitionSpec as P

        ns = NamedSharding(ctx.mesh, P(*attr.sharding))
        if isinstance(value, SequenceBatch):
            value = value.with_data(
                jax.lax.with_sharding_constraint(value.data, ns))
        else:
            value = jax.lax.with_sharding_constraint(value, ns)
    if attr.error_clipping_threshold > 0.0:
        # LAST in forward order = FIRST in backward: the raw upstream
        # gradient is clipped before dropout's 1/(1-p) rescale, matching
        # the reference (Layer.cpp backwardActivation clips the incoming
        # output-grad before any other backward work)
        t = float(attr.error_clipping_threshold)
        if isinstance(value, SequenceBatch):
            value = value.with_data(_clip_error(value.data, t))
        else:
            value = _clip_error(value, t)
    return value


def _data_of(v):
    return v.data if isinstance(v, SequenceBatch) else v


def _like(template, data):
    if isinstance(template, SequenceBatch):
        return template.with_data(data)
    return data


def _propagate_img_shape(node: LayerOutput, *sources) -> LayerOutput:
    """Copy (H, W, C) metadata through shape-preserving layers so the image
    stack (conv/pool/bn/addto chains in ResNet etc.) keeps its geometry.
    Uses _img_shape_of so data(height=, width=) geometry also propagates."""
    for src in sources:
        shp = _img_shape_of(src)
        if shp is not None:
            node.img_shape = shp
            break
    return node


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------


_data_counter = [0]


@_export
def data(name: str, type: InputType, height: int = None, width: int = None,
         **_ignored) -> LayerOutput:
    """Input placeholder (reference: data_layer, v2 layer.data)."""
    node = LayerOutput(
        name=name, layer_type="data", inputs=[], fn=None,
        size=type.dim, is_sequence=type.seq != SeqKind.NO_SEQUENCE)
    node.input_type = type
    node.height, node.width = height, width
    # declaration order drives the default feeding column order (v2
    # semantics: sample tuples align with data layers as declared)
    node.declare_idx = _data_counter[0]
    _data_counter[0] += 1
    return node


# ---------------------------------------------------------------------------
# fc / embedding / mixed projections
# ---------------------------------------------------------------------------


@_export
def fc(input, size: int, act=None, name: Optional[str] = None,
       param_attr=None, bias_attr=True, layer_attr=None) -> LayerOutput:
    """Fully connected layer; multiple inputs are projected and summed
    (reference: fc_layer, gserver/layers/FullyConnectedLayer.cpp:69-139)."""
    inputs = _as_list(input)
    name = name or unique_name("fc")
    activation = _resolve_act(act)
    attrs = _as_list(param_attr) if isinstance(param_attr, (list, tuple)) else [param_attr] * len(inputs)
    params: Dict[str, ParamSpec] = {}
    for i, (inp, pa) in enumerate(zip(inputs, attrs)):
        enforce_that(inp.size is not None, f"input {inp.name} has no size", context="fc")
        params[f"w{i}"] = ParamSpec((inp.size, size), ParamAttr.to_attr(pa))
    has_bias = bool(bias_attr)
    if has_bias:
        battr = ParamAttr.to_attr(None if bias_attr is True else bias_attr)
        params["b"] = ParamSpec((size,), battr)

    def compute(ctx: Context, p, ins):
        total = None
        for i, v in enumerate(ins):
            d = _data_of(v)
            if not isinstance(v, SequenceBatch) and d.ndim > 2:
                d = d.reshape(d.shape[0], -1)  # flatten image maps (NHWC)
            y = pmath.matmul(d, p[f"w{i}"])
            total = y if total is None else total + y
        if has_bias:
            total = total + p["b"]
        out = _like(ins[0], total) if isinstance(ins[0], SequenceBatch) else total
        out = _act_then_cast(activation, out, pmath.dense_activation_dtype())
        return _apply_extra(ctx, name, out, layer_attr)

    return LayerOutput(name=name, layer_type="fc", inputs=inputs, fn=compute,
                       params=params, size=size,
                       is_sequence=inputs[0].is_sequence)


@_export
def embedding(input, size: int, name: Optional[str] = None,
              param_attr=None, layer_attr=None) -> LayerOutput:
    """Table lookup (reference: embedding_layer → TableProjection)."""
    inp = input
    name = name or unique_name("embedding")
    attr = ParamAttr.to_attr(param_attr)
    params = {"w": ParamSpec((inp.size, size), attr)}

    def compute(ctx, p, ins):
        v = ins[0]
        ids = _data_of(v)
        out = embedding_lookup(p["w"], ids)
        return _like(v, out.astype(pmath.dense_activation_dtype()))

    return LayerOutput(name=name, layer_type="embedding", inputs=[inp],
                       fn=compute, params=params, size=size,
                       is_sequence=inp.is_sequence)


# ---- mixed layer & projections (reference: MixedLayer.cpp, Projection.h) ---


class Projection:
    """Projection descriptor for mixed(); computes a [*, size] contribution."""

    def __init__(self, input: LayerOutput, size: Optional[int]):
        self.input = input
        self.size = size
        self.params: Dict[str, ParamSpec] = {}

    def compute(self, p: Dict[str, jax.Array], value):
        raise NotImplementedError


class _FullMatrixProjection(Projection):
    def __init__(self, input, size, param_attr=None, trans=False):
        super().__init__(input, size)
        self.trans = trans
        shape = (size, input.size) if trans else (input.size, size)
        self.params["w"] = ParamSpec(shape, ParamAttr.to_attr(param_attr))

    def compute(self, p, value):
        return pmath.matmul(_data_of(value), p["w"], trans_b=self.trans)


@_export
def full_matrix_projection(input, size: int, param_attr=None) -> Projection:
    return _FullMatrixProjection(input, size, param_attr)


@_export
def trans_full_matrix_projection(input, size: int, param_attr=None) -> Projection:
    """Uses W^T (reference: TransposedFullMatrixProjection)."""
    return _FullMatrixProjection(input, size, param_attr, trans=True)


class _IdentityProjection(Projection):
    def __init__(self, input, offset=0, size=None):
        out_size = size or input.size
        super().__init__(input, out_size)
        self.offset = offset

    def compute(self, p, value):
        d = _data_of(value)
        return jax.lax.slice_in_dim(d, self.offset, self.offset + self.size, axis=-1)


@_export
def identity_projection(input, offset: int = 0, size: int = None) -> Projection:
    return _IdentityProjection(input, offset, size)


@_export
def slice_projection(input, slices: Sequence[Tuple[int, int]], **kw) -> Projection:
    class _Slice(Projection):
        def __init__(self):
            total = sum(e - s for s, e in slices)
            super().__init__(input, total)

        def compute(self, p, value):
            d = _data_of(value)
            parts = [jax.lax.slice_in_dim(d, s, e, axis=-1) for s, e in slices]
            return jnp.concatenate(parts, axis=-1)

    return _Slice()


class _DotMulProjection(Projection):
    def __init__(self, input, param_attr=None):
        super().__init__(input, input.size)
        self.params["w"] = ParamSpec((input.size,), ParamAttr.to_attr(param_attr))

    def compute(self, p, value):
        return _data_of(value) * p["w"]


@_export
def dotmul_projection(input, param_attr=None) -> Projection:
    return _DotMulProjection(input, param_attr)


class _ScalingProjection(Projection):
    def __init__(self, input, param_attr=None):
        super().__init__(input, input.size)
        self.params["w"] = ParamSpec((1,), ParamAttr.to_attr(param_attr))

    def compute(self, p, value):
        return _data_of(value) * p["w"][0]


@_export
def scaling_projection(input, param_attr=None) -> Projection:
    return _ScalingProjection(input, param_attr)


class _TableProjection(Projection):
    def __init__(self, input, size, param_attr=None):
        super().__init__(input, size)
        self.params["w"] = ParamSpec((input.size, size), ParamAttr.to_attr(param_attr))

    def compute(self, p, value):
        return embedding_lookup(p["w"], _data_of(value))


@_export
def table_projection(input, size: int, param_attr=None) -> Projection:
    return _TableProjection(input, size, param_attr)


class _ContextProjection(Projection):
    """Sliding window concat over sequence tokens (reference:
    ContextProjection / function/ContextProjectionOp.cpp)."""

    def __init__(self, input, context_len, context_start, param_attr=None,
                 trainable_padding=False):
        super().__init__(input, input.size * context_len)
        self.context_len = context_len
        self.context_start = context_start
        self.trainable_padding = trainable_padding
        if trainable_padding:
            pad_rows = max(0, -context_start) + max(0, context_start + context_len - 1)
            self.params["pad"] = ParamSpec((max(1, pad_rows), input.size),
                                           ParamAttr.to_attr(param_attr))

    def compute(self, p, value):
        enforce_that(isinstance(value, SequenceBatch),
                     "context projection needs sequence input", context="mixed")
        padded, mask = value.to_padded()
        B, T, D = padded.shape
        cols = []
        for k in range(self.context_len):
            off = self.context_start + k
            shifted = jnp.roll(padded, -off, axis=1)
            # zero (or learned pad) outside range
            t = jnp.arange(T)[None, :]
            valid = (t + off >= 0) & (t + off < value.lengths[:, None])
            col = jnp.where(valid[..., None], shifted, 0.0)
            cols.append(col)
        out = jnp.concatenate(cols, axis=-1)
        flat = SequenceBatch.from_padded(out, value.lengths, capacity=value.capacity)
        return flat.data


@_export
def context_projection(input, context_len: int, context_start: int = None,
                       padding_attr=False, **kw) -> Projection:
    start = context_start if context_start is not None else -(context_len // 2)
    trainable = padding_attr is not False and padding_attr is not None
    return _ContextProjection(input, context_len, start,
                              param_attr=None if padding_attr in (False, True, None) else padding_attr,
                              trainable_padding=trainable)


class Operator:
    """Mixed-layer operator (reference: Operator.h — conv, dot_mul)."""

    def __init__(self, inputs: List[LayerOutput], size: Optional[int]):
        self.inputs = inputs
        self.size = size

    def compute(self, values: list):
        raise NotImplementedError


@_export
def dotmul_operator(a: LayerOutput, b: LayerOutput, scale: float = 1.0) -> Operator:
    class _DotMul(Operator):
        def __init__(self):
            super().__init__([a, b], a.size)

        def compute(self, values):
            return scale * _data_of(values[0]) * _data_of(values[1])

    return _DotMul()


@_export
def conv_operator(img: LayerOutput, filter: LayerOutput, filter_size: int,
                  num_filters: int, num_channels: int, stride: int = 1,
                  padding: int = 0) -> Operator:
    """Conv with filter coming from a layer (dynamic filter conv)."""

    class _ConvOp(Operator):
        def __init__(self):
            super().__init__([img, filter], None)

        def compute(self, values):
            x, f = _data_of(values[0]), _data_of(values[1])
            B = x.shape[0]
            if x.ndim == 2:
                # flat dense image slots are CHW-major like every other
                # image layer (_to_nhwc; reference PyDataProvider2 layout)
                h = int(round((x.shape[-1] // num_channels) ** 0.5))
                x = x.reshape(B, num_channels, h, h).transpose(0, 2, 3, 1)
            w = f.reshape(B, filter_size, filter_size, num_channels, num_filters)

            def one(xi, wi):
                return pconv.conv2d(xi[None], wi, stride=stride, padding=padding)[0]

            y = jax.vmap(one)(x, w)
            return y.reshape(B, -1)

    return _ConvOp()


@_export
def mixed(size: int = None, input=None, name: Optional[str] = None, act=None,
          bias_attr=False, layer_attr=None) -> LayerOutput:
    """Sum of projections/operators (reference: mixed_layer, MixedLayer.cpp)."""
    name = name or unique_name("mixed")
    comps = _as_list(input)
    enforce_that(len(comps) > 0, "mixed needs at least one projection", context="mixed")
    activation = _resolve_act(act)
    # infer size
    sizes = [c.size for c in comps if c.size is not None]
    if size is None:
        enforce_that(len(sizes) > 0, "mixed size cannot be inferred", context="mixed")
        size = sizes[0]

    graph_inputs: List[LayerOutput] = []
    proj_params: Dict[str, ParamSpec] = {}
    plan = []  # (kind, component, input_indices, param_prefix)
    for ci, comp in enumerate(comps):
        if isinstance(comp, Projection):
            graph_inputs.append(comp.input)
            prefix = f"p{ci}_"
            for pn, spec in comp.params.items():
                proj_params[prefix + pn] = spec
            plan.append(("proj", comp, [len(graph_inputs) - 1], prefix))
        elif isinstance(comp, Operator):
            idxs = []
            for inp in comp.inputs:
                graph_inputs.append(inp)
                idxs.append(len(graph_inputs) - 1)
            plan.append(("op", comp, idxs, None))
        elif isinstance(comp, LayerOutput):
            proj = identity_projection(comp)
            graph_inputs.append(comp)
            plan.append(("proj", proj, [len(graph_inputs) - 1], f"p{ci}_"))
        else:
            raise EnforceError(f"bad mixed component {comp!r}", context="mixed")

    has_bias = bool(bias_attr)
    if has_bias:
        battr = ParamAttr.to_attr(None if bias_attr is True else bias_attr)
        proj_params["b"] = ParamSpec((size,), battr)

    is_seq = graph_inputs[0].is_sequence

    def compute(ctx, p, ins):
        total = None
        template = ins[0]
        for kind, comp, idxs, prefix in plan:
            if kind == "proj":
                local = {k[len(prefix):]: v for k, v in p.items() if k.startswith(prefix)}
                y = comp.compute(local, ins[idxs[0]])
            else:
                y = comp.compute([ins[i] for i in idxs])
            total = y if total is None else total + y
        if has_bias:
            total = total + p["b"]
        out = _like(template, total) if isinstance(template, SequenceBatch) else total
        out = _apply_act(activation, out)
        return _apply_extra(ctx, name, out, layer_attr)

    return LayerOutput(name=name, layer_type="mixed", inputs=graph_inputs,
                       fn=compute, params=proj_params, size=size,
                       is_sequence=is_seq)


# ---------------------------------------------------------------------------
# elementwise / math layers
# ---------------------------------------------------------------------------


@_export
def addto(input, act=None, name: Optional[str] = None, bias_attr=False,
          layer_attr=None) -> LayerOutput:
    """Elementwise sum (reference: addto_layer/AddtoLayer.cpp)."""
    inputs = _as_list(input)
    name = name or unique_name("addto")
    activation = _resolve_act(act)
    params = {}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((inputs[0].size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        total = _data_of(ins[0])
        for v in ins[1:]:
            total = total + _data_of(v)
        if has_bias:
            total = total + p["b"].astype(total.dtype)
        out = _like(ins[0], total)
        out = _apply_act(activation, out)
        return _apply_extra(ctx, name, out, layer_attr)

    node = LayerOutput(name=name, layer_type="addto", inputs=inputs, fn=compute,
                       params=params, size=inputs[0].size,
                       is_sequence=inputs[0].is_sequence)
    return _propagate_img_shape(node, *inputs)


@_export
def concat(input, name: Optional[str] = None, act=None, layer_attr=None) -> LayerOutput:
    """Feature-dim concat (reference: concat_layer/ConcatenateLayer)."""
    inputs = _as_list(input)
    name = name or unique_name("concat")
    activation = _resolve_act(act)
    size = sum(i.size for i in inputs)

    def compute(ctx, p, ins):
        out = jnp.concatenate([_data_of(v) for v in ins], axis=-1)
        out = _like(ins[0], out)
        out = _apply_act(activation, out)
        return _apply_extra(ctx, name, out, layer_attr)

    node = LayerOutput(name=name, layer_type="concat", inputs=inputs, fn=compute,
                       size=size, is_sequence=inputs[0].is_sequence)
    # channel concat of same-geometry images (inception towers): carry
    # (H, W, sum C) so downstream conv/pool keep the geometry
    shapes = [_img_shape_of(i) for i in inputs]
    if all(s is not None for s in shapes) and \
            len({(h, w) for h, w, _ in shapes}) == 1:
        h, w, _ = shapes[0]
        node.img_shape = (h, w, sum(c for _, _, c in shapes))
    return node


@_export
def dotmul(a, b, name: Optional[str] = None) -> LayerOutput:
    """Elementwise product of two layers."""
    name = name or unique_name("dotmul")

    def compute(ctx, p, ins):
        return _like(ins[0], _data_of(ins[0]) * _data_of(ins[1]))

    return LayerOutput(name=name, layer_type="dotmul", inputs=[a, b], fn=compute,
                       size=a.size, is_sequence=a.is_sequence)


@_export
def interpolation(input, weight, name: Optional[str] = None) -> LayerOutput:
    """out = w*a + (1-w)*b with per-example scalar w (reference:
    interpolation_layer/InterpolationLayer.cpp). input=[a, b]."""
    a, b = _as_list(input)
    name = name or unique_name("interpolation")

    def compute(ctx, p, ins):
        va, vb, w = _data_of(ins[0]), _data_of(ins[1]), _data_of(ins[2])
        w = w.reshape(w.shape[0], *([1] * (va.ndim - 1)))
        return _like(ins[0], w * va + (1.0 - w) * vb)

    return LayerOutput(name=name, layer_type="interpolation", inputs=[a, b, weight],
                       fn=compute, size=a.size, is_sequence=a.is_sequence)


@_export
def scaling(input, weight, name: Optional[str] = None) -> LayerOutput:
    """Row-wise scale by a per-example scalar (reference: scaling_layer)."""
    name = name or unique_name("scaling")

    def compute(ctx, p, ins):
        v, w = _data_of(ins[0]), _data_of(ins[1])
        w = w.reshape(w.shape[0], *([1] * (v.ndim - 1)))
        return _like(ins[0], w * v)

    return LayerOutput(name=name, layer_type="scaling", inputs=[input, weight],
                       fn=compute, size=input.size, is_sequence=input.is_sequence)


@_export
def power(input, weight, name: Optional[str] = None) -> LayerOutput:
    """Elementwise x^w with per-example scalar w (reference: power_layer)."""
    name = name or unique_name("power")

    def compute(ctx, p, ins):
        v, w = _data_of(ins[0]), _data_of(ins[1])
        w = w.reshape(w.shape[0], *([1] * (v.ndim - 1)))
        return _like(ins[0], jnp.power(v, w))

    return LayerOutput(name=name, layer_type="power", inputs=[input, weight],
                       fn=compute, size=input.size, is_sequence=input.is_sequence)


@_export
def slope_intercept(input, slope: float = 1.0, intercept: float = 0.0,
                    name: Optional[str] = None) -> LayerOutput:
    """y = slope*x + intercept (reference: slope_intercept_layer)."""
    name = name or unique_name("slope_intercept")

    def compute(ctx, p, ins):
        return _like(ins[0], slope * _data_of(ins[0]) + intercept)

    return LayerOutput(name=name, layer_type="slope_intercept", inputs=[input],
                       fn=compute, size=input.size, is_sequence=input.is_sequence)


@_export
def sum_to_one_norm(input, name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("sum_to_one_norm")

    def compute(ctx, p, ins):
        return _like(ins[0], pnorm.sum_to_one_norm(_data_of(ins[0])))

    return LayerOutput(name=name, layer_type="sum_to_one_norm", inputs=[input],
                       fn=compute, size=input.size, is_sequence=input.is_sequence)


@_export
def row_l2_norm(input, name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("row_l2_norm")

    def compute(ctx, p, ins):
        return _like(ins[0], pnorm.row_l2_norm(_data_of(ins[0])))

    return LayerOutput(name=name, layer_type="row_l2_norm", inputs=[input],
                       fn=compute, size=input.size, is_sequence=input.is_sequence)


@_export
def cos_sim(a, b, scale: float = 1.0, name: Optional[str] = None) -> LayerOutput:
    """Cosine similarity (reference: cos_sim/CosSimLayer.cpp)."""
    name = name or unique_name("cos_sim")

    def compute(ctx, p, ins):
        return ploss.cosine_similarity(_data_of(ins[0]), _data_of(ins[1]), scale)[..., None]

    return LayerOutput(name=name, layer_type="cos_sim", inputs=[a, b], fn=compute,
                       size=1, is_sequence=a.is_sequence)


@_export
def clip(input, min: float, max: float, name: Optional[str] = None) -> LayerOutput:
    """Elementwise clip (reference: ClipLayer.cpp)."""
    name = name or unique_name("clip")

    def compute(ctx, p, ins):
        return _like(ins[0], jnp.clip(_data_of(ins[0]), min, max))

    return LayerOutput(name=name, layer_type="clip", inputs=[input], fn=compute,
                       size=input.size, is_sequence=input.is_sequence)


@_export
def resize(input, size: int, name: Optional[str] = None) -> LayerOutput:
    """Reshape the batch matrix to `size` columns, keeping the total element
    count — the row count becomes B*input.size/size (reference: ResizeLayer).
    Sequences keep their token structure elsewhere; use seq_reshape for them."""
    name = name or unique_name("resize")
    enforce_that(not input.is_sequence,
                 "resize reshapes the dense batch matrix; use seq_reshape "
                 "for sequences", context="resize")

    def compute(ctx, p, ins):
        return _data_of(ins[0]).reshape(-1, size)

    return LayerOutput(name=name, layer_type="resize", inputs=[input], fn=compute,
                       size=size, is_sequence=False)


@_export
def dropout(input, dropout_rate: float, name: Optional[str] = None) -> LayerOutput:
    """Standalone dropout (reference: dropout_layer helper)."""
    name = name or unique_name("dropout")

    def compute(ctx, p, ins):
        v = ins[0]
        key = ctx.rng_for(name)
        if isinstance(v, SequenceBatch):
            return v.with_data(pmath.dropout(v.data, dropout_rate, key, ctx.train))
        return pmath.dropout(v, dropout_rate, key, ctx.train)

    node = LayerOutput(name=name, layer_type="dropout", inputs=[input], fn=compute,
                       size=input.size, is_sequence=input.is_sequence)
    return _propagate_img_shape(node, input)


# ---------------------------------------------------------------------------
# image layers
# ---------------------------------------------------------------------------


def _img_shape_of(node: LayerOutput) -> Optional[Tuple[int, int, int]]:
    """(H, W, C) metadata threaded through the image stack."""
    shp = getattr(node, "img_shape", None)
    if shp is not None:
        return shp
    h = getattr(node, "height", None)
    w = getattr(node, "width", None)
    if h and w and node.size and node.size % (h * w) == 0:
        return (h, w, node.size // (h * w))
    return None


def _to_nhwc(v: jax.Array, shape_hwc: Tuple[int, int, int]) -> jax.Array:
    """Accept [B, H, W, C] passthrough or flat [B, C*H*W] (reference layout is
    CHW-major, matching PyDataProvider2 dense image slots)."""
    if v.ndim == 4:
        return v
    h, w, c = shape_hwc
    return v.reshape(v.shape[0], c, h, w).transpose(0, 2, 3, 1)


def _conv_out_dim(in_size, k, pad, stride):
    return (in_size + 2 * pad - k) // stride + 1


@_export
def img_conv(input, filter_size: int, num_filters: int, num_channels: int = None,
             stride: int = 1, padding: int = 0, groups: int = 1, act=None,
             name: Optional[str] = None, param_attr=None, bias_attr=True,
             shared_biases: bool = True, trans: bool = False,
             dilation: int = 1, layer_attr=None) -> LayerOutput:
    """2-D convolution (reference: img_conv_layer → ExpandConvLayer /
    CudnnConvLayer; trans=True → ConvTransLayer).

    Weights are HWIO; compute is NHWC on the MXU (ops/conv.py)."""
    inp = input
    name = name or unique_name("conv")
    activation = _resolve_act(act)
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None or num_channels is not None,
                 "img_conv needs image shape metadata or num_channels", context="img_conv")
    if in_shape is None:
        # assume square image
        import math as _math
        hw = int(round(_math.sqrt(inp.size // num_channels)))
        in_shape = (hw, hw, num_channels)
    h, w, c = in_shape
    num_channels = num_channels or c
    if trans:
        oh = (h - 1) * stride + filter_size - 2 * padding
        ow = (w - 1) * stride + filter_size - 2 * padding
        wshape = (filter_size, filter_size, num_channels, num_filters)
    else:
        oh = _conv_out_dim(h, filter_size, padding, stride)
        ow = _conv_out_dim(w, filter_size, padding, stride)
        wshape = (filter_size, filter_size, num_channels // groups, num_filters)
    params = {"w": ParamSpec(wshape, ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        bshape = (num_filters,) if shared_biases else (num_filters * oh * ow,)
        params["b"] = ParamSpec(bshape, ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        if trans:
            y = pconv.conv2d_transpose(x, p["w"], stride=stride, padding=padding)
        else:
            y = pconv.conv2d(x, p["w"], stride=stride, padding=padding,
                             dilation=dilation, groups=groups)
        if has_bias:
            # cast the f32 bias into the activation dtype: a plain add would
            # promote bf16 activations back to f32 and double HBM traffic
            if shared_biases:
                y = y + p["b"].astype(y.dtype)
            else:
                y = y + p["b"].reshape(1, oh, ow, num_filters).astype(y.dtype)
        y = _apply_act(activation, y)
        return _apply_extra(ctx, name, y, layer_attr)

    node = LayerOutput(name=name, layer_type="conv", inputs=[inp], fn=compute,
                       params=params, size=oh * ow * num_filters)
    node.img_shape = (oh, ow, num_filters)
    return node


@_export
def img_pool(input, pool_size: int, pool_type=None, stride: int = None,
             padding: int = 0, name: Optional[str] = None,
             layer_attr=None, **_kw) -> LayerOutput:
    """Image pooling (reference: img_pool_layer → PoolLayer/CudnnPoolLayer)."""
    inp = input
    name = name or unique_name("pool")
    ptype = pooling_mod.get(pool_type)
    stride = stride if stride is not None else pool_size
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "img_pool needs image shape", context="img_pool")
    h, w, c = in_shape
    oh = _conv_out_dim(h, pool_size, padding, stride)
    ow = _conv_out_dim(w, pool_size, padding, stride)

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        if isinstance(ptype, pooling_mod.MaxPooling):
            y = ppool.max_pool2d(x, pool_size, stride, padding)
        else:
            y = ppool.avg_pool2d(x, pool_size, stride, padding)
        return _apply_extra(ctx, name, y, layer_attr)

    node = LayerOutput(name=name, layer_type="pool", inputs=[inp], fn=compute,
                       size=oh * ow * c)
    node.img_shape = (oh, ow, c)
    return node


@_export
def spp(input, pyramid_height: int, num_channels: int = None, pool_type=None,
        name: Optional[str] = None) -> LayerOutput:
    """Spatial pyramid pooling (reference: spp_layer)."""
    inp = input
    name = name or unique_name("spp")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "spp needs image shape", context="spp")
    c = in_shape[2]
    ptype = pooling_mod.get(pool_type)
    out_size = sum(4 ** l for l in range(pyramid_height)) * c

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return ppool.spatial_pyramid_pool(
            x, pyramid_height,
            "max" if isinstance(ptype, pooling_mod.MaxPooling) else "avg")

    return LayerOutput(name=name, layer_type="spp", inputs=[inp], fn=compute,
                       size=out_size)


@_export
def maxout(input, groups: int, num_channels: int = None,
           name: Optional[str] = None) -> LayerOutput:
    """Maxout over channel groups (reference: maxout_layer)."""
    inp = input
    name = name or unique_name("maxout")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "maxout needs image shape", context="maxout")
    h, w, c = in_shape
    oc = c // groups

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return ppool.maxout(x, groups)

    node = LayerOutput(name=name, layer_type="maxout", inputs=[inp], fn=compute,
                       size=h * w * oc)
    node.img_shape = (h, w, oc)
    return node


@_export
def batch_norm(input, act=None, name: Optional[str] = None,
               num_channels: int = None, bias_attr=None, param_attr=None,
               use_global_stats: bool = None, moving_average_fraction: float = 0.9,
               layer_attr=None, **_kw) -> LayerOutput:
    """Batch normalization with moving stats in the state pytree
    (reference: batch_norm_layer → BatchNormalizationLayer/CudnnBatchNormLayer)."""
    inp = input
    name = name or unique_name("batch_norm")
    activation = _resolve_act(act)
    in_shape = _img_shape_of(inp)
    c = in_shape[2] if in_shape is not None else inp.size
    params = {
        "gamma": ParamSpec((c,), ParamAttr.to_attr(param_attr) if param_attr
                           else ParamAttr(initializer=Constant(1.0))),
        "beta": ParamSpec((c,), ParamAttr.to_attr(bias_attr) if bias_attr
                          else ParamAttr(initializer=Constant(0.0))),
    }
    state = {
        "moving_mean": StateSpec((c,), 0.0),
        "moving_var": StateSpec((c,), 1.0),
    }

    def compute(ctx, p, ins):
        v = ins[0]
        x = _data_of(v)
        if in_shape is not None:
            x = _to_nhwc(x, in_shape)
        y, nm, nv = pnorm.batch_norm(
            x, p["gamma"], p["beta"],
            ctx.get_state(name, "moving_mean"), ctx.get_state(name, "moving_var"),
            train=ctx.train, momentum=moving_average_fraction,
            use_global_stats=use_global_stats)
        ctx.set_state(name, "moving_mean", nm)
        ctx.set_state(name, "moving_var", nv)
        y = _apply_act(activation, y)
        y = _apply_extra(ctx, name, y, layer_attr)
        return _like(v, y) if isinstance(v, SequenceBatch) else y

    node = LayerOutput(name=name, layer_type="batch_norm", inputs=[inp],
                       fn=compute, params=params, state=state, size=inp.size,
                       is_sequence=inp.is_sequence)
    if in_shape is not None:
        node.img_shape = in_shape
    return node


@_export
def layer_norm(input, act=None, name: Optional[str] = None, param_attr=None,
               bias_attr=None, epsilon: float = 1e-5, **_kw) -> LayerOutput:
    """Per-row layer normalization over the feature axis (ops/norm.py
    layer_norm) — transformer-era extension beyond the reference's norm
    inventory (BatchNorm/CrossMapNorm, gserver/layers/*NormLayer.cpp);
    the normalization of the transformer LM family (models/transformer.py).
    Stats are per row, so packed variable-length sequences need no segment
    metadata."""
    inp = input
    name = name or unique_name("layer_norm")
    activation = _resolve_act(act)
    params = {
        "gamma": ParamSpec((inp.size,), ParamAttr.to_attr(param_attr)
                           if param_attr else ParamAttr(initializer=Constant(1.0))),
        "beta": ParamSpec((inp.size,), ParamAttr.to_attr(bias_attr)
                          if bias_attr else ParamAttr(initializer=Constant(0.0))),
    }

    def compute(ctx, p, ins):
        v = ins[0]
        x = _data_of(v)
        # pnorm.layer_norm reduces stats in f32 and emits x.dtype
        y = pnorm.layer_norm(x, p["gamma"], p["beta"], eps=epsilon)
        y = _apply_act(activation, y)
        return _like(v, y) if isinstance(v, SequenceBatch) else y

    return LayerOutput(name=name, layer_type="layer_norm", inputs=[inp],
                       fn=compute, params=params, size=inp.size,
                       is_sequence=inp.is_sequence)


@_export
def img_cmrnorm(input, size: int = 5, scale: float = 0.0001, power: float = 0.75,
                name: Optional[str] = None, **_kw) -> LayerOutput:
    """Local response normalization across maps (reference: img_cmrnorm_layer
    → CMRProjectionNormLayer, function/CrossMapNormalOp.cpp)."""
    inp = input
    name = name or unique_name("cmrnorm")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "cmrnorm needs image shape", context="cmrnorm")

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return pnorm.cross_map_norm(x, size, scale, power)

    node = LayerOutput(name=name, layer_type="cmrnorm", inputs=[inp], fn=compute,
                       size=inp.size)
    node.img_shape = in_shape
    return node


@_export
def bilinear_interp(input, out_size_x: int, out_size_y: int,
                    name: Optional[str] = None) -> LayerOutput:
    """Bilinear upsampling (reference: bilinear_interp_layer, hl_cnn bilinear)."""
    inp = input
    name = name or unique_name("bilinear_interp")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "bilinear_interp needs image shape",
                 context="bilinear_interp")
    h, w, c = in_shape

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return jax.image.resize(x, (x.shape[0], out_size_y, out_size_x, c),
                                method="bilinear")

    node = LayerOutput(name=name, layer_type="bilinear_interp", inputs=[inp],
                       fn=compute, size=out_size_x * out_size_y * c)
    node.img_shape = (out_size_y, out_size_x, c)
    return node


@_export
def pad(input, pad_c=(0, 0), pad_h=(0, 0), pad_w=(0, 0),
        name: Optional[str] = None) -> LayerOutput:
    """Zero-pad image dims (reference: pad_layer, function/PadOp.cpp)."""
    inp = input
    name = name or unique_name("pad")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "pad needs image shape", context="pad")
    h, w, c = in_shape
    oshape = (h + sum(pad_h), w + sum(pad_w), c + sum(pad_c))

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return jnp.pad(x, ((0, 0), tuple(pad_h), tuple(pad_w), tuple(pad_c)))

    node = LayerOutput(name=name, layer_type="pad", inputs=[inp], fn=compute,
                       size=oshape[0] * oshape[1] * oshape[2])
    node.img_shape = oshape
    return node


@_export
def crop(input, offset_h: int = 0, offset_w: int = 0, crop_h: int = None,
         crop_w: int = None, name: Optional[str] = None) -> LayerOutput:
    """Crop image dims (reference: crop_layer, function/CropOp.cpp)."""
    inp = input
    name = name or unique_name("crop")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "crop needs image shape", context="crop")
    h, w, c = in_shape
    ch = crop_h or h - offset_h
    cw = crop_w or w - offset_w

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return x[:, offset_h:offset_h + ch, offset_w:offset_w + cw, :]

    node = LayerOutput(name=name, layer_type="crop", inputs=[inp], fn=compute,
                       size=ch * cw * c)
    node.img_shape = (ch, cw, c)
    return node


@_export
def rotate(input, name: Optional[str] = None) -> LayerOutput:
    """90-degree CCW rotation (reference: rotate_layer/RotateLayer.cpp)."""
    inp = input
    name = name or unique_name("rotate")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "rotate needs image shape", context="rotate")
    h, w, c = in_shape

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return jnp.rot90(x, k=1, axes=(1, 2))

    node = LayerOutput(name=name, layer_type="rotate", inputs=[inp], fn=compute,
                       size=inp.size)
    node.img_shape = (w, h, c)
    return node


@_export
def block_expand(input, block_x: int, block_y: int, stride_x: int = 1,
                 stride_y: int = 1, padding_x: int = 0, padding_y: int = 0,
                 num_channels: int = None, name: Optional[str] = None) -> LayerOutput:
    """im2col layer (reference: block_expand_layer/BlockExpandLayer)."""
    inp = input
    name = name or unique_name("block_expand")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "block_expand needs image shape",
                 context="block_expand")
    h, w, c = in_shape
    oh = (h + 2 * padding_y - block_y) // stride_y + 1
    ow = (w + 2 * padding_x - block_x) // stride_x + 1

    def compute(ctx, p, ins):
        x = _to_nhwc(_data_of(ins[0]), in_shape)
        return pconv.block_expand(x, (block_y, block_x), (stride_y, stride_x),
                                  (padding_y, padding_x))

    return LayerOutput(name=name, layer_type="block_expand", inputs=[inp],
                       fn=compute, size=block_x * block_y * c)


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------


def _need_seq(node, ctx_name):
    enforce_that(node.is_sequence, f"{ctx_name} needs a sequence input",
                 context=ctx_name)


@_export
def pooling(input, pooling_type=None, name: Optional[str] = None,
            **_kw) -> LayerOutput:
    """Sequence pooling to one vector per sequence (reference: pooling_layer
    → SequencePoolLayer max/avg/sum/sqrtn)."""
    inp = input
    _need_seq(inp, "pooling")
    name = name or unique_name("seq_pool")
    ptype = pooling_mod.get(pooling_type)

    def compute(ctx, p, ins):
        sb = ins[0]
        if isinstance(ptype, pooling_mod.MaxPooling):
            return pseq.seq_pool_max(sb)
        if isinstance(ptype, pooling_mod.AvgPooling):
            return pseq.seq_pool_avg(sb)
        if isinstance(ptype, pooling_mod.SumPooling):
            return pseq.seq_pool_sum(sb)
        return pseq.seq_pool_sqrtn(sb)

    return LayerOutput(name=name, layer_type="seq_pool", inputs=[inp],
                       fn=compute, size=inp.size, is_sequence=False)


@_export
def last_seq(input, name: Optional[str] = None, **_kw) -> LayerOutput:
    """Last token of each sequence (reference: last_seq → SequenceLastInstance)."""
    inp = input
    _need_seq(inp, "last_seq")
    name = name or unique_name("last_seq")

    def compute(ctx, p, ins):
        return pseq.seq_last(ins[0])

    return LayerOutput(name=name, layer_type="last_seq", inputs=[inp], fn=compute,
                       size=inp.size, is_sequence=False)


@_export
def first_seq(input, name: Optional[str] = None, **_kw) -> LayerOutput:
    """First token of each sequence (reference: first_seq)."""
    inp = input
    _need_seq(inp, "first_seq")
    name = name or unique_name("first_seq")

    def compute(ctx, p, ins):
        return pseq.seq_first(ins[0])

    return LayerOutput(name=name, layer_type="first_seq", inputs=[inp], fn=compute,
                       size=inp.size, is_sequence=False)


@_export
def expand(input, expand_as, name: Optional[str] = None, **_kw) -> LayerOutput:
    """Broadcast per-sequence rows to token layout (reference: expand_layer)."""
    name = name or unique_name("expand")

    def compute(ctx, p, ins):
        return pseq.seq_expand(ins[0], ins[1])

    return LayerOutput(name=name, layer_type="expand", inputs=[input, expand_as],
                       fn=compute, size=input.size, is_sequence=True)


@_export
def seq_concat(a, b, name: Optional[str] = None, **_kw) -> LayerOutput:
    """Concat along time (reference: seq_concat_layer)."""
    name = name or unique_name("seq_concat")

    def compute(ctx, p, ins):
        return pseq.seq_concat(ins[0], ins[1])

    return LayerOutput(name=name, layer_type="seq_concat", inputs=[a, b],
                       fn=compute, size=a.size, is_sequence=True)


@_export
def seq_reshape(input, reshape_size: int, name: Optional[str] = None,
                **_kw) -> LayerOutput:
    """Reshape token dim (reference: seq_reshape_layer)."""
    inp = input
    _need_seq(inp, "seq_reshape")
    name = name or unique_name("seq_reshape")

    def compute(ctx, p, ins):
        return pseq.seq_reshape(ins[0], reshape_size)

    return LayerOutput(name=name, layer_type="seq_reshape", inputs=[inp],
                       fn=compute, size=reshape_size, is_sequence=True)


@_export
def seq_slice(input, starts=None, ends=None, name: Optional[str] = None) -> LayerOutput:
    """Slice each sequence by per-sequence [start, end) (reference:
    seq_slice_layer). starts/ends are layers carrying int positions or None."""
    inp = input
    _need_seq(inp, "seq_slice")
    name = name or unique_name("seq_slice")
    extra = [l for l in (starts, ends) if l is not None]

    def compute(ctx, p, ins):
        sb = ins[0]
        idx = 1
        if starts is not None:
            s = _data_of(ins[idx]).reshape(-1).astype(jnp.int32)
            idx += 1
        else:
            s = jnp.zeros((sb.num_seqs,), jnp.int32)
        if ends is not None:
            e = _data_of(ins[idx]).reshape(-1).astype(jnp.int32)
        else:
            e = sb.lengths
        return pseq.seq_slice(sb, s, e)

    return LayerOutput(name=name, layer_type="seq_slice", inputs=[inp] + extra,
                       fn=compute, size=inp.size, is_sequence=True)


@_export
def kmax_seq_score(input, beam_size: int, name: Optional[str] = None) -> LayerOutput:
    """Top-k positions by score in each sequence (reference: kmax_seq_score)."""
    inp = input
    _need_seq(inp, "kmax_seq_score")
    name = name or unique_name("kmax_seq_score")

    def compute(ctx, p, ins):
        return pseq.kmax_seq_score(ins[0], beam_size)

    return LayerOutput(name=name, layer_type="kmax_seq_score", inputs=[inp],
                       fn=compute, size=beam_size, is_sequence=False)


@_export
def sub_nested_seq(input, selected_indices, name: Optional[str] = None) -> LayerOutput:
    """Select inner sequences of a nested sequence (reference: sub_nested_seq)."""
    name = name or unique_name("sub_nested_seq")

    def compute(ctx, p, ins):
        return pseq.sub_nested_seq(ins[0], _data_of(ins[1]).astype(jnp.int32))

    return LayerOutput(name=name, layer_type="sub_nested_seq",
                       inputs=[input, selected_indices], fn=compute,
                       size=input.size, is_sequence=True)


@_export
def max_id(input, name: Optional[str] = None) -> LayerOutput:
    """Argmax id (reference: maxid_layer/MaxIdLayer.cpp)."""
    inp = input
    name = name or unique_name("max_id")

    def compute(ctx, p, ins):
        v = ins[0]
        return _like(v, pseq.max_id(_data_of(v)))

    return LayerOutput(name=name, layer_type="max_id", inputs=[inp], fn=compute,
                       size=1, is_sequence=inp.is_sequence)


@_export
def sampling_id(input, name: Optional[str] = None) -> LayerOutput:
    """Sample an id from a row distribution (reference: sampling_id_layer)."""
    inp = input
    name = name or unique_name("sampling_id")

    def compute(ctx, p, ins):
        v = ins[0]
        probs = _data_of(v)
        key = ctx.rng_for(name)
        ids = jax.random.categorical(key, jnp.log(jnp.clip(probs, 1e-20, 1.0)))
        return _like(v, ids.astype(jnp.int32))

    return LayerOutput(name=name, layer_type="sampling_id", inputs=[inp],
                       fn=compute, size=1, is_sequence=inp.is_sequence)


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------


@_export
def lstmemory(input, size: int = None, reverse: bool = False, act=None,
              gate_act=None, state_act=None, name: Optional[str] = None,
              param_attr=None, bias_attr=True, layer_attr=None) -> LayerOutput:
    """LSTM over a sequence whose input is ALREADY projected to 4*size
    (reference contract: lstmemory, gserver/layers/LstmLayer.cpp — the input
    projection lives in the upstream fc/mixed layer; simple_lstm in networks
    composes both). One lax.scan; gates fused by XLA (hl_cuda_lstm.cu analog).
    """
    inp = input
    _need_seq(inp, "lstmemory")
    enforce_that(inp.size % 4 == 0, "lstmemory input size must be 4*size",
                 context="lstmemory")
    size = size or inp.size // 4
    name = name or unique_name("lstmemory")
    out_act = _resolve_act(act or "tanh")
    g_act = _resolve_act(gate_act or "sigmoid")
    s_act = _resolve_act(state_act or "tanh")
    params = {"w": ParamSpec((size, 4 * size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((4 * size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        sb: SequenceBatch = ins[0]
        padded, mask = sb.to_padded()
        hs, _ = prnn.lstm_scan(
            padded, mask, None, p["w"], p.get("b"), reverse=reverse,
            gate_act=g_act.fn, cell_act=s_act.fn, out_act=out_act.fn)
        out = SequenceBatch.from_padded(hs, sb.lengths, capacity=sb.capacity)
        return _apply_extra(ctx, name, out, layer_attr)

    return LayerOutput(name=name, layer_type="lstmemory", inputs=[inp],
                       fn=compute, params=params, size=size, is_sequence=True)


@_export
def grumemory(input, size: int = None, reverse: bool = False, act=None,
              gate_act=None, name: Optional[str] = None, param_attr=None,
              bias_attr=True, layer_attr=None) -> LayerOutput:
    """GRU over a sequence with input pre-projected to 3*size (reference:
    grumemory → GatedRecurrentLayer.cpp / hl_gpu_gru.cuh)."""
    inp = input
    _need_seq(inp, "grumemory")
    enforce_that(inp.size % 3 == 0, "grumemory input size must be 3*size",
                 context="grumemory")
    size = size or inp.size // 3
    name = name or unique_name("grumemory")
    params = {"w": ParamSpec((size, 3 * size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((3 * size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        sb: SequenceBatch = ins[0]
        padded, mask = sb.to_padded()
        hs, _ = prnn.gru_scan(padded, mask, None, p["w"], p.get("b"),
                              reverse=reverse)
        out = SequenceBatch.from_padded(hs, sb.lengths, capacity=sb.capacity)
        return _apply_extra(ctx, name, out, layer_attr)

    return LayerOutput(name=name, layer_type="grumemory", inputs=[inp],
                       fn=compute, params=params, size=size, is_sequence=True)


@_export
def recurrent(input, size: int = None, act=None, reverse: bool = False,
              name: Optional[str] = None, param_attr=None,
              bias_attr=True) -> LayerOutput:
    """Simple (Elman) recurrent layer: h_t = act(x_t + W h_{t-1})
    (reference: recurrent_layer/RecurrentLayer.cpp)."""
    inp = input
    _need_seq(inp, "recurrent")
    size = size or inp.size
    name = name or unique_name("recurrent")
    activation = _resolve_act(act or "tanh")
    params = {"w": ParamSpec((size, size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        sb: SequenceBatch = ins[0]
        padded, mask = sb.to_padded()
        B, T, D = padded.shape

        def step(h, xm):
            x, m = xm
            nh = activation.fn(x + pmath.matmul(h, p["w"]) +
                               (p["b"] if has_bias else 0.0))
            m = m[:, None].astype(nh.dtype)
            nh = m * nh + (1 - m) * h
            return nh, nh

        xs = (jnp.swapaxes(padded, 0, 1), jnp.swapaxes(mask, 0, 1))
        _, hs = jax.lax.scan(step, jnp.zeros((B, size), padded.dtype), xs,
                             reverse=reverse)
        hs = jnp.swapaxes(hs, 0, 1)
        return SequenceBatch.from_padded(hs, sb.lengths, capacity=sb.capacity)

    return LayerOutput(name=name, layer_type="recurrent", inputs=[inp],
                       fn=compute, params=params, size=size, is_sequence=True)


# ---------------------------------------------------------------------------
# special layers: selective_fc, nce, hsigmoid, crf, ctc
# ---------------------------------------------------------------------------


@_export
def selective_fc(input, size: int, select=None, act=None,
                 name: Optional[str] = None, param_attr=None,
                 bias_attr=True, **_kw) -> LayerOutput:
    """FC where only selected output columns matter (reference:
    selective_fc_layer/SelectiveFullyConnectedLayer.cpp).

    TPU-native: the full matmul runs on the MXU (dense is faster than gather
    on TPU); unselected columns are masked to -inf/0 — semantics preserved,
    the 'skip computation' trick is deliberately NOT ported."""
    inputs = [input] + ([select] if select is not None else [])
    name = name or unique_name("selective_fc")
    activation = _resolve_act(act)
    params = {"w": ParamSpec((input.size, size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        y = pmath.matmul(_data_of(ins[0]), p["w"])
        if has_bias:
            y = y + p["b"]
        if select is not None:
            sel = _data_of(ins[1])  # [B, size] 0/1 mask (sparse_binary rows)
            y = jnp.where(sel > 0, y, 0.0)
        out = _like(ins[0], y)
        return _apply_act(activation, out)

    return LayerOutput(name=name, layer_type="selective_fc", inputs=inputs,
                       fn=compute, params=params, size=size,
                       is_sequence=input.is_sequence)


@_export
def nce(input, label, num_classes: int, num_neg_samples: int = 10,
        name: Optional[str] = None, param_attr=None, bias_attr=True,
        neg_distribution=None) -> LayerOutput:
    """Noise-contrastive estimation cost (reference: nce_layer/NCELayer.cpp).

    Uniform (or given) noise; logistic loss over 1 positive + k sampled
    negatives per example. Returns per-example cost."""
    inputs = [input, label]
    name = name or unique_name("nce")
    params = {"w": ParamSpec((num_classes, input.size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((num_classes,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        x = _data_of(ins[0])            # [B, D]
        y = _data_of(ins[1]).reshape(-1).astype(jnp.int32)  # [B]
        B = x.shape[0]
        key = ctx.rng_for(name)
        if neg_distribution is not None:
            dist = jnp.asarray(neg_distribution)
            logits_dist = jnp.log(jnp.clip(dist, 1e-20, 1.0))
            neg = jax.random.categorical(key, logits_dist[None, :],
                                         shape=(B, num_neg_samples))
        else:
            neg = jax.random.randint(key, (B, num_neg_samples), 0, num_classes)
        ids = jnp.concatenate([y[:, None], neg], axis=1)      # [B, 1+k]
        w_rows = p["w"][ids]                                   # [B, 1+k, D]
        logits = jnp.einsum("bd,bkd->bk", x, w_rows)
        if has_bias:
            logits = logits + p["b"][ids]
        labels01 = jnp.concatenate(
            [jnp.ones((B, 1)), jnp.zeros((B, num_neg_samples))], axis=1)
        return ploss.sigmoid_cross_entropy_with_logits(logits, labels01)

    return LayerOutput(name=name, layer_type="nce", inputs=inputs, fn=compute,
                       params=params, size=1, is_cost=True)


@_export
def hsigmoid(input, label, num_classes: int, name: Optional[str] = None,
             param_attr=None, bias_attr=True) -> LayerOutput:
    """Hierarchical sigmoid cost over a complete binary tree (reference:
    hsigmoid_layer/HierarchicalSigmoidLayer.cpp)."""
    inputs = [input, label]
    name = name or unique_name("hsigmoid")
    num_nodes = num_classes - 1
    import math as _math
    code_len = max(1, int(_math.ceil(_math.log2(max(2, num_classes)))))
    params = {"w": ParamSpec((num_nodes, input.size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((num_nodes,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        x = _data_of(ins[0])
        y = _data_of(ins[1]).reshape(-1).astype(jnp.int32)
        # heap path: leaf id = y + num_nodes + 1 (1-based heap); ancestors =
        # successive >>1; bit = node & 1 gives left/right label.
        leaf = y + num_nodes + 1
        losses = 0.0
        node = leaf
        for _ in range(code_len):
            parent = node >> 1
            bit = (node & 1).astype(jnp.float32)      # 1 = right child
            valid = parent >= 1
            idx = jnp.clip(parent - 1, 0, num_nodes - 1)
            logit = jnp.einsum("bd,bd->b", x, p["w"][idx])
            if has_bias:
                logit = logit + p["b"][idx]
            # label 1 for left (bit==0) as in reference's sign convention
            t = 1.0 - bit
            step_loss = jnp.maximum(logit, 0) - logit * t + jnp.log1p(jnp.exp(-jnp.abs(logit)))
            losses = losses + jnp.where(valid, step_loss, 0.0)
            node = parent
        return losses

    return LayerOutput(name=name, layer_type="hsigmoid", inputs=inputs,
                       fn=compute, params=params, size=1, is_cost=True)


def _crf_forward(emissions, mask, transitions, start, stop, labels):
    """Linear-chain CRF negative log-likelihood per sequence.

    emissions [B,T,K], mask [B,T] bool, labels [B,T] int.
    """
    B, T, K = emissions.shape
    lab = labels.astype(jnp.int32)

    # score of the gold path
    first_score = start[lab[:, 0]] + emissions[:, 0, :][jnp.arange(B), lab[:, 0]]

    def score_step(carry, t):
        s, prev = carry
        e = emissions[:, t, :][jnp.arange(B), lab[:, t]]
        tr = transitions[prev, lab[:, t]]
        m = mask[:, t].astype(e.dtype)
        s = s + m * (e + tr)
        prev = jnp.where(mask[:, t], lab[:, t], prev)
        return (s, prev), None

    (gold, last_lab), _ = jax.lax.scan(score_step, (first_score, lab[:, 0]),
                                       jnp.arange(1, T))
    gold = gold + stop[last_lab]

    # log partition via forward algorithm
    alpha0 = start[None, :] + emissions[:, 0, :]

    def fwd_step(alpha, t):
        e = emissions[:, t, :]
        scores = alpha[:, :, None] + transitions[None, :, :] + e[:, None, :]
        new_alpha = jax.nn.logsumexp(scores, axis=1)
        m = mask[:, t][:, None]
        alpha = jnp.where(m, new_alpha, alpha)
        return alpha, None

    alpha, _ = jax.lax.scan(fwd_step, alpha0, jnp.arange(1, T))
    logz = jax.nn.logsumexp(alpha + stop[None, :], axis=-1)
    return logz - gold


def _crf_viterbi(emissions, mask, transitions, start, stop):
    B, T, K = emissions.shape
    alpha0 = start[None, :] + emissions[:, 0, :]

    def vit_step(alpha, t):
        e = emissions[:, t, :]
        scores = alpha[:, :, None] + transitions[None, :, :] + e[:, None, :]
        best_prev = jnp.argmax(scores, axis=1)
        new_alpha = jnp.max(scores, axis=1)
        m = mask[:, t][:, None]
        alpha_out = jnp.where(m, new_alpha, alpha)
        bp = jnp.where(m, best_prev, jnp.broadcast_to(jnp.arange(K)[None, :], (B, K)))
        return alpha_out, bp

    alpha, bps = jax.lax.scan(vit_step, alpha0, jnp.arange(1, T))
    last = jnp.argmax(alpha + stop[None, :], axis=-1)

    def back_step(nxt, bp):
        cur = bp[jnp.arange(B), nxt]
        return cur, nxt

    # reverse scan emits y[t] = state at position t+1 and carries the
    # chain back to position 0 (the final carry) — prepend it, don't
    # re-append `last`
    first, path_rev = jax.lax.scan(back_step, last, bps, reverse=True)
    path = jnp.concatenate([first[None, :], path_rev], axis=0)  # [T, B]
    return jnp.swapaxes(path, 0, 1).astype(jnp.int32)


def _crf_params(size: int, param_attr) -> Dict[str, "ParamSpec"]:
    """CRF parameter table. An explicit ParamAttr.name becomes a PREFIX so
    a crf cost layer and its crf_decoding twin can share the learned
    transitions (the reference shares via parameter_name on both layers)."""
    import dataclasses

    attr = ParamAttr.to_attr(param_attr)

    def per(pname):
        if attr.name:
            return dataclasses.replace(attr, name=f"{attr.name}.{pname}")
        return attr

    return {
        "transitions": ParamSpec((size, size), per("transitions")),
        "start": ParamSpec((size,), per("start")),
        "stop": ParamSpec((size,), per("stop")),
    }


@_export
def crf(input, label, size: int = None, name: Optional[str] = None,
        param_attr=None, **_kw) -> LayerOutput:
    """Linear-chain CRF cost (reference: crf_layer/CRFLayer.cpp,
    LinearChainCRF.cpp — its transition matrix packs start/stop weights; here
    they are separate parameters)."""
    inp, lab = input, label
    _need_seq(inp, "crf")
    size = size or inp.size
    name = name or unique_name("crf")
    params = _crf_params(size, param_attr)

    def compute(ctx, p, ins):
        sb, lb = ins[0], ins[1]
        emissions, mask = sb.to_padded()
        labels, _ = lb.to_padded() if isinstance(lb, SequenceBatch) else (lb, None)
        if labels.ndim == 3:
            labels = labels[..., 0]
        return _crf_forward(emissions, mask, p["transitions"], p["start"],
                            p["stop"], labels)

    return LayerOutput(name=name, layer_type="crf", inputs=[inp, lab],
                       fn=compute, params=params, size=1, is_cost=True)


@_export
def crf_decoding(input, size: int = None, label=None,
                 name: Optional[str] = None, param_attr=None, **_kw) -> LayerOutput:
    """Viterbi decode (reference: crf_decoding_layer). With a label input,
    outputs per-token error like the reference; else the best path ids."""
    inp = input
    _need_seq(inp, "crf_decoding")
    size = size or inp.size
    name = name or unique_name("crf_decoding")
    params = _crf_params(size, param_attr)
    inputs = [inp] + ([label] if label is not None else [])

    def compute(ctx, p, ins):
        sb = ins[0]
        emissions, mask = sb.to_padded()
        path = _crf_viterbi(emissions, mask, p["transitions"], p["start"], p["stop"])
        if label is not None:
            lb = ins[1]
            labels, _ = lb.to_padded() if isinstance(lb, SequenceBatch) else (lb, None)
            if labels.ndim == 3:
                labels = labels[..., 0]
            err = (path != labels.astype(path.dtype)) & mask
            flat = SequenceBatch.from_padded(
                err[..., None].astype(jnp.float32), sb.lengths, capacity=sb.capacity)
            return flat
        flat = SequenceBatch.from_padded(path[..., None], sb.lengths,
                                         capacity=sb.capacity)
        return flat

    return LayerOutput(name=name, layer_type="crf_decoding", inputs=inputs,
                       fn=compute, params=params, size=1, is_sequence=True)


@_export
def ctc(input, label, size: int = None, blank: int = 0, norm_by_times: bool = False,
        name: Optional[str] = None) -> LayerOutput:
    """CTC cost (reference: ctc_layer/CTCLayer.cpp & warp_ctc_layer; the TPU
    path uses a jax-native CTC — optax.ctc_loss — instead of warpctc)."""
    inp, lab = input, label
    _need_seq(inp, "ctc")
    name = name or unique_name("ctc")

    def compute(ctx, p, ins):
        import optax

        sb, lb = ins[0], ins[1]
        logits, mask = sb.to_padded()
        labels, lab_mask = lb.to_padded()
        if labels.ndim == 3:
            labels = labels[..., 0]
        logit_pad = 1.0 - mask.astype(jnp.float32)
        label_pad = 1.0 - lab_mask.astype(jnp.float32)
        loss = optax.ctc_loss(logits, logit_pad, labels.astype(jnp.int32),
                              label_pad, blank_id=blank)
        if norm_by_times:
            loss = loss / jnp.maximum(sb.lengths.astype(loss.dtype), 1.0)
        return loss

    return LayerOutput(name=name, layer_type="ctc", inputs=[inp, lab],
                       fn=compute, size=1, is_cost=True)


@_export
def warp_ctc(input, label, size: int = None, blank: int = 0,
             norm_by_times: bool = False, name: Optional[str] = None) -> LayerOutput:
    """Alias of ctc — warpctc was a CUDA-perf variant; XLA needs no second path."""
    return ctc(input, label, size=size, blank=blank, norm_by_times=norm_by_times,
               name=name or unique_name("warp_ctc"))


# ---------------------------------------------------------------------------
# cost layers
# ---------------------------------------------------------------------------


def _cost_node(name, ltype, inputs, fn) -> LayerOutput:
    return LayerOutput(name=name, layer_type=ltype, inputs=inputs, fn=fn,
                       size=1, is_cost=True)


def _per_example(fn_dense, value, *args):
    """Run a per-row loss on dense or sequence (per-token) input."""
    if isinstance(value, SequenceBatch):
        out = fn_dense(value.data, *[_data_of(a) for a in args])
        masked = jnp.where(value.valid_mask, out, 0.0)
        return value.with_data(masked)
    return fn_dense(value, *[_data_of(a) for a in args])


@_export
def multi_head_attention(query, key=None, value=None, *, num_heads: int,
                         size: int = None, causal: bool = False,
                         name: Optional[str] = None, param_attr=None,
                         layer_attr=None) -> LayerOutput:
    """Multi-head (flash) attention over packed variable-length sequences —
    the long-context extension of the reference's attention helpers
    (networks.py:1304 simple_attention, :1402 dot_product_attention),
    built on the blockwise pallas kernel (ops/attention.py).

    Sequence inputs ride the packed SequenceBatch form: segment ids ARE
    the attention mask (tokens never attend across sequences — the
    padding-free Argument.sequenceStartPositions capability), so no
    [B, T, T] mask is ever materialised. ``causal=True`` adds
    per-sequence causal masking (positions are absolute in the packed
    buffer, combined with segment ids). key/value default to query
    (self-attention); pass an encoder sequence for cross-attention."""
    q_in = query
    k_in = key if key is not None else query
    v_in = value if value is not None else k_in
    _need_seq(q_in, "multi_head_attention")
    _need_seq(k_in, "multi_head_attention")
    _need_seq(v_in, "multi_head_attention")
    # causal masking uses absolute positions in the packed buffer; two
    # independently packed buffers have incomparable positions, so causal
    # cross-attention would silently mask wrong keys
    enforce_that(not (causal and key is not None and key is not query),
                 "causal=True is self-attention only (packed positions "
                 "are incomparable across different key/query buffers)",
                 context="multi_head_attention")
    size = size or q_in.size
    enforce_that(size % num_heads == 0,
                 f"num_heads {num_heads} must divide size {size}",
                 context="multi_head_attention")
    name = name or unique_name("mha")
    attr = ParamAttr.to_attr(param_attr)
    params = {
        "wq": ParamSpec((q_in.size, size), attr),
        "wk": ParamSpec((k_in.size, size), attr),
        "wv": ParamSpec((v_in.size, size), attr),
        "wo": ParamSpec((size, size), attr),
    }
    head_dim = size // num_heads

    def compute(ctx, p, ins):
        from paddle_tpu.ops import attention as pattn

        qs, ks, vs = ins[0], ins[1], ins[2]
        cap_q, cap_k = qs.capacity, ks.capacity
        enforce_that(vs.capacity == cap_k,
                     f"key/value capacities differ ({cap_k} vs "
                     f"{vs.capacity}) — they must come from the same "
                     "feeder bucket", context="multi_head_attention")
        # q/k/v ride bf16 into the flash kernel under the global policy
        # (the kernel accumulates scores/output in f32). The projections
        # still ACCUMULATE in f32 (matmul's preferred_element_type) and
        # round once on the way out — the policy ops/math.py documents.
        qkv_t = pmath.compute_dtype(qs.data)
        q = pmath.matmul(qs.data, p["wq"]).astype(qkv_t).reshape(
            1, cap_q, num_heads, head_dim)
        k = pmath.matmul(ks.data, p["wk"]).astype(qkv_t).reshape(
            1, cap_k, num_heads, head_dim)
        v = pmath.matmul(vs.data, p["wv"]).astype(qkv_t).reshape(
            1, cap_k, num_heads, head_dim)
        out = pattn.flash_attention(
            q, k, v, segment_ids=qs.segment_ids[None, :],
            kv_segment_ids=ks.segment_ids[None, :], causal=causal)
        y = pmath.matmul(out.reshape(cap_q, size), p["wo"])
        y = qs.with_data(y.astype(pmath.dense_activation_dtype()))
        return _apply_extra(ctx, name, y, layer_attr)

    node = LayerOutput(name=name, layer_type="multi_head_attention",
                       inputs=[q_in, k_in, v_in], fn=compute, params=params,
                       size=size, is_sequence=True)
    return node


@_export
class BeamInput:
    """One beam expansion for cross_entropy_over_beam (reference:
    trainer_config_helpers/layers.py BeamInput): candidate scores over the
    expansion's search space, the selected top-k candidate ids, and the
    gold candidate id. ``prev_ids`` (optional) links each selected
    candidate to the beam slot of the PREVIOUS expansion it extends —
    the dense analog of the reference's seqInfo path bookkeeping; with
    it, path scores accumulate across expansions and every expansion's
    scorer receives gradient."""

    def __init__(self, candidate_scores, selected_candidates, gold,
                 prev_ids=None):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold
        self.prev_ids = prev_ids


@_export
def cross_entropy_over_beam(input, name: Optional[str] = None) -> LayerOutput:
    """Training-through-beam cost for learning-to-search models
    (reference: CrossEntropyOverBeam.cpp:131-162 + the
    cross_entropy_over_beam helper). Takes a list of BeamInput (one per
    beam expansion); the cost is -log P(gold path) under a softmax over
    the beam at the expansion where gold falls off (gold joins the
    normalizer as an extra path). Works with kmax_seq_score /
    sub_nested_seq / seq_slice to trim the search space."""
    beams = [input] if isinstance(input, BeamInput) else list(input)
    for b in beams:
        enforce_that(isinstance(b, BeamInput),
                     "cross_entropy_over_beam takes BeamInput(s)",
                     context="cross_entropy_over_beam")
    name = name or unique_name("cross_entropy_over_beam")
    inputs = []
    arity = []
    for b in beams:
        ins_b = [b.candidate_scores, b.selected_candidates, b.gold]
        if b.prev_ids is not None:
            ins_b.append(b.prev_ids)
        arity.append(len(ins_b))
        inputs += ins_b

    def compute(ctx, p, ins):
        triples = []
        i = 0
        for n in arity:
            scores = _data_of(ins[i])
            selected = _data_of(ins[i + 1])
            gold = _data_of(ins[i + 2]).reshape(-1)
            if scores.ndim == 1:
                scores = scores.reshape(1, -1)
            if selected.ndim == 1:
                selected = selected.reshape(1, -1)
            entry = [scores, selected.astype(jnp.int32), gold]
            if n == 4:
                prev = _data_of(ins[i + 3])
                if prev.ndim == 1:
                    prev = prev.reshape(1, -1)
                entry.append(prev.astype(jnp.int32))
            triples.append(tuple(entry))
            i += n
        return ploss.cross_entropy_over_beam(triples)

    return _cost_node(name, "cross_entropy_over_beam", inputs, compute)


@_export
def classification_cost(input, label, weight=None, name: Optional[str] = None,
                        **_kw) -> LayerOutput:
    """Softmax cross-entropy on logits (reference: classification_cost —
    the fused softmax+xent path, CostLayer.cpp MultiClassCrossEntropy).

    NOTE: `input` should be pre-softmax logits; if the final layer used a
    softmax activation the reference computed log on probabilities — we fuse
    for numerical stability either way."""
    name = name or unique_name("classification_cost")
    inputs = [input, label] + ([weight] if weight is not None else [])

    def compute(ctx, p, ins):
        logits, lab = ins[0], ins[1]

        def f(lg, lb):
            lb = lb.reshape(lb.shape[0]).astype(jnp.int32)
            return ploss.softmax_cross_entropy(lg, lb)

        out = _per_example(f, logits, lab)
        if weight is not None:
            w = _data_of(ins[2]).reshape(-1)
            out = _like(out, _data_of(out) * w) if isinstance(out, SequenceBatch) else out * w
        return out

    return _cost_node(name, "classification_cost", inputs, compute)


@_export
def cross_entropy_cost(input, label, name: Optional[str] = None, **_kw) -> LayerOutput:
    """Cross entropy on probabilities (reference: cross_entropy)."""
    name = name or unique_name("cross_entropy")

    def compute(ctx, p, ins):
        def f(pr, lb):
            lb = lb.reshape(lb.shape[0]).astype(jnp.int32)
            picked = jnp.take_along_axis(pr, lb[:, None], axis=-1)[:, 0]
            return -jnp.log(jnp.clip(picked, 1e-10, 1.0))

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "cross_entropy", [input, label], compute)


@_export
def cross_entropy_with_selfnorm_cost(input, label, softmax_selfnorm_alpha: float = 0.1,
                                     name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("cross_entropy_with_selfnorm")

    def compute(ctx, p, ins):
        def f(lg, lb):
            lb = lb.reshape(lb.shape[0]).astype(jnp.int32)
            return ploss.cross_entropy_with_selfnorm(lg, lb, softmax_selfnorm_alpha)

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "cross_entropy_with_selfnorm", [input, label], compute)


@_export
def square_error_cost(input, label, name: Optional[str] = None, **_kw) -> LayerOutput:
    """0.5*||p-t||^2 (reference: square_error_cost / regression_cost)."""
    name = name or unique_name("square_error")

    def compute(ctx, p, ins):
        def f(a, b):
            return ploss.square_error(a, b.reshape(a.shape))

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "square_error", [input, label], compute)


regression_cost = square_error_cost
__all__.append("regression_cost")


@_export
def multi_binary_label_cross_entropy_cost(input, label,
                                          name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("multi_binary_label_xent")

    def compute(ctx, p, ins):
        def f(lg, lb):
            # an integer [B] label against [B, 1] logits must not broadcast
            # to [B, B]
            if lb.size == lg.size:
                lb = lb.reshape(lg.shape)
            return ploss.multi_binary_label_cross_entropy(
                lg, lb.astype(lg.dtype))

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "multi_binary_label_xent", [input, label], compute)


@_export
def soft_binary_class_cross_entropy_cost(input, label,
                                         name: Optional[str] = None) -> LayerOutput:
    """Soft-label binary xent on probabilities (reference:
    SoftBinaryClassCrossEntropy)."""
    name = name or unique_name("soft_binary_xent")

    def compute(ctx, p, ins):
        def f(pr, lb):
            pr = jnp.clip(pr, 1e-7, 1 - 1e-7)
            return -jnp.sum(lb * jnp.log(pr) + (1 - lb) * jnp.log(1 - pr), axis=-1)

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "soft_binary_xent", [input, label], compute)


@_export
def rank_cost(left, right, label, weight=None, name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("rank_cost")
    inputs = [left, right, label] + ([weight] if weight is not None else [])

    def compute(ctx, p, ins):
        w = _data_of(ins[3]) if weight is not None else None
        return ploss.rank_cost(_data_of(ins[0]), _data_of(ins[1]),
                               _data_of(ins[2]), w)

    return _cost_node(name, "rank_cost", inputs, compute)


@_export
def lambda_cost(input, score, NDCG_num: int = 5, max_sort_size: int = -1,
                name: Optional[str] = None) -> LayerOutput:
    """LambdaRank cost over each query's documents (reference: lambda_cost /
    LambdaCost.cpp). input: sequence of scores, score: sequence of relevance."""
    name = name or unique_name("lambda_cost")
    _need_seq(input, "lambda_cost")

    def compute(ctx, p, ins):
        sb_pred, sb_rel = ins[0], ins[1]
        pred, mask = sb_pred.to_padded()
        rel, _ = sb_rel.to_padded()
        pred = pred[..., 0] if pred.ndim == 3 else pred
        rel = rel[..., 0] if rel.ndim == 3 else rel
        B, T = pred.shape
        # ideal DCG from top-NDCG_num relevances
        sorted_rel = -jnp.sort(-jnp.where(mask, rel, -jnp.inf), axis=1)
        k = jnp.arange(T)
        disc = 1.0 / jnp.log2(k + 2.0)
        topk_mask = (k < NDCG_num)[None, :]
        gains = (jnp.power(2.0, jnp.where(jnp.isfinite(sorted_rel), sorted_rel, 0.0)) - 1.0)
        idcg = jnp.sum(gains * disc * topk_mask * jnp.isfinite(sorted_rel), axis=1)
        # pairwise lambda loss approximation: logistic on score diffs weighted
        # by |delta NDCG| of swapping
        sdiff = pred[:, :, None] - pred[:, None, :]
        rdiff = rel[:, :, None] - rel[:, None, :]
        pair_mask = mask[:, :, None] & mask[:, None, :] & (rdiff > 0)
        logistic = jnp.log1p(jnp.exp(-sdiff))
        loss = jnp.sum(jnp.where(pair_mask, logistic, 0.0), axis=(1, 2))
        denom = jnp.maximum(jnp.sum(pair_mask, axis=(1, 2)), 1)
        return loss / denom / jnp.maximum(idcg, 1.0)

    return _cost_node(name, "lambda_cost", [input, score], compute)


@_export
def huber_regression_cost(input, label, delta: float = 1.0,
                          name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("huber_regression")

    def compute(ctx, p, ins):
        def f(a, b):
            return ploss.huber_regression(a, b.reshape(a.shape), delta)

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "huber_regression", [input, label], compute)


@_export
def huber_classification_cost(input, label, name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("huber_classification")

    def compute(ctx, p, ins):
        return _per_example(ploss.huber_classification, ins[0], ins[1])

    return _cost_node(name, "huber_classification", [input, label], compute)


@_export
def smooth_l1_cost(input, label, name: Optional[str] = None) -> LayerOutput:
    name = name or unique_name("smooth_l1")

    def compute(ctx, p, ins):
        def f(a, b):
            return ploss.smooth_l1(a, b.reshape(a.shape))

        return _per_example(f, ins[0], ins[1])

    return _cost_node(name, "smooth_l1", [input, label], compute)


@_export
def moe_ffn(input, num_experts: int = 0, expert_hidden: int = 0,
            capacity_factor: float = 1.25, aux_weight: float = 0.01,
            top_k: int = 1, config=None,
            name: Optional[str] = None, param_attr=None):
    """Mixture-of-Experts FFN layer (new-build extension; parallel/moe.py
    holds the kernels): Switch-style top-1 — or, with ``top_k=2``,
    GShard-style top-2 with renormalized gates — routing into per-expert
    two-layer FFNs. Returns ``(out, aux_cost)`` — add ``aux_cost`` to the
    SGD cost list (multi-cost training, the MultiNetwork path) so routing
    stays load-balanced; its value is ``aux_weight *`` the Switch
    balance loss.

    ``config=`` takes a :class:`paddle_tpu.parallel.moe.MoEConfig` in
    place of the scalar kwargs (explicit kwargs win where both are
    given).  The expert weights declare leading-dim sharding over the
    config's ``expert`` axis (MoEConfig.param_plan through the one
    placement layer), so on an expert mesh each device holds only its
    E/N experts — on a mesh WITHOUT that axis the declared dim falls
    back to replicated and the dense path runs.

    Under a mesh with an ``'expert'`` axis the experts shard and dispatch
    rides two all_to_alls (parallel.moe.moe_ffn); otherwise the dense
    single-device formulation runs. Over-capacity tokens pass through as
    zeros (callers add the residual). On packed SequenceBatch inputs the
    padding slots also route (they waste a little capacity; their outputs
    are zeroed)."""
    import dataclasses

    from paddle_tpu.parallel import moe as pmoe

    inp = input
    axis = "expert"
    if config is not None:
        num_experts = int(num_experts or config.num_experts)
        expert_hidden = int(expert_hidden or config.expert_hidden)
        capacity_factor = float(config.capacity_factor)
        top_k = int(config.top_k)
        aux_weight = float(config.aux_weight)
        axis = str(config.axis)
        if expert_hidden <= 0:
            # MoEConfig.expert_hidden == 0: derive from the model width
            expert_hidden = 4 * int(inp.size)
    if num_experts <= 0 or expert_hidden <= 0:
        raise ValueError("moe_ffn needs num_experts/expert_hidden > 0 "
                         "(directly or via config=MoEConfig(...))")

    name = name or unique_name("moe_ffn")
    attr = ParamAttr.to_attr(param_attr)

    def _expert(base, ndim):
        # stacked [E, ...] expert weights: leading dim over the expert
        # axis unless the caller pinned a sharding explicitly
        if base.sharding is not None:
            return base
        return dataclasses.replace(
            base, sharding=(axis,) + (None,) * (ndim - 1))

    d = inp.size
    params = {
        "router": ParamSpec((d, num_experts), attr),
        "w1": ParamSpec((num_experts, d, expert_hidden), _expert(attr, 3)),
        "b1": ParamSpec((num_experts, expert_hidden),
                        _expert(ParamAttr.to_attr(None), 2)),
        "w2": ParamSpec((num_experts, expert_hidden, d), _expert(attr, 3)),
        "b2": ParamSpec((num_experts, d),
                        _expert(ParamAttr.to_attr(None), 2)),
    }

    def compute(ctx, p, ins):
        v = ins[0]
        x = _data_of(v)
        mp = pmoe.MoEParams(p["router"], p["w1"], p["b1"], p["w2"], p["b2"])
        mesh = ctx.mesh
        if mesh is not None and axis in tuple(
                getattr(mesh, "axis_names", ())):
            y, aux = pmoe.moe_ffn(mesh, x, mp, axis=axis,
                                  capacity_factor=capacity_factor,
                                  top_k=top_k)
        else:
            y, aux = pmoe.moe_ffn_reference(
                x, mp, capacity_factor=capacity_factor, top_k=top_k)
        if isinstance(v, SequenceBatch):
            y = jnp.where(v.valid_mask[:, None], y, 0)
        out = _like(v, y.astype(pmath.dense_activation_dtype()))
        return (out, aux * aux_weight)

    core = LayerOutput(name=name, layer_type="moe_ffn", inputs=[inp],
                       fn=compute, params=params, size=d,
                       is_sequence=inp.is_sequence)

    def pick_out(ctx, p, ins):
        return ins[0][0]

    def pick_aux(ctx, p, ins):
        return jnp.reshape(ins[0][1], (1,))

    out_node = LayerOutput(name=f"{name}_out", layer_type="moe_out",
                           inputs=[core], fn=pick_out, size=d,
                           is_sequence=inp.is_sequence)
    aux_node = LayerOutput(name=f"{name}_aux", layer_type="moe_aux",
                           inputs=[core], fn=pick_aux, size=1, is_cost=True)
    return out_node, aux_node


@_export
def lm_head_cost(input, label, vocab_size: int, name: Optional[str] = None,
                 param_attr=None, bias_attr=True,
                 block_size: int = 4096) -> LayerOutput:
    """Fused LM-head + softmax cross-entropy over a large vocabulary — the
    TPU-first replacement for ``fc(vocab) -> classification_cost`` on LM
    heads (new-build extension; the reference's era had selective_fc/NCE
    for big-softmax costs). Computes per-token loss in vocab blocks with
    an online logsumexp, so the [tokens, vocab] logits matrix never
    reaches HBM in forward OR backward (ops/losses.py:lm_head_xent) —
    at d=2048/V=32k bench shapes that is ~0.5-1 GB of traffic saved per
    step and the activation memory to run bigger batches. Equivalent to
    the unfused pair to f32 rounding (test_network_compare pins it)."""
    inputs = [input, label]
    name = name or unique_name("lm_head_cost")
    params = {"w": ParamSpec((input.size, vocab_size),
                             ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((vocab_size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        def f(x, lb):
            return ploss.lm_head_xent(x, p["w"], p.get("b"),
                                      lb.reshape(x.shape[0]),
                                      block_v=block_size)

        return _per_example(f, ins[0], ins[1])

    return LayerOutput(name=name, layer_type="lm_head_cost", inputs=inputs,
                       fn=compute, params=params, size=1, is_cost=True)


@_export
def sum_cost(input, name: Optional[str] = None) -> LayerOutput:
    """Sum of the input as a cost (reference: sum_cost/SumCostLayer)."""
    name = name or unique_name("sum_cost")

    def compute(ctx, p, ins):
        v = ins[0]
        d = _data_of(v)
        out = jnp.sum(d, axis=tuple(range(1, d.ndim)))
        if isinstance(v, SequenceBatch):
            out = jnp.where(v.valid_mask, out, 0.0)
            seg = jnp.where(v.valid_mask, v.segment_ids, v.num_seqs)
            return jax.ops.segment_sum(out, seg, num_segments=v.num_seqs + 1)[:v.num_seqs]
        return out

    return _cost_node(name, "sum_cost", [input], compute)


@_export
def eos(input, eos_id: int, name: Optional[str] = None) -> LayerOutput:
    """Truncate sequences at the end-of-sequence id (reference: eos_layer)."""
    inp = input
    _need_seq(inp, "eos")
    name = name or unique_name("eos")

    def compute(ctx, p, ins):
        sb: SequenceBatch = ins[0]
        ids, mask = sb.to_padded()
        tok = ids[..., 0] if ids.ndim == 3 else ids
        is_eos = (tok == eos_id) & mask
        # new length = index of first eos (exclusive), else original length
        T = tok.shape[1]
        first_eos = jnp.argmax(is_eos, axis=1)
        has_eos = jnp.any(is_eos, axis=1)
        new_len = jnp.where(has_eos, first_eos, sb.lengths).astype(jnp.int32)
        return pseq.seq_slice(sb, jnp.zeros_like(new_len), new_len)

    return LayerOutput(name=name, layer_type="eos", inputs=[inp], fn=compute,
                       size=inp.size, is_sequence=True)


@_export
def dotmul_bcast(a, b, name: Optional[str] = None) -> LayerOutput:
    """Tokenwise multiply with broadcasting over the feature dim — used to
    scale sequence tokens by per-token scalar weights (attention)."""
    name = name or unique_name("dotmul_bcast")

    def compute(ctx, p, ins):
        va, vb = _data_of(ins[0]), _data_of(ins[1])
        if vb.ndim < va.ndim:
            vb = vb[..., None]
        return _like(ins[0], va * vb)

    return LayerOutput(name=name, layer_type="dotmul_bcast", inputs=[a, b],
                       fn=compute, size=a.size, is_sequence=a.is_sequence)


# ---------------------------------------------------------------------------
# recurrent group surface (paddle_tpu/recurrent.py) + step cells
# ---------------------------------------------------------------------------

from paddle_tpu.recurrent import (StaticInput, SubsequenceInput,  # noqa: E402
                                  memory, recurrent_group)

__all__ += ["StaticInput", "SubsequenceInput", "memory",
            "recurrent_group", "gru_step", "lstm_step"]


def gru_step(input, output_mem, size: int = None, act=None, gate_act=None,
             name: Optional[str] = None, param_attr=None,
             bias_attr=True) -> LayerOutput:
    """One GRU step for use inside recurrent_group (reference:
    gru_step_layer/GruStepLayer.cpp). input: [B, 3*size] projected x_t;
    output_mem: the memory holding h_{t-1}."""
    size = size or output_mem.size
    name = name or unique_name("gru_step")
    params = {"w": ParamSpec((size, 3 * size), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((3 * size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))
    cand = _resolve_act(act or "tanh")
    gate = _resolve_act(gate_act or "sigmoid")

    def compute(ctx, p, ins):
        x, h = _data_of(ins[0]), _data_of(ins[1])
        return prnn.gru_cell(x, h, p["w"], p.get("b"), gate_act=gate.fn,
                             cand_act=cand.fn)

    return LayerOutput(name=name, layer_type="gru_step",
                       inputs=[input, output_mem], fn=compute, params=params,
                       size=size, is_sequence=False)


def lstm_step(input, state_mem, output_mem=None, size: int = None, act=None,
              gate_act=None, state_act=None, name: Optional[str] = None,
              param_attr=None, bias_attr=True) -> LayerOutput:
    """One LSTM step (reference: lstm_step_layer). input: [B, 4*size]
    pre-projected; state_mem: memory of c_{t-1}; output_mem: memory of
    h_{t-1}. Returns h_t; ``.state`` output is exposed as a second node via
    lstm_step_state()."""
    size = size or state_mem.size
    name = name or unique_name("lstm_step")
    # the h-recurrence weight only exists when the step actually carries an
    # h memory; without output_mem the recurrence must be pre-projected into
    # ``input`` (the reference lstm_step contract) and a weight here would be
    # a dead randomly-initialised parameter
    params = {}
    if output_mem is not None:
        params["w"] = ParamSpec((size, 4 * size), ParamAttr.to_attr(param_attr))
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((4 * size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))
    o_act = _resolve_act(act or "tanh")
    g_act = _resolve_act(gate_act or "sigmoid")
    s_act = _resolve_act(state_act or "tanh")
    inputs = [input, state_mem] + ([output_mem] if output_mem is not None else [])

    def compute(ctx, p, ins):
        x, c = _data_of(ins[0]), _data_of(ins[1])
        h = _data_of(ins[2]) if len(ins) > 2 else jnp.zeros_like(c)
        new_h, st = prnn.lstm_cell(x, prnn.LSTMState(h, c), p.get("w"),
                                   p.get("b"),
                                   gate_act=g_act.fn, cell_act=s_act.fn,
                                   out_act=o_act.fn)
        # pack h and c side by side; callers split with lstm_step_state
        return jnp.concatenate([new_h, st.c], axis=-1)

    node = LayerOutput(name=name, layer_type="lstm_step", inputs=inputs,
                       fn=compute, params=params, size=2 * size,
                       is_sequence=False)
    node.lstm_size = size
    return node


def lstm_step_output(step_node, name: Optional[str] = None) -> LayerOutput:
    """h_t half of an lstm_step node."""
    size = step_node.lstm_size
    name = name or unique_name("lstm_h")

    def compute(ctx, p, ins):
        return _data_of(ins[0])[..., :size]

    return LayerOutput(name=name, layer_type="lstm_h", inputs=[step_node],
                       fn=compute, size=size, is_sequence=False)


def lstm_step_state(step_node, name: Optional[str] = None) -> LayerOutput:
    """c_t half of an lstm_step node."""
    size = step_node.lstm_size
    name = name or unique_name("lstm_c")

    def compute(ctx, p, ins):
        return _data_of(ins[0])[..., size:]

    return LayerOutput(name=name, layer_type="lstm_c", inputs=[step_node],
                       fn=compute, size=size, is_sequence=False)


__all__ += ["lstm_step_output", "lstm_step_state"]


# ---------------------------------------------------------------------------
# round-2 completeness batch: the remaining registered layer types of the
# reference (REGISTER_LAYER list, SURVEY.md §2.1 "Layers (95 types)")
# ---------------------------------------------------------------------------


@_export
def prelu(input, partial_sum: int = 1, param_attr=None,
          name: Optional[str] = None) -> LayerOutput:
    """Parametric ReLU; one slope per group of `partial_sum` features
    (reference: prelu_layer → ParameterReluLayer.cpp)."""
    inp = input
    name = name or unique_name("prelu")
    enforce_that(inp.size % partial_sum == 0,
                 "prelu partial_sum must divide input size", context="prelu")
    n_slopes = inp.size // partial_sum
    params = {"w": ParamSpec((n_slopes,), ParamAttr.to_attr(param_attr))}

    def compute(ctx, p, ins):
        v = ins[0]
        x = _data_of(v)
        flat = x.reshape(x.shape[0], n_slopes, partial_sum)
        slope = p["w"].reshape(1, n_slopes, 1)
        y = jnp.where(flat >= 0, flat, slope * flat).reshape(x.shape)
        return _like(v, y)

    node = LayerOutput(name=name, layer_type="prelu", inputs=[inp],
                       fn=compute, params=params, size=inp.size,
                       is_sequence=inp.is_sequence)
    return _propagate_img_shape(node, inp)


@_export
def scale_shift(input, param_attr=None, bias_attr=True,
                name: Optional[str] = None) -> LayerOutput:
    """y = w * x + b with scalar w, b (reference: scale_shift_layer →
    ScaleShiftLayer.cpp)."""
    inp = input
    name = name or unique_name("scale_shift")
    params = {"w": ParamSpec((1,), ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((1,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        v = ins[0]
        y = _data_of(v) * p["w"][0]
        if has_bias:
            y = y + p["b"][0]
        return _like(v, y)

    return LayerOutput(name=name, layer_type="scale_shift", inputs=[inp],
                       fn=compute, params=params, size=inp.size,
                       is_sequence=inp.is_sequence)


@_export
def data_norm(input, mean=None, std=None, mode: str = "z-score",
              name: Optional[str] = None) -> LayerOutput:
    """Input normalization with fixed statistics (reference: data_norm_layer
    → DataNormLayer.cpp; stats are precomputed, never trained).

    mean/std are python arrays or scalars; mode ∈ {z-score, min-max,
    decimal-scaling} (min-max interprets mean/std as min/range)."""
    inp = input
    name = name or unique_name("data_norm")
    mean_a = jnp.asarray(0.0 if mean is None else mean, jnp.float32)
    std_a = jnp.asarray(1.0 if std is None else std, jnp.float32)

    def compute(ctx, p, ins):
        v = ins[0]
        x = _data_of(v)
        if mode == "z-score":
            y = (x - mean_a) / jnp.maximum(std_a, 1e-8)
        elif mode == "min-max":
            y = (x - mean_a) / jnp.maximum(std_a, 1e-8)
        elif mode == "decimal-scaling":
            y = x / jnp.power(10.0, jnp.ceil(jnp.log10(
                jnp.maximum(std_a, 1e-8))))
        else:
            raise EnforceError(f"bad data_norm mode {mode}", context="data_norm")
        return _like(v, y)

    return LayerOutput(name=name, layer_type="data_norm", inputs=[inp],
                       fn=compute, size=inp.size,
                       is_sequence=inp.is_sequence)


@_export
def trans(input, name: Optional[str] = None) -> LayerOutput:
    """Transpose the (flattened) feature matrix of a non-sequence batch
    (reference: trans_layer → TransLayer.cpp: batch-size x size matrix
    transposed). Output batch dim becomes the feature dim."""
    inp = input
    name = name or unique_name("trans")

    def compute(ctx, p, ins):
        return _data_of(ins[0]).T

    return LayerOutput(name=name, layer_type="trans", inputs=[inp],
                       fn=compute, size=None, is_sequence=False)


@_export
def switch_order(input, reshape_to=("h", "w", "c"),
                 name: Optional[str] = None) -> LayerOutput:
    """Switch image memory layout between HWC and CHW flattenings
    (reference: switch_order_layer → SwitchOrderLayer.cpp)."""
    inp = input
    name = name or unique_name("switch_order")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "switch_order needs image shape",
                 context="switch_order")
    h, w, c = in_shape
    to_hwc = tuple(reshape_to) == ("h", "w", "c")

    def compute(ctx, p, ins):
        x = _data_of(ins[0])
        n = x.shape[0]
        if to_hwc:   # stored CHW → emit HWC
            y = x.reshape(n, c, h, w).transpose(0, 2, 3, 1)
        else:        # stored HWC → emit CHW
            y = x.reshape(n, h, w, c).transpose(0, 3, 1, 2)
        return y.reshape(n, -1)

    node = LayerOutput(name=name, layer_type="switch_order", inputs=[inp],
                       fn=compute, size=inp.size)
    node.img_shape = (h, w, c)
    return node


@_export
def tensor(a, b, size: int, act=None, param_attr=None,
           name: Optional[str] = None) -> LayerOutput:
    """Bilinear tensor product: out[k] = a · W_k · bᵀ (reference:
    tensor_layer → TensorLayer.cpp)."""
    name = name or unique_name("tensor")
    activation = _resolve_act(act)
    params = {"w": ParamSpec((size, a.size, b.size),
                             ParamAttr.to_attr(param_attr))}

    def compute(ctx, p, ins):
        x, y = _data_of(ins[0]), _data_of(ins[1])
        out = jnp.einsum("bi,kij,bj->bk", x, p["w"], y)
        return _apply_act(activation, out)

    return LayerOutput(name=name, layer_type="tensor", inputs=[a, b],
                       fn=compute, params=params, size=size)


@_export
def out_prod(a, b, name: Optional[str] = None) -> LayerOutput:
    """Row-wise outer product, flattened (reference: out_prod_layer →
    OuterProdLayer.cpp)."""
    name = name or unique_name("out_prod")

    def compute(ctx, p, ins):
        x, y = _data_of(ins[0]), _data_of(ins[1])
        return jnp.einsum("bi,bj->bij", x, y).reshape(x.shape[0], -1)

    return LayerOutput(name=name, layer_type="out_prod", inputs=[a, b],
                       fn=compute, size=a.size * b.size)


@_export
def multiplex(index, inputs, name: Optional[str] = None) -> LayerOutput:
    """Row-wise select among candidate layers by index layer (reference:
    multiplex_layer → MultiplexLayer.cpp)."""
    cands = _as_list(inputs)
    name = name or unique_name("multiplex")

    def compute(ctx, p, ins):
        idx = _data_of(ins[0]).reshape(-1).astype(jnp.int32)
        stack = jnp.stack([_data_of(v) for v in ins[1:]], axis=0)  # [K,B,D]
        return jnp.take_along_axis(
            stack, idx[None, :, None], axis=0)[0]

    return LayerOutput(name=name, layer_type="multiplex",
                       inputs=[index] + cands, fn=compute,
                       size=cands[0].size)


@_export
def conv_shift(a, b, name: Optional[str] = None) -> LayerOutput:
    """Circular convolution of each row of `a` with the (odd-width) kernel
    rows of `b` (reference: conv_shift_layer → ConvShiftLayer.cpp; used by
    NTM-style addressing)."""
    name = name or unique_name("conv_shift")
    enforce_that(b.size % 2 == 1, "conv_shift kernel width must be odd",
                 context="conv_shift")
    half = b.size // 2

    def compute(ctx, p, ins):
        x, k = _data_of(ins[0]), _data_of(ins[1])
        m = x.shape[1]
        shifts = [jnp.roll(x, half - j, axis=1) for j in range(k.shape[1])]
        stack = jnp.stack(shifts, axis=-1)            # [B, M, K]
        return jnp.einsum("bmk,bk->bm", stack, k)

    return LayerOutput(name=name, layer_type="conv_shift", inputs=[a, b],
                       fn=compute, size=a.size)


@_export
def linear_comb(weights, vectors, size: int,
                name: Optional[str] = None) -> LayerOutput:
    """Weighted combination of M sub-vectors: out = Σ_m w[:,m]·x[:,m,:]
    (reference: linear_comb_layer / convex_comb_layer →
    LinearChainCRF... LinearCombLayer.cpp)."""
    name = name or unique_name("linear_comb")

    def compute(ctx, p, ins):
        w, x = _data_of(ins[0]), _data_of(ins[1])
        m = w.shape[1]
        return jnp.einsum("bm,bmd->bd", w, x.reshape(x.shape[0], m, size))

    return LayerOutput(name=name, layer_type="linear_comb",
                       inputs=[weights, vectors], fn=compute, size=size)


@_export
def convex_comb(weights, vectors, size: int,
                name: Optional[str] = None) -> LayerOutput:
    """Alias of linear_comb (reference registers convex_comb as the same
    layer)."""
    return linear_comb(weights, vectors, size, name=name)


@_export
def cos_vm(a, b, size: int, scale: float = 1.0,
           name: Optional[str] = None) -> LayerOutput:
    """Cosine similarity of vector `a` against each of the M rows packed in
    `b` (reference: cos_vm → CosSimVecMatLayer.cpp)."""
    name = name or unique_name("cos_vm")

    def compute(ctx, p, ins):
        x, y = _data_of(ins[0]), _data_of(ins[1])
        m = y.shape[1] // x.shape[1]
        ym = y.reshape(y.shape[0], m, x.shape[1])
        num = jnp.einsum("bd,bmd->bm", x, ym)
        den = (jnp.linalg.norm(x, axis=1, keepdims=True)
               * jnp.linalg.norm(ym, axis=2))
        return scale * num / jnp.maximum(den, 1e-8)

    return LayerOutput(name=name, layer_type="cos_vm", inputs=[a, b],
                       fn=compute, size=size)


@_export
def row_conv(input, context_len: int, act=None, param_attr=None,
             name: Optional[str] = None) -> LayerOutput:
    """Lookahead row convolution over future frames within each sequence
    (reference: row_conv_layer → RowConvLayer.cpp, Deep Speech 2)."""
    inp = input
    _need_seq(inp, "row_conv")
    name = name or unique_name("row_conv")
    activation = _resolve_act(act)
    params = {"w": ParamSpec((context_len, inp.size),
                             ParamAttr.to_attr(param_attr))}

    def compute(ctx, p, ins):
        sb = ins[0]
        x, seg = sb.data, sb.segment_ids
        total = jnp.zeros_like(x)
        cap = x.shape[0]
        for j in range(context_len):
            shifted = jnp.concatenate(
                [x[j:], jnp.zeros((j,) + x.shape[1:], x.dtype)], axis=0)
            seg_sh = jnp.concatenate(
                [seg[j:], jnp.full((j,), -1, seg.dtype)], axis=0)
            ok = (seg_sh == seg)[:, None]
            total = total + jnp.where(ok, shifted * p["w"][j][None, :], 0.0)
        return sb.with_data(_apply_act(activation, total))

    return LayerOutput(name=name, layer_type="row_conv", inputs=[inp],
                       fn=compute, params=params, size=inp.size,
                       is_sequence=True)


@_export
def subseq(input, offsets, sizes, name: Optional[str] = None) -> LayerOutput:
    """Per-sequence sub-range [offset, offset+size) (reference: subseq →
    SubSequenceLayer.cpp); offsets/sizes are int layers, one per sequence."""
    inp = input
    _need_seq(inp, "subseq")
    name = name or unique_name("subseq")

    def compute(ctx, p, ins):
        sb = ins[0]
        s = _data_of(ins[1]).reshape(-1).astype(jnp.int32)
        n = _data_of(ins[2]).reshape(-1).astype(jnp.int32)
        return pseq.seq_slice(sb, s, s + n)

    return LayerOutput(name=name, layer_type="subseq",
                       inputs=[inp, offsets, sizes], fn=compute,
                       size=inp.size, is_sequence=True)


@_export
def featmap_expand(input, num_filters: int, as_row_vector: bool = True,
                   name: Optional[str] = None) -> LayerOutput:
    """Tile each feature map `num_filters` times (reference:
    featmap_expand → FeatureMapExpandLayer.cpp)."""
    inp = input
    name = name or unique_name("featmap_expand")

    def compute(ctx, p, ins):
        v = ins[0]
        x = _data_of(v)
        if as_row_vector:
            y = jnp.tile(x, (1, num_filters))
        else:
            y = jnp.repeat(x, num_filters, axis=1)
        return _like(v, y)

    return LayerOutput(name=name, layer_type="featmap_expand", inputs=[inp],
                       fn=compute, size=inp.size * num_filters,
                       is_sequence=inp.is_sequence)


@_export
def get_output(input, arg_name: str = "default",
               name: Optional[str] = None) -> LayerOutput:
    """Expose a named internal output of a multi-output layer (reference:
    get_output_layer → GetOutputLayer.cpp). For lstm step nodes,
    arg_name="state" selects c_t (the reference's 'state' output)."""
    if arg_name in ("state", "cell") and getattr(input, "lstm_size", None):
        return lstm_step_state(input, name=name)
    inp = input
    name = name or unique_name("get_output")

    def compute(ctx, p, ins):
        return ins[0]

    node = LayerOutput(name=name, layer_type="get_output", inputs=[inp],
                       fn=compute, size=inp.size,
                       is_sequence=inp.is_sequence)
    return _propagate_img_shape(node, inp)


@_export
def print_layer(input, format: Optional[str] = None,
                name: Optional[str] = None) -> LayerOutput:
    """Debug-print the input at step time (reference: print layer →
    PrintLayer.cpp). jax.debug.print fires from inside the compiled
    program; the layer passes its input through unchanged."""
    inp = input
    name = name or unique_name("print")
    fmt = format or (name + ": {x}")

    def compute(ctx, p, ins):
        v = ins[0]
        jax.debug.print(fmt, x=_data_of(v))
        return v

    node = LayerOutput(name=name, layer_type="print", inputs=[inp],
                       fn=compute, size=inp.size,
                       is_sequence=inp.is_sequence)
    return _propagate_img_shape(node, inp)


# ---------------------------------------------------------------------------
# 3-D convolution stack (reference: Conv3DLayer/DeConv3DLayer/Pool3DLayer)
# ---------------------------------------------------------------------------


def _vol_shape_of(node: LayerOutput):
    """(D, H, W, C) metadata threaded through the 3-D stack."""
    return getattr(node, "vol_shape", None)


@_export
def img_conv3d(input, filter_size, num_filters: int, num_channels=None,
               stride: int = 1, padding: int = 0, act=None,
               bias_attr=True, param_attr=None, trans: bool = False,
               depth: int = None, height: int = None, width: int = None,
               name: Optional[str] = None) -> LayerOutput:
    """3-D (de)convolution, NDHWC on the MXU (reference: conv3d/deconv3d →
    Conv3DLayer.cpp / DeConv3DLayer.cpp)."""
    inp = input
    name = name or unique_name("conv3d")
    activation = _resolve_act(act)
    vol = _vol_shape_of(inp)
    if vol is None:
        enforce_that(None not in (depth, height, width, num_channels),
                     "img_conv3d needs vol shape metadata or "
                     "depth/height/width/num_channels", context="conv3d")
        vol = (depth, height, width, num_channels)
    d, h, w, c = vol
    k = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    if trans:
        od = (d - 1) * stride + k[0] - 2 * padding
        oh = (h - 1) * stride + k[1] - 2 * padding
        ow = (w - 1) * stride + k[2] - 2 * padding
    else:
        od = _conv_out_dim(d, k[0], padding, stride)
        oh = _conv_out_dim(h, k[1], padding, stride)
        ow = _conv_out_dim(w, k[2], padding, stride)
    wshape = k + ((num_filters, c) if trans else (c, num_filters))
    params = {"w": ParamSpec(wshape, ParamAttr.to_attr(param_attr))}
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((num_filters,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        x = _data_of(ins[0]).reshape(-1, d, h, w, c)
        if trans:
            # lhs_dilation = fractional stride; k-1-p pads convert to the
            # equivalent forward conv (same scheme as ops/conv.py 2-D path)
            wk = jnp.flip(p["w"], (0, 1, 2)).transpose(0, 1, 2, 4, 3)
            y = jax.lax.conv_general_dilated(
                x, wk, window_strides=(1, 1, 1),
                padding=[(kk - 1 - padding, kk - 1 - padding) for kk in k],
                lhs_dilation=(stride,) * 3,
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        else:
            y = pconv.conv3d(x, p["w"], stride=stride, padding=padding)
        if has_bias:
            y = y + p["b"]
        y = _apply_act(activation, y)
        return _apply_extra(ctx, name, y.reshape(y.shape[0], -1), None)

    node = LayerOutput(name=name, layer_type="conv3d", inputs=[inp],
                       fn=compute, params=params,
                       size=od * oh * ow * num_filters)
    node.vol_shape = (od, oh, ow, num_filters)
    return node


@_export
def img_pool3d(input, pool_size, pool_type=None, stride: int = None,
               padding: int = 0, name: Optional[str] = None,
               **_kw) -> LayerOutput:
    """3-D pooling (reference: pool3d → Pool3DLayer.cpp)."""
    inp = input
    name = name or unique_name("pool3d")
    ptype = pooling_mod.get(pool_type)
    stride = stride if stride is not None else pool_size
    vol = _vol_shape_of(inp)
    enforce_that(vol is not None, "img_pool3d needs vol shape",
                 context="pool3d")
    d, h, w, c = vol
    k = (pool_size,) * 3 if isinstance(pool_size, int) else tuple(pool_size)
    od = _conv_out_dim(d, k[0], padding, stride)
    oh = _conv_out_dim(h, k[1], padding, stride)
    ow = _conv_out_dim(w, k[2], padding, stride)
    is_max = isinstance(ptype, pooling_mod.MaxPooling)

    def compute(ctx, p, ins):
        x = _data_of(ins[0]).reshape(-1, d, h, w, c)
        window = (1,) + k + (1,)
        strides = (1,) + (stride,) * 3 + (1,)
        pads = ((0, 0),) + ((padding, padding),) * 3 + ((0, 0),)
        if is_max:
            y = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                      strides, pads)
        else:
            y = jax.lax.reduce_window(x, 0.0, jax.lax.add, window,
                                      strides, pads) / (k[0] * k[1] * k[2])
        return y.reshape(y.shape[0], -1)

    node = LayerOutput(name=name, layer_type="pool3d", inputs=[inp],
                       fn=compute, size=od * oh * ow * c)
    node.vol_shape = (od, oh, ow, c)
    return node


# ---------------------------------------------------------------------------
# MDLSTM (reference: mdlstmemory → MDLstmLayer.cpp) — 2-D LSTM whose cell
# (i, j) sees states from (i-1, j) and (i, j-1). TPU-native: a lax.scan over
# rows whose body is a lax.scan over columns (row-major wavefront), all
# compiled into one XLA while-loop nest.
# ---------------------------------------------------------------------------


@_export
def mdlstmemory(input, size: int, height: int, width: int,
                param_attr=None, bias_attr=True,
                name: Optional[str] = None) -> LayerOutput:
    """2-D multidimensional LSTM over an image laid out [B, H*W*C].

    Gates: input, output, cell candidate + one forget gate per direction
    (MDLstmLayer.cpp). Output is [B, H*W*size]."""
    inp = input
    name = name or unique_name("mdlstm")
    enforce_that(inp.size % (height * width) == 0,
                 "mdlstm input size must be H*W*C", context="mdlstm")
    c_in = inp.size // (height * width)
    # x proj -> 5*size (i, f_row, f_col, o, g); two recurrent projections
    params = {
        "wx": ParamSpec((c_in, 5 * size), ParamAttr.to_attr(param_attr)),
        "wr": ParamSpec((size, 5 * size), ParamAttr.to_attr(param_attr)),
        "wc": ParamSpec((size, 5 * size), ParamAttr.to_attr(param_attr)),
    }
    has_bias = bool(bias_attr)
    if has_bias:
        params["b"] = ParamSpec((5 * size,), ParamAttr.to_attr(
            None if bias_attr is True else bias_attr))

    def compute(ctx, p, ins):
        x = _data_of(ins[0])
        b = x.shape[0]
        grid = x.reshape(b, height, width, c_in)
        xs = jnp.einsum("bhwc,cg->hwbg", grid, p["wx"])
        if has_bias:
            xs = xs + p["b"]

        def cell(pre, h_up, c_up, h_left, c_left):
            z = pre + h_up @ p["wr"] + h_left @ p["wc"]
            i, f_r, f_c, o, g = jnp.split(z, 5, axis=-1)
            c_new = (jax.nn.sigmoid(f_r) * c_up
                     + jax.nn.sigmoid(f_c) * c_left
                     + jax.nn.sigmoid(i) * jnp.tanh(g))
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return h_new, c_new

        zeros = jnp.zeros((b, size), x.dtype)

        def row_step(carry_row, xrow):
            h_prev_row, c_prev_row = carry_row   # [W, B, size] each

            def col_step(carry_col, inputs):
                h_left, c_left = carry_col
                pre, h_up, c_up = inputs
                h_new, c_new = cell(pre, h_up, c_up, h_left, c_left)
                return (h_new, c_new), (h_new, c_new)

            (_, _), (h_row, c_row) = jax.lax.scan(
                col_step, (zeros, zeros), (xrow, h_prev_row, c_prev_row))
            return (h_row, c_row), h_row

        h0 = jnp.zeros((width, b, size), x.dtype)
        (_, _), hs = jax.lax.scan(row_step, (h0, h0), xs)  # [H, W, B, size]
        return hs.transpose(2, 0, 1, 3).reshape(b, -1)

    node = LayerOutput(name=name, layer_type="mdlstm", inputs=[inp],
                       fn=compute, params=params,
                       size=height * width * size)
    node.img_shape = (height, width, size)
    return node


# ---------------------------------------------------------------------------
# detection suite (reference: priorbox/multibox_loss/detection_output —
# PriorBoxLayer.cpp, MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp)
# ---------------------------------------------------------------------------


@_export
def priorbox(input, image_size, min_size, max_size=(), aspect_ratio=(2.0,),
             variance=(0.1, 0.1, 0.2, 0.2), name: Optional[str] = None
             ) -> LayerOutput:
    """Prior (anchor) boxes for a feature map: output [1, P*8] = boxes then
    variances (reference priorbox emits boxes+variances rows)."""
    from paddle_tpu.ops import detection as pdet
    inp = input
    name = name or unique_name("priorbox")
    in_shape = _img_shape_of(inp)
    enforce_that(in_shape is not None, "priorbox needs image shape",
                 context="priorbox")
    fh, fw, _ = in_shape
    ih, iw = (image_size, image_size) if isinstance(image_size, int) \
        else tuple(image_size)
    min_sizes = [min_size] if isinstance(min_size, (int, float)) else list(min_size)
    max_sizes = [max_size] if isinstance(max_size, (int, float)) else list(max_size)
    boxes_np, var_np = pdet.prior_boxes(fh, fw, ih, iw, min_sizes,
                                        max_sizes, list(aspect_ratio),
                                        list(variance))
    num_p = boxes_np.shape[0]

    def compute(ctx, p, ins):
        flat = jnp.concatenate([jnp.asarray(boxes_np).reshape(-1),
                                jnp.asarray(var_np).reshape(-1)])
        return flat[None, :]

    node = LayerOutput(name=name, layer_type="priorbox", inputs=[inp],
                       fn=compute, size=num_p * 8)
    node.num_priors = num_p
    return node


def _gather_ssd_preds(ins, k, num_classes):
    """Concat per-feature-map loc/conf predictions + split the prior blob
    (shared by multibox_loss and detection_output so train-time matching
    and inference-time decoding can never disagree on packing)."""
    loc = jnp.concatenate(
        [_data_of(v).reshape(_data_of(v).shape[0], -1, 4)
         for v in ins[:k]], axis=1)
    conf = jnp.concatenate(
        [_data_of(v).reshape(_data_of(v).shape[0], -1, num_classes)
         for v in ins[k:2 * k]], axis=1)
    pb = _data_of(ins[2 * k])[0]
    return loc, conf, pb


def _split_priors(pb_flat, num_p):
    boxes = pb_flat[: num_p * 4].reshape(num_p, 4)
    var = pb_flat[num_p * 4:].reshape(num_p, 4)
    return boxes, var


@_export
def multibox_loss(input_loc, input_conf, priorbox, label, num_classes: int,
                  overlap_threshold: float = 0.5, neg_pos_ratio: float = 3.0,
                  background_id: int = 0, max_boxes: int = 16,
                  name: Optional[str] = None) -> LayerOutput:
    """SSD loss. ``label`` is a dense [B, max_boxes*5] layer of
    (class, xmin, ymin, xmax, ymax) rows, class<0 ⇒ padding (the reference
    feeds the same records as a sequence; dense-with-padding is the
    static-shape TPU equivalent)."""
    from paddle_tpu.ops import detection as pdet
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    name = name or unique_name("multibox_loss")
    num_p = priorbox.num_priors

    def compute(ctx, p, ins):
        k = len(locs)
        loc, conf, pb = _gather_ssd_preds(ins, k, num_classes)
        gt = _data_of(ins[2 * k + 1]).reshape(loc.shape[0], max_boxes, 5)
        boxes, var = _split_priors(pb, num_p)

        def one(loc_i, conf_i, gt_i):
            valid = gt_i[:, 0] >= 0
            return pdet.multibox_loss(
                loc_i, conf_i, boxes, var, gt_i[:, 1:5],
                jnp.maximum(gt_i[:, 0], 0).astype(jnp.int32), valid,
                num_classes, overlap_threshold, neg_pos_ratio,
                background_id)

        return jax.vmap(one)(loc, conf, gt)[:, None]

    node = LayerOutput(name=name, layer_type="multibox_loss",
                       inputs=locs + confs + [priorbox, label], fn=compute,
                       size=1, is_cost=True)
    return node


@_export
def detection_output(input_loc, input_conf, priorbox, num_classes: int,
                     nms_threshold: float = 0.45,
                     confidence_threshold: float = 0.01,
                     keep_top_k: int = 100, background_id: int = 0,
                     name: Optional[str] = None) -> LayerOutput:
    """Decode + per-class NMS → [B, keep_top_k*6] detections of
    (label, score, xmin, ymin, xmax, ymax), label −1 = empty slot."""
    from paddle_tpu.ops import detection as pdet
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    name = name or unique_name("detection_output")
    num_p = priorbox.num_priors

    def compute(ctx, p, ins):
        k = len(locs)
        loc, conf, pb = _gather_ssd_preds(ins, k, num_classes)
        boxes, var = _split_priors(pb, num_p)

        def one(loc_i, conf_i):
            return pdet.detection_output(
                loc_i, conf_i, boxes, var, num_classes, nms_threshold,
                confidence_threshold, keep_top_k, background_id)

        return jax.vmap(one)(loc, conf).reshape(loc.shape[0], -1)

    return LayerOutput(name=name, layer_type="detection_output",
                       inputs=locs + confs + [priorbox], fn=compute,
                       size=keep_top_k * 6)


# v1-compatible aliases for registered type names
gated_recurrent = grumemory
__all__ += ["gated_recurrent"]
