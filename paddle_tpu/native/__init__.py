"""Python bindings for the native C++ runtime pieces (ctypes).

Reference analog: the reference's engine is C++ with Python on top; here
the compute path is jax/XLA and these native pieces cover the IO/runtime
side — recordio file handling and the async shuffling data pool
(PyDataProvider2's pool thread, DataProvider double buffering) — plus the
C inference ABI (paddle/capi) built from native/src/.

The shared library builds on demand with g++ (cached by source mtime);
everything degrades gracefully when no toolchain is present
(``available()`` returns False and the pure-python paths keep working).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterable, List, Optional

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "src")
_BUILD = os.path.join(_NATIVE_DIR, "build")
_LIB_PATH = os.path.join(_BUILD, "libptn.so")

_lib = None
_load_error: Optional[str] = None


def _sources() -> List[str]:
    return [os.path.join(_SRC, f) for f in ("recordio.cpp",
                                            "shuffle_pool.cpp")]


def _deps() -> List[str]:
    import glob

    return _sources() + glob.glob(os.path.join(_SRC, "*.h"))


def build(force: bool = False) -> str:
    """Compile native/src → native/build/libptn.so (no python linkage —
    the capi library builds separately via build_capi)."""
    os.makedirs(_BUILD, exist_ok=True)
    srcs = _sources()
    if (not force and os.path.exists(_LIB_PATH)
            and all(os.path.getmtime(_LIB_PATH) >= os.path.getmtime(s)
                    for s in _deps())):
        return _LIB_PATH
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
           "-o", _LIB_PATH] + srcs + ["-lpthread"]
    subprocess.run(cmd, check=True, capture_output=True)
    return _LIB_PATH


def build_capi(force: bool = False) -> str:
    """Compile the C inference ABI (embeds CPython) → libptpu_capi.so."""
    import sysconfig

    os.makedirs(_BUILD, exist_ok=True)
    out = os.path.join(_BUILD, "libptpu_capi.so")
    src = os.path.join(_SRC, "capi.cpp")
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
           f"-I{inc}", "-o", out, src,
           f"-L{libdir}", f"-lpython{ver}", "-lpthread"]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def build_aot(force: bool = False) -> str:
    """Compile the interpreter-free AOT inference runtime →
    libptpu_aot.so. PURE C++ — no Python, no jax, no XLA linked; this is
    the embedded-deployment artifact (paddle/capi Android analog)."""
    os.makedirs(_BUILD, exist_ok=True)
    out = os.path.join(_BUILD, "libptpu_aot.so")
    src = os.path.join(_SRC, "aot_runtime.cpp")
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out, src]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def _pjrt_include_dir():
    """The PJRT C API header ships with the tensorflow wheel."""
    import sysconfig

    inc = os.path.join(sysconfig.get_paths()["purelib"], "tensorflow",
                       "include")
    if os.path.exists(os.path.join(inc, "xla", "pjrt", "c", "pjrt_c_api.h")):
        return inc
    return None


def build_pjrt(force: bool = False) -> str:
    """Compile the PJRT C-API inference runtime → libptpu_pjrt.so.
    Pure C++ + libdl; the PJRT plugin (libtpu.so on TPU hosts) is
    dlopen'd at runtime, never linked."""
    os.makedirs(_BUILD, exist_ok=True)
    out = os.path.join(_BUILD, "libptpu_pjrt.so")
    src = os.path.join(_SRC, "pjrt_capi.cpp")
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    inc = _pjrt_include_dir()
    if inc is None:
        raise RuntimeError("no pjrt_c_api.h found in site-packages")
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", f"-I{inc}",
           "-o", out, src, "-ldl"]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def _load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    try:
        path = build()
        lib = ctypes.CDLL(path)
    except Exception as e:  # toolchain missing etc.
        _load_error = str(e)
        return None
    lib.ptn_write_open.restype = ctypes.c_void_p
    lib.ptn_write_open.argtypes = [ctypes.c_char_p]
    lib.ptn_write_record.restype = ctypes.c_int
    lib.ptn_write_record.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64]
    lib.ptn_write_close.restype = ctypes.c_uint64
    lib.ptn_write_close.argtypes = [ctypes.c_void_p]
    lib.ptn_index.restype = ctypes.c_int
    lib.ptn_index.argtypes = [ctypes.c_char_p,
                              ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
                              ctypes.POINTER(ctypes.c_uint64)]
    lib.ptn_free_offsets.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
    lib.ptn_read_chunk.restype = ctypes.c_void_p
    lib.ptn_read_chunk.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_uint64]
    lib.ptn_buf_count.restype = ctypes.c_uint64
    lib.ptn_buf_count.argtypes = [ctypes.c_void_p]
    lib.ptn_buf_get.restype = ctypes.c_int
    lib.ptn_buf_get.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                ctypes.POINTER(ctypes.c_char_p),
                                ctypes.POINTER(ctypes.c_uint64)]
    lib.ptn_buf_free.argtypes = [ctypes.c_void_p]
    lib.ptn_pool_create.restype = ctypes.c_void_p
    lib.ptn_pool_create.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.c_uint64, ctypes.c_uint64,
                                    ctypes.c_uint64]
    lib.ptn_pool_next.restype = ctypes.c_int
    lib.ptn_pool_next.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.ptn_pool_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _require():
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    return lib


# ---------------------------------------------------------------------------
# recordio
# ---------------------------------------------------------------------------


def write_records(path: str, records: Iterable[bytes]) -> int:
    lib = _require()
    h = lib.ptn_write_open(path.encode())
    if not h:
        raise OSError(f"cannot open {path}")
    n = 0
    for rec in records:
        if isinstance(rec, str):
            rec = rec.encode()
        if lib.ptn_write_record(h, rec, len(rec)) != 0:
            lib.ptn_write_close(h)
            raise OSError(f"short write to {path}")
        n += 1
    if lib.ptn_write_close(h) == 2 ** 64 - 1:  # flush failed (disk full)
        raise OSError(f"flush failed writing {path}")
    return n


def index(path: str) -> List[int]:
    lib = _require()
    arr = ctypes.POINTER(ctypes.c_uint64)()
    n = ctypes.c_uint64()
    if lib.ptn_index(path.encode(), ctypes.byref(arr),
                     ctypes.byref(n)) != 0:
        raise OSError(f"cannot index {path}")
    out = [arr[i] for i in range(n.value)]
    lib.ptn_free_offsets(arr)
    return out


def read_chunk(path: str, offset: int, count: int) -> List[bytes]:
    lib = _require()
    h = lib.ptn_read_chunk(path.encode(), offset, count)
    if not h:
        raise OSError(f"cannot read {path}")
    out = []
    data = ctypes.c_char_p()
    length = ctypes.c_uint64()
    for i in range(lib.ptn_buf_count(h)):
        lib.ptn_buf_get(h, i, ctypes.byref(data), ctypes.byref(length))
        out.append(ctypes.string_at(data, length.value))
    lib.ptn_buf_free(h)
    return out


# ---------------------------------------------------------------------------
# async shuffle pool (the native data loader)
# ---------------------------------------------------------------------------


class ShufflePool:
    """Background-thread record streamer with a shuffle window.

    Iterating yields raw record bytes in shuffled order while the native
    producer thread keeps the window full (IO overlaps compute)."""

    def __init__(self, paths: List[str], window: int = 1024, seed: int = 0):
        self._lib = _require()
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._h = self._lib.ptn_pool_create(arr, len(paths), window, seed)

    def __iter__(self):
        data = ctypes.c_char_p()
        length = ctypes.c_uint64()
        while True:
            rc = self._lib.ptn_pool_next(self._h, ctypes.byref(data),
                                         ctypes.byref(length))
            if rc < 0:
                raise OSError("shuffle pool IO error (missing file or "
                              "corrupt record stream)")
            if rc == 0:
                return
            yield ctypes.string_at(data, length.value)

    def close(self):
        if self._h:
            self._lib.ptn_pool_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def recordio_reader(paths, window: int = 1024, seed: int = 0):
    """Reader-creator over native recordio files with async shuffling
    (v2 reader protocol: call → iterator of records)."""
    if isinstance(paths, str):
        paths = [paths]

    def reader():
        pool = ShufflePool(list(paths), window=window, seed=seed)
        try:
            for rec in pool:
                yield rec
        finally:
            pool.close()

    return reader
