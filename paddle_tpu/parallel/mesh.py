"""Mesh construction helpers.

The mesh is the TPU-native replacement for the reference's process topology
(trainer_count threads × num_gradient_servers pservers, Flags.cpp): axes are
logical ('data', 'model', 'seq', 'expert'), devices come from
platform.device discovery, ICI within a slice / DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.platform import device as pdevice
from paddle_tpu.platform.enforce import enforce_that


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices=None):
    import jax

    devs = list(devices) if devices is not None else pdevice.devices()
    n = int(np.prod(shape))
    enforce_that(n <= len(devs),
                 f"mesh {tuple(shape)} needs {n} devices, have {len(devs)}",
                 context="mesh")
    arr = np.asarray(devs[:n]).reshape(tuple(shape))
    return jax.sharding.Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num: Optional[int] = None):
    """1-D 'data' mesh over all (or the first ``num``) devices."""
    devs = pdevice.devices()
    n = num or len(devs)
    return make_mesh((n,), ("data",), devs)


def mesh_axis_names(mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)
