"""Mesh construction helpers.

The mesh is the TPU-native replacement for the reference's process topology
(trainer_count threads × num_gradient_servers pservers, Flags.cpp): axes are
logical ('data', 'model', 'seq', 'expert'), devices come from
platform.device discovery, ICI within a slice / DCN across slices.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.platform import device as pdevice
from paddle_tpu.platform.enforce import enforce_that


def make_mesh(shape: Sequence[int], axis_names: Sequence[str],
              devices=None):
    import jax

    devs = list(devices) if devices is not None else pdevice.devices()
    n = int(np.prod(shape))
    enforce_that(n <= len(devs),
                 f"mesh {tuple(shape)} needs {n} devices, have {len(devs)}",
                 context="mesh")
    arr = np.asarray(devs[:n]).reshape(tuple(shape))
    return jax.sharding.Mesh(arr, tuple(axis_names))


def data_parallel_mesh(num: Optional[int] = None):
    """1-D 'data' mesh over all (or the first ``num``) devices."""
    devs = pdevice.devices()
    n = num or len(devs)
    return make_mesh((n,), ("data",), devs)


def hybrid_mesh(ici_shape: Sequence[int], dcn_shape: Sequence[int],
                axis_names: Sequence[str], devices=None):
    """Multi-slice mesh: per-axis size = dcn * ici, devices laid out so the
    DCN factor spans slices and the ICI factor stays within a slice —
    collectives along an axis then prefer ICI hops and cross DCN only at
    slice granularity (the pserver-fleet-over-network analog, rebuilt on
    jax mesh_utils). Falls back to a plain reshape when the platform
    exposes no slice topology (CPU tests / single slice)."""
    import jax

    enforce_that(len(ici_shape) == len(dcn_shape) == len(axis_names),
                 "ici_shape/dcn_shape/axis_names must have the same rank",
                 context="hybrid_mesh")
    devs = list(devices) if devices is not None else pdevice.devices()
    # BOTH branches require the exact device count (create_hybrid_device_
    # mesh does; the fallback must not be laxer, or CPU-validated configs
    # would fail only on real hardware). Pass devices= for a sub-mesh.
    shape = tuple(int(i) * int(d) for i, d in zip(ici_shape, dcn_shape))
    n = int(np.prod(shape))
    enforce_that(n == len(devs),
                 f"hybrid mesh {shape} needs exactly {n} devices, got "
                 f"{len(devs)} (pass devices= to build a sub-mesh)",
                 context="hybrid_mesh")
    has_slice_topology = all(
        getattr(d, "slice_index", None) is not None for d in devs)
    if has_slice_topology:
        # real multi-slice hardware: config errors must propagate, not
        # degrade into a topology-blind layout
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), tuple(dcn_shape), devices=devs)
    else:
        # no slice topology exposed (CPU tests / single slice): plain
        # reshape — every hop is equivalent anyway
        arr = np.asarray(devs).reshape(shape)
    return jax.sharding.Mesh(arr, tuple(axis_names))


def mesh_slices(tp: int, axis: str = "model", devices=None,
                max_slices: Optional[int] = None):
    """Partition the device set into consecutive ``tp``-chip slices,
    one 1-D ``axis`` mesh per slice — the serving fleet's replica unit
    under tensor parallelism: each slice backs ONE
    ``ServingEngine(mesh=slice)`` replica, so "replica" stops meaning
    "chip" and starts meaning "enough chips to hold the model".
    Consecutive devices stay ICI-adjacent under the platform's default
    ordering, keeping each replica's psums on the fastest links.
    Leftover devices (count not divisible by ``tp``) are unused."""
    import jax

    devs = list(devices) if devices is not None else pdevice.devices()
    tp = int(tp)
    enforce_that(tp >= 1, f"tp must be >= 1, got {tp}", context="mesh")
    n = len(devs) // tp
    enforce_that(n >= 1,
                 f"{len(devs)} device(s) cannot host even one {tp}-chip "
                 "slice", context="mesh")
    if max_slices is not None:
        n = min(n, int(max_slices))
    return [jax.sharding.Mesh(
        np.asarray(devs[i * tp:(i + 1) * tp]).reshape((tp,)), (axis,))
        for i in range(n)]


def mesh_axis_names(mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)
