"""Distributed/parallel machinery over the device mesh.

Reference inventory replaced here (SURVEY.md §2.3): MultiGradientMachine ring
DP → sharded-batch pjit + psum; ParameterServer2 block sharding → ZeRO-style
optimizer-state sharding; sparse remote tables → row-sharded embeddings with
all_to_all; LightNetwork/RDMA → XLA collectives over ICI/DCN.
"""

from paddle_tpu.parallel.mesh import (make_mesh, data_parallel_mesh, hybrid_mesh,
                                      mesh_axis_names)
from paddle_tpu.parallel.api import (shard_batch, replicate, param_sharding,
                                     DataParallel)
from paddle_tpu.parallel.placement import (stage_attrs, model_parallel_fc,
                                           model_parallel_mlp)
from paddle_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from paddle_tpu.parallel.moe import (MoEParams, init_moe_params, moe_ffn,
                                     moe_ffn_reference)
from paddle_tpu.parallel.zero import (ZeroPlan, build_zero_plan,
                                      opt_state_bytes_per_device)
