"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference's model parallelism places whole layers on devices and streams
work through per-device compute threads (ParallelNeuralNetwork.h:15-70
dispatchByDeviceId; MultiGradientMachine.h:41-165 pipelines its ring copies
between trainer threads).  The TPU-native carry-over of that capability is a
collective-permute pipeline:

  - the model is S identical stages; each stage's parameters live ONLY on
    its device along the ``stage`` mesh axis (stacked leading dim, sharded),
  - microbatches enter at stage 0 and hop stage->stage+1 each tick via
    ``lax.ppermute`` over ICI,
  - one ``lax.scan`` runs M + S - 1 ticks (the GPipe fill+drain schedule);
    the last stage accumulates per-microbatch outputs,
  - everything is a plain shard_map program: ``jax.grad`` differentiates
    through scan + ppermute (ppermute's transpose is the reverse hop), so
    pipeline-parallel TRAINING needs no hand-written backward schedule.

This trades the 1F1B memory optimisation for compiler-visible simplicity —
the XLA analog of GPipe, not PipeDream; remat (jax.checkpoint) on stage_fn
recovers most of the memory if needed.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
try:  # jax >= 0.6 top-level; experimental path is deprecated
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

# the audited compiled-path site every pipeline_apply dispatch runs
# through; its sharding contract (stage-sharded params, replicated
# feeds/outputs, collectives are the point) is what `python -m
# paddle_tpu.analysis sharding` checks — and loudly reports as NOT
# audited while this stays a stub nothing exercises
PIPELINE_SITE = "parallel.pipeline"


def stub_contract(axis: str = "stage"):
    """The declared (trivial, pre-build-out) sharding contract: stacked
    stage params shard their leading dim over ``axis``, microbatches
    and outputs replicate, and the ppermute/psum hops are intentional.
    ``mesh_axes`` stays undeclared until a concrete mesh exists —
    collective costs then come from the shard_map eqn's own mesh."""
    from paddle_tpu.analysis.retrace import SiteContract

    return SiteContract(allow_collectives=True,
                        in_specs=((axis,), ()), out_specs=((),))


def stack_stage_params(param_list: Sequence[Any], mesh: Mesh = None,
                       axis: str = "stage"):
    """Stack S per-stage pytrees into one pytree with leading dim S (the
    stage axis), placed so each stage's slice lives on its own device —
    the 'weights live only on their stage' layout."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
    if mesh is not None:
        def _place(x):
            spec = P(axis, *([None] * (x.ndim - 1)))
            return jax.device_put(x, NamedSharding(mesh, spec))

        stacked = jax.tree.map(_place, stacked)
    return stacked


def pipeline_apply(mesh: Mesh, stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params, microbatches: jax.Array,
                   axis: str = "stage") -> jax.Array:
    """Run M microbatches through S pipeline stages; returns [M, ...] outputs.

    ``stacked_params``: pytree with leading dim S (see stack_stage_params).
    ``microbatches``: [M, mb, ...] array, replicated (every stage sees the
    feed; only stage 0 reads it — the cheap choice at small M, and the
    scan/ppermute structure is identical either way).
    ``stage_fn(params, x) -> y`` with y.shape == x.shape (homogeneous
    stages — the classic collective-permute pipeline contract).
    """
    return _pipeline_jit(mesh, stage_fn, axis,
                         int(microbatches.shape[0]))(stacked_params,
                                                     microbatches)


@functools.lru_cache(maxsize=64)
def _pipeline_jit(mesh: Mesh, stage_fn, axis: str, m: int):
    """One audited jit per (mesh, stage_fn, axis, microbatch count) —
    the zero.py identity idiom: a fresh wrapper per call would re-trace
    an identical program every call, which the retrace auditor would
    rightly flag, and an unnamed bare dispatch would leave the pipeline
    invisible to the sharding/xla gates.  The cache keys on the
    CALLER'S ``stage_fn`` identity: pass a stable (module-level)
    callable to reuse compiles across calls — a fresh lambda per call
    re-traces per call (exactly the pre-cache behavior), and the
    bounded maxsize evicts dead entries so that pattern cannot pin
    meshes/executables forever."""
    n_stages = mesh.shape[axis]
    ticks = m + n_stages - 1

    def per_device(params_blk, mbs):
        # params_blk leaves: [1, ...] (this device's stage); drop the dim
        params = jax.tree.map(lambda x: x[0], params_blk)
        stage = lax.axis_index(axis)
        out_shape = mbs.shape[1:]
        acc0 = jnp.zeros((m,) + out_shape, mbs.dtype)
        recv0 = jnp.zeros(out_shape, mbs.dtype)
        if hasattr(lax, "pvary"):
            # newer shard_map tracks varying-manual-axes (VMA): the carry
            # becomes stage-varying after one tick, so it must start so
            acc0, recv0 = lax.pvary((acc0, recv0), (axis,))

        def tick(carry, t):
            acc, recv = carry
            mb_idx = jnp.clip(t, 0, m - 1)
            feed = lax.dynamic_index_in_dim(mbs, mb_idx, keepdims=False)
            x = jnp.where(stage == 0, feed, recv)
            y = stage_fn(params, x)
            # hop to the next stage (no wraparound: stage 0's input is the
            # feed; ppermute fills missing receivers with zeros)
            nxt = lax.ppermute(y, axis,
                               [(i, i + 1) for i in range(n_stages - 1)])
            # last stage emits microbatch t-(S-1) at tick t
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            take = (stage == n_stages - 1) & (t >= n_stages - 1)
            cur = lax.dynamic_index_in_dim(acc, out_idx, keepdims=False)
            upd = jnp.where(take, y, cur)
            acc = lax.dynamic_update_index_in_dim(acc, upd, out_idx, 0)
            return (acc, nxt), None

        (acc, _), _ = lax.scan(tick, (acc0, recv0), jnp.arange(ticks))
        # replicate the last stage's outputs to every device (psum of a
        # one-hot-masked buffer); its transpose distributes cotangents back
        acc = lax.psum(jnp.where(stage == n_stages - 1, acc, 0.0), axis)
        return acc

    def run(stacked_params, microbatches):
        from paddle_tpu.parallel.compat import no_rep_check_kw

        in_params_spec = jax.tree.map(lambda _: P(axis), stacked_params)
        # replication checking off: under jit (the audited dispatch)
        # the scan carry's replication-type inference rejects the
        # pvary'd carry on the grad path ("mismatched replication
        # types" — the workaround jax itself suggests); the
        # grads-match-sequential parity test pins the math unchanged
        return shard_map(per_device, mesh=mesh,
                         in_specs=(in_params_spec, P()),
                         out_specs=P(),
                         **no_rep_check_kw())(stacked_params,
                                              microbatches)

    from paddle_tpu.analysis.retrace import audit_jit

    return audit_jit(run, site=PIPELINE_SITE,
                     xla_contract=stub_contract(axis))
