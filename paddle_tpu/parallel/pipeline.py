"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference's model parallelism places whole layers on devices and streams
work through per-device compute threads (ParallelNeuralNetwork.h:15-70
dispatchByDeviceId; MultiGradientMachine.h:41-165 pipelines its ring copies
between trainer threads).  The TPU-native carry-over of that capability is a
collective-permute pipeline:

  - the model is S identical stages; each stage's parameters live ONLY on
    its device along the ``stage`` mesh axis (stacked leading dim, sharded),
  - microbatches enter at stage 0 and hop stage->stage+1 each tick via
    ``lax.ppermute`` over ICI,
  - one ``lax.scan`` runs M + S - 1 ticks (the GPipe fill+drain schedule);
    the last stage accumulates per-microbatch outputs,
  - everything is a plain shard_map program: ``jax.grad`` differentiates
    through scan + ppermute (ppermute's transpose is the reverse hop), so
    pipeline-parallel TRAINING needs no hand-written backward schedule.

First/last-stage hooks put the EMBED and the LOSS/HEAD on the boundary
stages: ``first_fn(first_params, mb)`` maps the raw microbatch feed into
the stage-0 activation, ``last_fn(last_params, y, mb)`` maps the last
stage's emission into the per-microbatch output that accumulates (a
loss, logits, ...).  Under SPMD every device computes both hooks each
tick and ``where``-masks the result — the same cheap-at-small-M choice
the replicated feed already makes.

This trades the 1F1B memory optimisation for compiler-visible simplicity —
the XLA analog of GPipe, not PipeDream; ``remat=True`` wraps the stage
body in ``jax.checkpoint`` and recovers most of the memory if needed.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel.compat import no_rep_check_kw, shard_map

# the audited compiled-path site every pipeline_apply dispatch runs
# through; its contract (below) declares the closed-form collective
# budget `python -m paddle_tpu.analysis sharding` checks
PIPELINE_SITE = "parallel.pipeline"


@dataclass(frozen=True)
class PipelineConfig:
    """Trainer-facing pipeline-parallel configuration
    (``trainer.SGD(pipeline=PipelineConfig(...))``).

    - ``num_stages``: S.  0 derives it from the mesh's ``axis`` size
      (or, when the trainer builds the mesh, from
      ``FLAGS.pipeline_stages`` falling back to the device count).
    - ``microbatches``: M per step.  0 reads
      ``FLAGS.pipeline_microbatches``.  Bubble fraction is the GPipe
      closed form ``(S-1)/(M+S-1)`` — raise M to amortize.
    - ``n_layers`` / ``n_heads``: the transformer-zoo geometry the
      trainer partitions (``blk{i}_*`` params -> S stages of
      ``n_layers/S`` blocks; embed + loss/head ride the boundary-stage
      hooks).
    - ``remat``: ``jax.checkpoint`` on the stage body (GPipe remat).
    """

    num_stages: int = 0
    microbatches: int = 0
    axis: str = "stage"
    remat: bool = False
    n_layers: int = 0
    n_heads: int = 1


def pipeline_contract(mesh, axis: str, m: int, hop_shape, hop_dtype,
                      out_shape, out_dtype, n_extra_args: int = 0):
    """The REAL declared sharding contract for one pipeline geometry:
    stacked stage params shard their leading dim over ``axis``,
    microbatches and outputs replicate, and the schedule's collectives
    are priced in closed form (the arXiv 2112.09017 model the auditor
    uses — budget == estimate, so ANY extra collective trips the gate):

      - one ``ppermute`` hop of the per-shard activation ``y`` per scan
        tick: ``b_hop`` bytes each, ``ticks = M + S - 1`` ticks;
      - the final one-hot-masked psum replicating the last stage's
        [M, ...] accumulator: ``2 * M*b_out * (S-1)/S``.
    """
    import numpy as np

    from paddle_tpu.analysis.retrace import SiteContract
    from paddle_tpu.analysis.sharding import all_reduce_bytes

    s = int(mesh.shape[axis])
    ticks = m + s - 1
    b_hop = int(np.prod(hop_shape)) * jnp.dtype(hop_dtype).itemsize
    b_out = int(np.prod(out_shape)) * jnp.dtype(out_dtype).itemsize
    comm = float(ticks * b_hop) + all_reduce_bytes(m * b_out, s)
    return SiteContract(
        allow_collectives=True,
        mesh_axes=tuple((a, int(mesh.shape[a])) for a in mesh.axis_names),
        comm_bytes=comm,
        in_specs=((axis,),) + ((),) * (1 + n_extra_args),
        out_specs=((),))


def stack_stage_params(param_list: Sequence[Any], mesh: Mesh = None,
                       axis: str = "stage"):
    """Stack S per-stage pytrees into one pytree with leading dim S (the
    stage axis), placed so each stage's slice lives on its own device —
    the 'weights live only on their stage' layout."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
    if mesh is not None:
        def _place(x):
            spec = P(axis, *([None] * (x.ndim - 1)))
            return jax.device_put(x, NamedSharding(mesh, spec))

        stacked = jax.tree.map(_place, stacked)
    return stacked


def _mb_slice_struct(microbatches):
    """Abstract one microbatch (leading M dim dropped) from the feed
    pytree; every leaf must carry the same leading M."""
    leaves = jax.tree.leaves(microbatches)
    m = int(leaves[0].shape[0])
    sliced = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), microbatches)
    return m, sliced


def _sds_key(x):
    return (tuple(x.shape), jnp.dtype(x.dtype).name)


def pipeline_apply(mesh: Mesh, stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stacked_params, microbatches,
                   axis: str = "stage",
                   first_fn: Optional[Callable] = None,
                   first_params=None,
                   last_fn: Optional[Callable] = None,
                   last_params=None,
                   remat: bool = False) -> jax.Array:
    """Run M microbatches through S pipeline stages; returns [M, ...] outputs.

    ``stacked_params``: pytree with leading dim S (see stack_stage_params).
    ``microbatches``: [M, mb, ...] array — or a pytree of such arrays
    when ``first_fn`` digests a structured feed — replicated (every
    stage sees the feed; only stage 0 reads it — the cheap choice at
    small M, and the scan/ppermute structure is identical either way).
    ``stage_fn(params, x) -> y`` with y.shape == x.shape (homogeneous
    stages — the classic collective-permute pipeline contract).

    Boundary hooks (both optional):
      - ``first_fn(first_params, mb) -> x``: the EMBED on the first
        stage — maps one microbatch feed into the stage-0 activation;
      - ``last_fn(last_params, y, mb) -> out``: the LOSS/HEAD on the
        last stage — maps the final emission (plus the feed, for
        targets) into the per-microbatch value to accumulate.
    ``remat=True`` wraps the stage body in ``jax.checkpoint``.
    """
    m, mb_sds = _mb_slice_struct(microbatches)
    stage_sds = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape[1:], a.dtype), stacked_params)
    if first_fn is not None:
        x_sds = jax.eval_shape(first_fn, first_params, mb_sds)
    else:
        x_sds = jax.tree.leaves(mb_sds)[0]
    y_sds = jax.eval_shape(stage_fn, stage_sds, x_sds)
    if (y_sds.shape, y_sds.dtype) != (x_sds.shape, x_sds.dtype):
        raise ValueError(
            f"pipeline stage_fn must be shape-homogeneous: in "
            f"{x_sds.shape}:{x_sds.dtype} vs out {y_sds.shape}:{y_sds.dtype}")
    if last_fn is not None:
        out_sds = jax.eval_shape(last_fn, last_params, y_sds, mb_sds)
    else:
        out_sds = y_sds
    fn = _pipeline_jit(mesh, stage_fn, axis, m, first_fn, last_fn,
                       bool(remat), _sds_key(x_sds), _sds_key(out_sds))
    return fn(stacked_params,
              () if first_params is None else first_params,
              () if last_params is None else last_params,
              microbatches)


@functools.lru_cache(maxsize=64)
def _pipeline_jit(mesh: Mesh, stage_fn, axis: str, m: int, first_fn,
                  last_fn, remat: bool, x_key, out_key):
    """One audited jit per (mesh, stage_fn, axis, microbatch count,
    hooks, remat, activation/output geometry) — the zero.py identity
    idiom: a fresh wrapper per call would re-trace an identical program
    every call, which the retrace auditor would rightly flag, and an
    unnamed bare dispatch would leave the pipeline invisible to the
    sharding/xla gates.  The cache keys on the CALLER'S ``stage_fn``
    (and hook) identity: pass stable (module-level) callables to reuse
    compiles across calls — a fresh lambda per call re-traces per call
    (exactly the pre-cache behavior), and the bounded maxsize evicts
    dead entries so that pattern cannot pin meshes/executables forever.
    The geometry keys (activation/output shape+dtype) are exactly what
    the closed-form comm budget needs, so the REAL contract is computed
    at wrap time."""
    n_stages = mesh.shape[axis]
    ticks = m + n_stages - 1
    x_shape, x_dtype = x_key
    out_shape, out_dtype = out_key
    body_fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def per_device(params_blk, first_p, last_p, mbs):
        # params_blk leaves: [1, ...] (this device's stage); drop the dim
        params = jax.tree.map(lambda x: x[0], params_blk)
        stage = lax.axis_index(axis)
        acc0 = jnp.zeros((m,) + tuple(out_shape), out_dtype)
        recv0 = jnp.zeros(tuple(x_shape), x_dtype)
        if hasattr(lax, "pvary"):
            # newer shard_map tracks varying-manual-axes (VMA): the carry
            # becomes stage-varying after one tick, so it must start so
            acc0, recv0 = lax.pvary((acc0, recv0), (axis,))

        def tick(carry, t):
            acc, recv = carry
            mb_idx = jnp.clip(t, 0, m - 1)
            mb = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, mb_idx,
                                                   keepdims=False), mbs)
            feed = first_fn(first_p, mb) if first_fn is not None \
                else jax.tree.leaves(mb)[0]
            x = jnp.where(stage == 0, feed, recv)
            y = body_fn(params, x)
            # hop to the next stage (no wraparound: stage 0's input is the
            # feed; ppermute fills missing receivers with zeros)
            nxt = lax.ppermute(y, axis,
                               [(i, i + 1) for i in range(n_stages - 1)])
            # last stage emits microbatch t-(S-1) at tick t — its hook
            # must see THAT microbatch's feed (targets), not tick t's
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            if last_fn is not None:
                mb_out = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, out_idx,
                                                       keepdims=False), mbs)
                emit = last_fn(last_p, y, mb_out)
            else:
                emit = y
            take = (stage == n_stages - 1) & (t >= n_stages - 1)
            cur = lax.dynamic_index_in_dim(acc, out_idx, keepdims=False)
            upd = jnp.where(take, emit, cur)
            acc = lax.dynamic_update_index_in_dim(acc, upd, out_idx, 0)
            return (acc, nxt), None

        (acc, _), _ = lax.scan(tick, (acc0, recv0), jnp.arange(ticks))
        # replicate the last stage's outputs to every device (psum of a
        # one-hot-masked buffer); its transpose distributes cotangents back
        acc = lax.psum(jnp.where(stage == n_stages - 1, acc,
                                 jnp.zeros_like(acc)), axis)
        return acc

    def run(stacked_params, first_params, last_params, microbatches):
        in_params_spec = jax.tree.map(lambda _: P(axis), stacked_params)
        repl = lambda tree: jax.tree.map(lambda _: P(), tree)  # noqa: E731
        # replication checking off: under jit (the audited dispatch)
        # the scan carry's replication-type inference rejects the
        # pvary'd carry on the grad path ("mismatched replication
        # types" — the workaround jax itself suggests); the
        # grads-match-sequential parity test pins the math unchanged
        return shard_map(per_device, mesh=mesh,
                         in_specs=(in_params_spec, repl(first_params),
                                   repl(last_params), repl(microbatches)),
                         out_specs=P(),
                         **no_rep_check_kw())(stacked_params, first_params,
                                              last_params, microbatches)

    from paddle_tpu.analysis.retrace import audit_jit

    contract = pipeline_contract(mesh, axis, m, x_shape, x_dtype,
                                 out_shape, out_dtype, n_extra_args=2)
    return audit_jit(run, site=PIPELINE_SITE, xla_contract=contract)
