"""Async-SGD analog: local SGD with periodic parameter averaging.

Reference analog: the pserver async path — ParameterServer2::asyncSGD
applies each trainer's gradients immediately without barriers
(ParameterServer2.cpp:457), trainers tolerate stale parameters, and
``async_lagged_grad_discard_ratio`` drops gradients that lag too far
behind (TrainerConfig.proto:132-134).

TPU-native reinterpretation (SURVEY.md §7 item 8): there is no parameter
server to absorb staleness on an ICI mesh — asynchrony becomes LOCAL
updates. Each data shard keeps its own parameter replica and steps
independently (zero cross-chip traffic); every ``sync_period`` steps the
replicas are averaged with one ``pmean`` (the WaitPassStart/synchronize
barrier collapses into a collective). The staleness-control knob
survives as ``lagged_grad_discard_ratio``: a shard whose gradient norm
exceeds ratio x the mesh-mean norm skips its local update that step
(outlier/straggler gradient rejection, the async discard analog).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.platform.enforce import enforce_that

try:
    from jax import shard_map                      # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


from paddle_tpu.parallel.compat import no_rep_check_kw


def _tree_norm(tree) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in leaves))


class LocalSGD:
    """Local-update data parallelism with periodic averaging.

    Parameters are stacked per worker on a leading axis sharded over
    ``axis`` — each shard owns its replica. ``make_step(grad_fn)``
    compiles one mesh-wide step; ``replicate``/``average`` move between
    single and per-worker parameter layouts.
    """

    def __init__(self, mesh, sync_period: int = 4, axis: str = "data",
                 lagged_grad_discard_ratio: float = 0.0,
                 learning_rate: float = 0.01):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.sync_period = int(sync_period)
        self.discard_ratio = float(lagged_grad_discard_ratio)
        self.lr = float(learning_rate)

    # -- parameter layout --------------------------------------------------

    def replicate(self, params: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        """params -> per-worker stacked replicas [n, ...], sharded."""
        def rep(x):
            stacked = jnp.broadcast_to(x[None], (self.n,) + x.shape)
            return jax.device_put(
                stacked, NamedSharding(self.mesh, P(self.axis)))
        return jax.tree.map(rep, params)

    def average(self, stacked: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return jax.tree.map(lambda x: jnp.mean(x, axis=0), stacked)

    # -- step --------------------------------------------------------------

    def make_step(self, grad_fn: Callable):
        """``grad_fn(params, feeds) -> (loss, grads)`` per shard.

        Returns jitted ``step(stacked_params, step_idx, feeds)`` ->
        (mean_loss, new_stacked_params). Feeds must have a leading batch
        dim divisible by the worker count (sharded over ``axis``)."""
        axis = self.axis
        period = self.sync_period
        ratio = self.discard_ratio
        lr = self.lr

        def local(params_stk, step_idx, feeds):
            # params_stk: [1, ...] this worker's replica
            params = jax.tree.map(lambda x: x[0], params_stk)
            loss, grads = grad_fn(params, feeds)
            if ratio > 0.0:
                gn = _tree_norm(grads)
                mean_gn = jax.lax.pmean(gn, axis)
                keep = gn <= ratio * mean_gn
                grads = jax.tree.map(
                    lambda g: jnp.where(keep, g, jnp.zeros_like(g)), grads)
            new_params = jax.tree.map(lambda p, g: p - lr * g, params,
                                      grads)
            do_sync = (step_idx + 1) % period == 0
            # lax.cond, not where-select: the pmean collective must only
            # EXECUTE on sync steps (every worker sees the same step_idx,
            # so the branch is uniform and cannot deadlock)
            new_params = jax.lax.cond(
                do_sync,
                lambda p: jax.tree.map(
                    lambda q: jax.lax.pmean(q, axis), p),
                lambda p: p,
                new_params)
            mean_loss = jax.lax.pmean(loss, axis)
            return jax.tree.map(lambda x: x[None], new_params), mean_loss

        fn = shard_map(local, mesh=self.mesh,
                       in_specs=(P(axis), P(), P(axis)),
                       out_specs=(P(axis), P()),
                       **no_rep_check_kw())
        return jax.jit(fn)
