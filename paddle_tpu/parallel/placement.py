"""Per-layer placement / model parallelism over a mesh axis.

Reference: paddle/gserver/gradientmachines/ParallelNeuralNetwork.h:15-70 —
the v1 engine places layers on devices via a per-layer ``device`` attr
(--parallel_nn) and runs one compute thread per device with queue dispatch.

TPU-native redesign: manual thread/queue placement becomes SPMD sharding.
A "stage" here is a (weight sharding, activation sharding) pair over a
named mesh axis; XLA inserts the transfers/collectives that the
reference's dispatchByDeviceId did by hand:

- ``part="col"``: W sharded [in, axis] — output features sharded over the
  axis (no collective on the forward matmul);
- ``part="row"``: W sharded [axis, out] — input features expected sharded,
  output replicated (XLA inserts the psum).

A col->row pair is the classic tensor-parallel block: the model's weights
never exist replicated on any device, which is the capability the
reference's layer placement provided (models too big for one device).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from paddle_tpu.attr import ExtraAttr, ParamAttr


#: The mesh-axis taxonomy every placement plan draws from (one axis,
#: one meaning — MIGRATION.md "Pod-scale training" spells out the
#: composition rules):
#:   data   — batch replication; the grad-psum / ZeRO domain
#:   zero   — alias role of ``data`` when ZeRO shards optimizer state
#:   stage  — pipeline stages (stacked layer dim, leading-dim sharded)
#:   expert — MoE experts (stacked expert dim, leading-dim sharded)
#:   model  — tensor parallelism (megatron col/row feature sharding)
KNOWN_AXES = ("data", "zero", "stage", "expert", "model")


@dataclass(frozen=True)
class _PlanSpec:
    """Adapter so a serving ``shard_plan`` entry plugs into the
    ``specs[name].attr`` shape :func:`~paddle_tpu.parallel.api.param_sharding`
    and :func:`~paddle_tpu.parallel.zero.build_zero_plan` consume."""

    attr: ParamAttr


def plan_param_attrs(plan: Dict[str, Tuple]) -> Dict[str, _PlanSpec]:
    """Bridge a model's tensor-parallel ``shard_plan`` ({param name:
    per-dim axis tuple}) into the explicit-``ParamAttr.sharding`` spec
    dict the data-parallel/ZeRO machinery takes — the train→serve
    "one placement story": ``build_zero_plan(mesh, params,
    specs=plan_param_attrs(model.shard_plan()))`` keeps every
    TP-sharded weight in its declared megatron layout (explicit
    sharding wins the precedence rules) while the replicated remainder
    (embeddings, the vocab head) still gets its optimizer state
    ZeRO-sharded over the ``data`` axis.  Entries with no real axis are
    OMITTED rather than declared ``P()`` — an explicit empty spec would
    opt them out of ZeRO, which is exactly backwards."""
    out: Dict[str, _PlanSpec] = {}
    for name, spec in plan.items():
        dims = tuple(spec)
        if any(a is not None for a in dims):
            out[name] = _PlanSpec(attr=ParamAttr(sharding=dims))
    return out


def leading_axis_plan(params: Dict[str, object],
                      axis: str) -> Dict[str, Tuple]:
    """{name: (axis, None, ...)} plan for stacked-leading-dim weights —
    the layout pipeline stages (``axis="stage"``: [L, ...] layer stacks)
    and MoE experts (``axis="expert"``: [E, ...] expert stacks) share.
    ``params`` maps names to arrays (or anything with ``ndim``/``shape``).
    Feed the result to :func:`plan_param_attrs`; it composes with TP and
    ZeRO entries in the same plan — the one-placement-layer story."""
    out: Dict[str, Tuple] = {}
    for name, v in params.items():
        nd = getattr(v, "ndim", None)
        if nd is None:
            nd = len(getattr(v, "shape", ()))
        out[name] = (axis,) + (None,) * (int(nd) - 1)
    return out


def pipeline_param_attrs(params: Dict[str, object],
                         axis: str = "stage") -> Dict[str, _PlanSpec]:
    """``plan_param_attrs`` of the pipeline leading-dim plan: every
    stacked body weight [L, ...] shards its layer dim over ``axis`` so
    each stage's device holds exactly its L/S layers.  The stacked [L,
    ...] layout itself is LAYOUT-INDEPENDENT: checkpoints save the full
    gathered stack and reload into any stage count dividing L
    (gather-on-save / scatter-on-load, same as every sharded param)."""
    return plan_param_attrs(leading_axis_plan(params, axis))


def expert_param_attrs(params: Dict[str, object],
                       axis: str = "expert") -> Dict[str, _PlanSpec]:
    """``plan_param_attrs`` of the MoE leading-dim plan ([E, ...] expert
    stacks over ``axis``) — :meth:`paddle_tpu.parallel.moe.MoEConfig.
    param_plan` names which weights; this shards any stacked dict."""
    return plan_param_attrs(leading_axis_plan(params, axis))


def stage_attrs(part: str, axis: str = "model"):
    """(param_attr, layer_attr) for one model-parallel fc stage."""
    if part == "col":
        pa = ParamAttr(sharding=(None, axis))
        la = ExtraAttr(sharding=(None, axis))
    elif part == "row":
        pa = ParamAttr(sharding=(axis, None))
        la = ExtraAttr(sharding=(None, None))
    else:
        raise ValueError(f"part must be 'col' or 'row', got {part!r}")
    return pa, la


def model_parallel_fc(input, size: int, *, part: str, axis: str = "model",
                      act=None, name: Optional[str] = None,
                      bias_attr=True):
    """fc whose weight AND activation are sharded over ``axis``.

    col-part biases are feature-sharded too (they live with the output
    features); row-part biases stay replicated (they add to the psum
    result).
    """
    from paddle_tpu import layer

    pa, la = stage_attrs(part, axis)
    if bias_attr is True and part == "col":
        bias_attr = ParamAttr(sharding=(axis,))
    return layer.fc(input=input, size=size, act=act, name=name,
                    param_attr=pa, bias_attr=bias_attr, layer_attr=la)


def model_parallel_mlp(input, hidden_sizes: Sequence[int], out_size: int,
                       *, axis: str = "model", act: str = "relu",
                       out_act=None, name_prefix: str = "mp"):
    """Alternating col/row tensor-parallel MLP (megatron-style pairs).

    Hidden layers shard features over ``axis``; the final row-parallel
    projection returns a replicated [batch, out_size] output ready for a
    loss layer. With an even number of hidden layers every weight is
    sharded; no device ever holds a full replica.
    """
    net = input
    part = "col"
    for i, h in enumerate(hidden_sizes):
        net = model_parallel_fc(net, h, part=part, axis=axis, act=act,
                                name=f"{name_prefix}_fc{i}")
        part = "row" if part == "col" else "col"
    return model_parallel_fc(net, out_size, part="row", axis=axis,
                             act=out_act, name=f"{name_prefix}_out")


