"""Sharded embedding tables + sparse-row updates — the "large model
distributed training" capability.

Reference analog (SURVEY.md §2.3): huge embedding tables living only on
pservers with per-batch row prefetch and sparse-row gradient pushes —
doc/design/cluster_train/large_model_dist_train.md:1-38,
SparseRemoteParameterUpdater (trainer/RemoteParameterUpdater.h:265),
SparseRowCpuMatrix (math/SparseRowMatrix.h), GET_PARAM_SPARSE RPC
(ParameterService.proto), sparse ports (Flags.cpp:70).

TPU-native design: the table is row-sharded over a mesh axis with
``NamedSharding(P(axis, None))``; lookups run under ``shard_map`` as
owner-computes + ``psum`` (each shard gathers the rows it owns, zeros
elsewhere — the GET_PARAM_SPARSE prefetch becomes one small id all-gather
plus one row-sum over ICI instead of parameter-server RPC). Gradients stay
in SelectedRows form (ids + rows) and optimizers update only touched rows
(the SparseRowMatrix capability), scatter-added shard-locally."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.platform.enforce import enforce_that

try:
    from jax import shard_map                      # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from paddle_tpu.parallel.compat import no_rep_check_kw


# ---------------------------------------------------------------------------
# SelectedRows — the sparse gradient representation (selected_rows.h analog)
# ---------------------------------------------------------------------------


@dataclass
class SelectedRows:
    """A sparse slab of a [vocab, dim] tensor: ``rows[i]`` is the gradient
    for table row ``ids[i]``. Duplicate ids are allowed (scatter-add)."""

    ids: jax.Array      # [n] int32
    rows: jax.Array     # [n, dim]
    height: int         # vocab size

    def to_dense(self) -> jax.Array:
        out = jnp.zeros((self.height, self.rows.shape[-1]),
                        self.rows.dtype)
        return out.at[self.ids].add(self.rows)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda s: ((s.ids, s.rows), s.height),
    lambda h, c: SelectedRows(c[0], c[1], h))


def embedding_grad(table: jax.Array, ids: jax.Array,
                   loss_fn: Callable[[jax.Array], jax.Array]
                   ) -> Tuple[jax.Array, SelectedRows]:
    """loss + SelectedRows gradient of an embedding lookup.

    ``loss_fn(rows)`` consumes the gathered rows [n, dim]. The table itself
    is never densely differentiated — the grad lives only on touched rows
    (the reference's sparse_update=True path)."""
    flat_ids = ids.reshape(-1).astype(jnp.int32)
    rows = jnp.take(table, flat_ids, axis=0)
    loss, d_rows = jax.value_and_grad(loss_fn)(rows)
    return loss, SelectedRows(flat_ids, d_rows, table.shape[0])


# ---------------------------------------------------------------------------
# sparse-row optimizers (SparseRowCpuMatrix sgdUpdate / adagrad analogs)
# ---------------------------------------------------------------------------


def sgd_update_rows(table: jax.Array, grad: SelectedRows,
                    lr: float) -> jax.Array:
    return table.at[grad.ids].add(-lr * grad.rows)


def adagrad_update_rows(table: jax.Array, accum: jax.Array,
                        grad: SelectedRows, lr: float,
                        epsilon: float = 1e-6
                        ) -> Tuple[jax.Array, jax.Array]:
    """Row-sparse Adagrad: O(n_rows * dim) work, no dense temporaries.

    Duplicate ids are pre-combined (segment-sum over the deduped slots)
    so the accumulator sees each touched row exactly once."""
    n = grad.ids.shape[0]
    uniq, inv = jnp.unique(grad.ids, size=n, fill_value=-1,
                           return_inverse=True)
    pad = uniq < 0
    safe = jnp.clip(uniq, 0, table.shape[0] - 1)
    combined = jax.ops.segment_sum(grad.rows, inv.reshape(-1),
                                   num_segments=n)
    combined = jnp.where(pad[:, None], 0.0, combined)
    acc_delta = jnp.square(combined)   # pad rows already zeroed above
    acc_rows = jnp.take(accum, safe, axis=0) + acc_delta
    step = lr * combined / (jnp.sqrt(acc_rows) + epsilon)
    tab_delta = jnp.where(pad[:, None], 0.0, -step)
    # pad slots are clipped to index 0; scatter-add with zeroed deltas is
    # well-defined under that collision (set would drop row 0's update)
    return (table.at[safe].add(tab_delta),
            accum.at[safe].add(acc_delta))


# ---------------------------------------------------------------------------
# mesh-sharded table + lookup
# ---------------------------------------------------------------------------


def shard_table(mesh, table, axis: str = "model"):
    """Place a [vocab, dim] table row-sharded over ``axis`` (the pserver
    block-partition analog; each shard owns vocab/n contiguous rows)."""
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def sharded_lookup(mesh, table: jax.Array, ids: jax.Array,
                   axis: str = "model",
                   batch_axis: Optional[str] = None) -> jax.Array:
    """Gather rows from a row-sharded table: owner-computes + psum.

    Each shard holds rows [lo, hi); it serves the ids it owns and
    contributes zeros for the rest; a single ``psum`` over the table axis
    assembles full rows on every participant. ``batch_axis`` optionally
    shards ``ids`` over the data axis too (each data-shard gets its own
    rows; the psum rides ICI)."""
    vocab = table.shape[0]
    n_shards = mesh.shape[axis]
    enforce_that(vocab % n_shards == 0,
                 f"vocab {vocab} must divide over {n_shards} '{axis}' shards",
                 context="sparse")
    per = vocab // n_shards

    id_spec = P(batch_axis) if batch_axis else P()

    def local(tab, idv):
        # tab: [per, dim] local rows; idv: local ids
        shard = jax.lax.axis_index(axis)
        lo = shard * per
        rel = idv.astype(jnp.int32) - lo
        mine = (rel >= 0) & (rel < per)
        rows = jnp.take(tab, jnp.clip(rel, 0, per - 1), axis=0)
        rows = jnp.where(mine[..., None], rows, 0.0)
        return jax.lax.psum(rows, axis)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(axis, None), id_spec),
                   out_specs=id_spec,
                   **no_rep_check_kw())
    return fn(table, ids)


def sharded_row_update(mesh, table: jax.Array, grad: SelectedRows,
                       lr: float, axis: str = "model") -> jax.Array:
    """Apply an SGD row update to a row-sharded table: every shard
    scatter-adds only the rows it owns (no gradient traffic for rows the
    shard doesn't hold — the sparse SendParameter analog)."""
    vocab = table.shape[0]
    n_shards = mesh.shape[axis]
    per = vocab // n_shards

    def local(tab, idv, rows):
        shard = jax.lax.axis_index(axis)
        lo = shard * per
        rel = idv.astype(jnp.int32) - lo
        mine = (rel >= 0) & (rel < per)
        contrib = jnp.where(mine[:, None], rows, 0.0)
        return tab.at[jnp.clip(rel, 0, per - 1)].add(-lr * contrib)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(axis, None), P(), P()),
                   out_specs=P(axis, None),
                   **no_rep_check_kw())
    return fn(table, grad.ids, grad.rows)


def alltoall_lookup(mesh, table: jax.Array, ids: jax.Array,
                    axis: str = "model") -> jax.Array:
    """Expert-parallel style lookup: ids are sharded over ``axis`` (each
    shard has its own query slice); rows come back via all_to_all-shaped
    traffic (here: all_gather of the per-shard queries + owner-computes +
    reduce_scatter). Bandwidth-optimal when queries are sharded."""
    vocab = table.shape[0]
    n_shards = mesh.shape[axis]
    per = vocab // n_shards
    enforce_that(ids.shape[0] % n_shards == 0,
                 "alltoall_lookup needs ids divisible over the axis",
                 context="sparse")

    def local(tab, idv):
        # idv: this shard's queries [b/n]. Gather everyone's queries,
        # serve owned rows, reduce_scatter the answers back.
        all_ids = jax.lax.all_gather(idv, axis, tiled=True)   # [b]
        shard = jax.lax.axis_index(axis)
        lo = shard * per
        rel = all_ids.astype(jnp.int32) - lo
        mine = (rel >= 0) & (rel < per)
        rows = jnp.take(tab, jnp.clip(rel, 0, per - 1), axis=0)
        rows = jnp.where(mine[..., None], rows, 0.0)
        return jax.lax.psum_scatter(rows, axis, scatter_dimension=0,
                                    tiled=True)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(axis, None), P(axis)),
                   out_specs=P(axis),
                   **no_rep_check_kw())
    return fn(table, ids)


# ---------------------------------------------------------------------------
# v2-API integration: a sparse updater for embedding parameters
# ---------------------------------------------------------------------------


class SparseEmbeddingUpdater:
    """Routes embedding parameters through row-sparse updates inside a
    training loop (the sparse_update=True ParamAttr path of the reference).

    ``apply(params, grads, lr, ids={...})`` updates marked params only on
    the rows named by that step's ids (SelectedRows + scatter-add —
    sharded when a mesh is given); unmarked params take the dense step.
    Without ids for a marked param it falls back to the dense update."""

    def __init__(self, mesh=None, sparse_params: Tuple[str, ...] = (),
                 axis: str = "model"):
        self.mesh = mesh
        self.sparse = set(sparse_params)
        self.axis = axis

    def apply(self, params: Dict[str, jax.Array],
              grads: Dict[str, jax.Array], lr: float,
              ids: Optional[Dict[str, jax.Array]] = None
              ) -> Dict[str, jax.Array]:
        ids = ids or {}
        out = {}
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                out[k] = p
            elif k in self.sparse and k in ids:
                row_ids = ids[k].reshape(-1).astype(jnp.int32)
                # jax.grad gives the scatter-summed dense grad; taking its
                # touched rows per occurrence would double-count duplicate
                # ids, so dedupe (pad slots masked to zero rows, not routed
                # to a real id)
                uniq = jnp.unique(row_ids, size=row_ids.shape[0],
                                  fill_value=-1)
                pad = uniq < 0
                safe = jnp.clip(uniq, 0, p.shape[0] - 1)
                rows = jnp.where(pad[:, None], 0.0,
                                 jnp.take(g, safe, axis=0))
                sel = SelectedRows(safe, rows, p.shape[0])
                if self.mesh is not None:
                    out[k] = sharded_row_update(self.mesh, p, sel, lr,
                                                self.axis)
                else:
                    out[k] = sgd_update_rows(p, sel, lr)
            else:
                out[k] = p - lr * g
        return out
