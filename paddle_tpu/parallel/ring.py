"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference (2017) has no long-context parallelism — its long-sequence
story is the ragged Argument/LoD representation plus RecurrentGradientMachine
frame batching (SURVEY.md §2.3 'Sequence parallelism' row).  This module is
the TPU-native extension that carries that capability to modern scale:

  - ``ring_attention``: q/k/v sharded along the sequence dim over a mesh
    axis; kv chunks rotate around the ring via ``lax.ppermute`` (ICI
    neighbour exchange), each step merged with online-softmax (m, l, acc)
    accumulation.  Communication overlaps compute the way the reference's
    MultiGradientMachine pipelined its ring gradient copies
    (MultiGradientMachine.h:60-90) — here XLA does the overlap.
  - ``ulysses_attention``: all_to_all head<->sequence reshard (the sparse
    all-to-all machinery of SURVEY §2.3 applied to attention): each device
    gets the full sequence for a subset of heads, runs local (flash)
    attention, and resharding back.

Both are plain shard_map programs: autodiff flows through ppermute /
all_to_all transposes, so training works without hand-written backward.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.ops.attention import DEFAULT_MASK_VALUE, flash_attention
from paddle_tpu.parallel.compat import no_rep_check_kw, shard_map


def _mark_varying(tree, axis: str):
    """Start shard_map carries as axis-varying where the jax version
    tracks varying-manual-axes (VMA) — ``lax.pvary`` on jax >= 0.6,
    a no-op on older jax whose shard_map has no VMA inference (the
    same guard parallel/pipeline.py uses for its scan carry)."""
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(tree, (axis,))
    return tree


def _chunk_attn(q, k, v, q_seg, k_seg, q_off, k_off, causal, sm_scale):
    """One q-chunk x kv-chunk blockwise attention; returns (acc, m, l).

    q: (B, Sq, H, D); k/v: (B, Sk, H, D); offsets are global token offsets
    of the chunks (for causal masking across the ring).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    mask = (q_seg[:, None, :, None] == k_seg[:, None, None, :])
    if causal:
        q_ids = q_off + jnp.arange(q.shape[1])
        k_ids = k_off + jnp.arange(k.shape[1])
        mask = mask & (q_ids[None, None, :, None] >= k_ids[None, None, None, :])
    s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1)                        # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)                        # (B,H,Sq)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return acc, m, l


def _merge(acc, m, l, acc2, m2, l2):
    m_new = jnp.maximum(m, m2)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m2 - m_new)
    l_new = l * a1 + l2 * a2
    acc_new = (acc * a1.transpose(0, 2, 1)[..., None]
               + acc2 * a2.transpose(0, 2, 1)[..., None])
    return acc_new, m_new, l_new


def ring_attention(q, k, v, mesh, axis: str = "seq", segment_ids=None,
                   causal: bool = False, sm_scale: Optional[float] = None):
    """Ring self-attention over sequence-sharded q/k/v.

    Args:
      q, k, v: (B, S, H, D) arrays logically sharded (B, S/axis, H, D) —
        pass the global arrays; shard_map partitions them.
      segment_ids: (B, S) int32 packed-segment ids (None => one segment).
    Returns (B, S, H, D) with the same sequence sharding as q.
    """
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    n = mesh.shape[axis]
    batch, seq, heads, head_dim = q.shape
    assert seq % n == 0, f"seq {seq} must divide over axis {axis}={n}"
    local = seq // n
    if segment_ids is None:
        segment_ids = jnp.zeros((batch, seq), jnp.int32)
    segment_ids = segment_ids.astype(jnp.int32)

    def body(q, k, v, seg):
        # all args are the local shards: (B, local, H, D) / (B, local)
        idx = jax.lax.axis_index(axis)
        q_off = idx * local

        def step(t, carry):
            acc, m, l, kc, vc, segc = carry
            src = jax.lax.rem(idx - t + n, n)       # origin device of chunk
            k_off = src * local
            acc2, m2, l2 = _chunk_attn(q, kc, vc, seg, segc, q_off, k_off,
                                       causal, sm_scale)
            acc, m, l = _merge(acc, m, l, acc2, m2, l2)
            perm = [(i, (i + 1) % n) for i in range(n)]
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            segc = jax.lax.ppermute(segc, axis, perm)
            return acc, m, l, kc, vc, segc

        acc0, m0, l0 = _mark_varying(
            (jnp.zeros((batch, local, heads, head_dim), jnp.float32),
             jnp.full((batch, heads, local), -jnp.inf, jnp.float32),
             jnp.zeros((batch, heads, local), jnp.float32)), axis)
        acc, m, l, _, _, _ = jax.lax.fori_loop(
            0, n, step, (acc0, m0, l0, k, v, seg))
        l = jnp.where(l == 0.0, 1.0, l)
        out = acc / l.transpose(0, 2, 1)[..., None]
        return out.astype(q.dtype)

    spec = P(None, axis, None, None)
    seg_spec = P(None, axis)
    # replication checking off (compat kw): the fori_loop carry's VMA
    # inference rejects the pvary'd carry on older jax grad paths —
    # the ring-matches-flash parity tests pin the math unchanged
    fn = shard_map(body, mesh=mesh,
                   in_specs=(spec, spec, spec, seg_spec),
                   out_specs=spec, **no_rep_check_kw())
    return fn(q, k, v, segment_ids)


def ulysses_attention(q, k, v, mesh, axis: str = "seq", segment_ids=None,
                      causal: bool = False, sm_scale: Optional[float] = None,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      interpret: Optional[bool] = None):
    """DeepSpeed-Ulysses-style sequence parallelism.

    q/k/v sequence-sharded over ``axis``; all_to_all resharding gives each
    device ALL tokens for heads/axis_size heads; local flash attention; then
    all_to_all back to sequence sharding.  Heads must divide by axis size.
    """
    n = mesh.shape[axis]
    batch, seq, heads, head_dim = q.shape
    assert heads % n == 0, f"heads {heads} must divide over {axis}={n}"
    assert seq % n == 0
    if segment_ids is None:
        segment_ids = jnp.zeros((batch, seq), jnp.int32)
    segment_ids = segment_ids.astype(jnp.int32)

    def body(q, k, v, seg):
        # local: (B, S/n, H, D) -> (B, S, H/n, D)
        def to_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        seg_full = jax.lax.all_gather(seg, axis, axis=1, tiled=True)
        out = flash_attention(qh, kh, vh, segment_ids=seg_full,
                              causal=causal, sm_scale=sm_scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(None, axis, None, None)
    # replication check off: pallas_call inside shard_map doesn't
    # annotate vma yet (check_vma on new jax, check_rep on 0.4.x)
    fn = shard_map(body, mesh=mesh,
                   in_specs=(spec, spec, spec, P(None, axis)),
                   out_specs=spec, **no_rep_check_kw())
    return fn(q, k, v, segment_ids)
