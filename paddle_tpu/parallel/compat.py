"""jax version shims shared by the parallel modules."""

from __future__ import annotations

try:
    from jax import shard_map                      # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["no_rep_check_kw", "shard_map"]


def no_rep_check_kw() -> dict:
    """The kwarg that disables shard_map's replication-type checking,
    under whichever name this jax spells it (``check_vma`` on new
    releases, ``check_rep`` before) — passing the wrong one is a
    TypeError that used to fail the whole EP/sparse/local-SGD paths on
    older jax."""
    import inspect

    params = inspect.signature(shard_map).parameters
    for name in ("check_vma", "check_rep"):
        if name in params:
            return {name: False}
    return {}
