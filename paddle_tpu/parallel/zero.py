"""ZeRO-1 cross-replica sharded weight update (arXiv 2004.13336).

The replicated data-parallel path keeps the full optimizer state on every
replica and all-reduces gradients before the update.  "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" replaces
that with: reduce-scatter the gradients, update a 1/N shard of every
parameter per replica, all-gather the updated weights — cutting
optimizer-state HBM by N x and swapping one all-reduce for the cheaper
reduce-scatter + all-gather pair over ICI.

Formulation here: each parameter is flattened, zero-padded to a multiple of
the ``data``-axis size, and viewed as a 1-D array sharded over that axis.
Inside the jitted train step the shard view is expressed with
``with_sharding_constraint`` — under GSPMD the grad constraint lowers the
preceding psum into a reduce-scatter and the replicated constraint on the
updated flat weights lowers into an all-gather, i.e. exactly the paper's
``psum_scatter`` / ``all_gather`` pair without hand-splitting the step into
a shard_map.  Optimizer slot state lives PERMANENTLY in the flat sharded
layout (allocated sharded at ``init_state``, never replicated), so every
existing optimizer's elementwise ``_update`` works through the shard view
unchanged — one wrapper, not N forks.

Precedence (mirrors :func:`paddle_tpu.parallel.api.param_sharding`): a
param with an explicit ``ParamAttr.sharding`` — or one the ``zero_axis``
largest-dim rule already shards — keeps its declared layout and passes
through untouched; static params pass through too (their state never
changes, so sharding it would buy nothing and cost a per-step gather).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from paddle_tpu.platform.enforce import enforce_that

# state keys holding one entry per parameter name (the trees the plan
# re-lays-out); everything else in an optimizer state (step, sm scalars,
# avg_count) is layout-free and passes through untouched
_PARAM_KEYED = ("avg", "prune_masks")


@dataclass(frozen=True)
class ZeroEntry:
    """Per-parameter shard layout: ``shape`` flattens to ``size`` elements,
    zero-padded to ``padded`` (a multiple of the axis size) when sharded."""

    shape: Tuple[int, ...]
    size: int
    padded: int
    sharded: bool


class ZeroPlan:
    """Shard plan for ZeRO-1 optimizer-state sharding over one mesh axis.

    Traced-side (inside jit): :meth:`shard_tree` / :meth:`gather_tree`
    re-layout params+grads around the optimizer update.  Placement-side
    (outside jit): :meth:`place_flat` / :meth:`shard_state` /
    :meth:`gather_state` move host/checkpoint arrays into and out of the
    flat sharded layout.
    """

    def __init__(self, mesh, axis: str, entries: Dict[str, ZeroEntry]):
        self.mesh = mesh
        self.axis = axis
        self.entries = entries

    # -- shardings ---------------------------------------------------------

    def flat_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P(self.axis))

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    def is_sharded(self, name: str) -> bool:
        e = self.entries.get(name)
        return e is not None and e.sharded

    # -- traced-side views (used inside the jitted step) -------------------

    def shard_view(self, name: str, x):
        """Full tensor -> padded flat view constrained to 1/N per replica.
        On a gradient fresh out of a psum this is the reduce-scatter; on a
        replicated param it is a local slice."""
        e = self.entries.get(name)
        if e is None or not e.sharded:
            return x
        import jax.numpy as jnp

        flat = x.reshape(-1)
        if e.padded != e.size:
            flat = jnp.pad(flat, (0, e.padded - e.size))
        return _constrain(flat, self.flat_sharding())

    def gather_view(self, name: str, x):
        """Padded flat shard view -> full replicated tensor (the all-gather
        of the updated weights)."""
        e = self.entries.get(name)
        if e is None or not e.sharded:
            return x
        full = _constrain(x, self.replicated_sharding())
        return full[:e.size].reshape(e.shape)

    def shard_tree(self, tree: Dict[str, Any]) -> Dict[str, Any]:
        return {k: self.shard_view(k, v) for k, v in tree.items()}

    def gather_tree(self, tree: Dict[str, Any]) -> Dict[str, Any]:
        return {k: self.gather_view(k, v) for k, v in tree.items()}

    # -- placement-side (init / checkpoint resume) -------------------------

    def _host_full(self, v) -> np.ndarray:
        """Full host copy of ``v``.  A SHARDED device array (the
        gather-on-save path walking flat 1/N slot shards) goes through
        the compiled ``zero.replicate`` identity — one XLA all-gather
        then a single host read, instead of np.asarray's per-shard
        host copies — which also covers the multi-process case where
        np.asarray on non-addressable devices would raise.  Replicated
        or single-device arrays read straight through."""
        import jax

        sh = self.replicated_sharding()
        if isinstance(v, jax.Array) and \
                (not v.is_fully_addressable
                 or (not v.is_fully_replicated and _mesh_spanning(v, sh))):
            v = _identity_jit(sh, "zero.replicate",
                              in_spec=(self.axis,))(v)
            return np.asarray(v.addressable_data(0))
        return np.asarray(v)

    def place_flat(self, name: str, v):
        """Place a host/device array (full-shape OR already-flat) into the
        flat sharded layout on the mesh."""
        import jax

        e = self.entries[name]
        if not e.sharded:
            return v
        if isinstance(v, jax.Array) and tuple(v.shape) == (e.padded,):
            # already-flat device state being RE-placed (a resume, or
            # _place_on_mesh over live slots): one compiled reshard
            # identity instead of gathering to host and scattering back
            # per tensor — the re-place the sharding auditor flagged
            return _constrain(v, self.flat_sharding())
        host = self._host_full(v)
        if host.shape != (e.padded,):
            enforce_that(host.size == e.size,
                         f"zero shard of {name!r}: got {host.shape}, "
                         f"expected {e.shape} or flat ({e.padded},)",
                         context="zero")
            flat = host.reshape(-1)
            if e.padded != e.size:
                flat = np.concatenate(
                    [flat, np.zeros(e.padded - e.size, flat.dtype)])
            host = flat
        return _put_global(host, self.flat_sharding())

    def shard_state(self, state: Any) -> Any:
        """Re-lay-out an optimizer state (full-shape host arrays from a
        checkpoint, or an already-flat state being re-placed) into the flat
        sharded layout.  Non-param-keyed entries pass through."""
        if not isinstance(state, dict):
            return state
        out = dict(state)
        if "slots" in out:
            out["slots"] = {
                s: {k: (self.place_flat(k, v) if k in self.entries else v)
                    for k, v in d.items()}
                for s, d in out["slots"].items()}
        for key in _PARAM_KEYED:
            if key in out:
                out[key] = {
                    k: (self.place_flat(k, v) if k in self.entries else v)
                    for k, v in out[key].items()}
        return out

    def _unflatten(self, name: str, v):
        e = self.entries[name]
        if not e.sharded:
            return self._host_full(v)
        host = self._host_full(v)  # gathers shards on the host
        if host.shape == e.shape:
            return host  # already layout-independent (zero was off)
        enforce_that(host.shape == (e.padded,),
                     f"zero gather of {name!r}: got {host.shape}, "
                     f"expected ({e.padded},)", context="zero")
        return host[:e.size].reshape(e.shape)

    def gather_state(self, state: Any) -> Any:
        """Inverse of :meth:`shard_state`: flat shard views back to
        full-shape host arrays, so checkpoints stay layout-independent
        (a zero_stage=1 save loads under zero_stage=0 and vice versa)."""
        if not isinstance(state, dict):
            return state
        out = dict(state)
        if "slots" in out:
            out["slots"] = {
                s: {k: (self._unflatten(k, v) if k in self.entries else v)
                    for k, v in d.items()}
                for s, d in out["slots"].items()}
        for key in _PARAM_KEYED:
            if key in out:
                out[key] = {
                    k: (self._unflatten(k, v) if k in self.entries else v)
                    for k, v in out[key].items()}
        return out


def build_zero_plan(mesh, params: Dict[str, Any], specs=None,
                    axis: str = "data",
                    zero_axis: Optional[str] = None) -> ZeroPlan:
    """Build the per-tensor shard plan for ZeRO-1 over ``axis``.

    Reuses :func:`param_sharding` for the precedence rules: only params it
    leaves fully replicated (no explicit ``ParamAttr.sharding``, not taken
    by the ``zero_axis`` largest-dim rule) get the flat 1/N layout.
    Non-divisible sizes pad up to the axis size; scalars degenerate to one
    real element plus padding (still correct, trivially small).
    """
    from paddle_tpu.parallel.api import param_sharding

    enforce_that(axis in mesh.axis_names, f"no axis {axis!r} in mesh",
                 context="zero")
    n = int(dict(zip(mesh.axis_names, mesh.devices.shape))[axis])
    declared = param_sharding(mesh, params, specs=specs, zero_axis=zero_axis)
    entries = {}
    for name, v in params.items():
        attr = specs[name].attr if specs is not None and name in specs else None
        static = bool(attr is not None and attr.is_static)
        explicit = attr is not None and attr.sharding is not None
        # replicated = no dim actually carries a mesh axis (the zero_axis
        # largest-dim rule leaves non-divisible params at P(None,...), which
        # is logically replicated and still wants its slots ZeRO-sharded)
        replicated = not explicit and all(
            a is None for a in tuple(declared[name].spec))
        size = int(np.prod(np.shape(v))) if np.ndim(v) else 1
        sharded = replicated and not static and n > 1
        padded = -(-size // n) * n if sharded else size
        entries[name] = ZeroEntry(shape=tuple(np.shape(v)), size=size,
                                  padded=padded, sharded=sharded)
    return ZeroPlan(mesh, axis, entries)


def host_tree(tree):
    """Full host (numpy) copy of a pytree of arrays — the checkpoint
    snapshot path (``checkpoint.snapshot_checkpoint``).  Replicated and
    single-device arrays read straight through ``np.asarray``; a
    physically-sharded mesh-spanning array routes through the compiled
    ``zero.host_gather`` identity — one XLA all-gather then a single
    host read instead of per-shard host copies — which also covers the
    multi-process case where ``np.asarray`` on non-addressable devices
    would raise (the same contract as :meth:`ZeroPlan._host_full`, made
    plan-free so params/model-state snapshot through it too)."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    def leaf(v):
        if v is None:
            return None
        if isinstance(v, jax.Array) and \
                (not v.is_fully_addressable or not v.is_fully_replicated):
            mesh = getattr(v.sharding, "mesh", None)
            if mesh is not None:
                sh = NamedSharding(mesh, P())
                if _mesh_spanning(v, sh):
                    v = _identity_jit(sh, "zero.host_gather")(v)
                    return np.asarray(v.addressable_data(0))
        return np.asarray(v)

    return jax.tree.map(leaf, tree)


def opt_state_bytes_per_device(tree) -> int:
    """Exact per-device bytes of a (possibly sharded) state pytree — the
    bench/acceptance metric for the N x optimizer-state reduction."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array) and getattr(leaf, "sharding", None) \
                is not None:
            shard = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard)) * leaf.dtype.itemsize
        else:
            total += np.asarray(leaf).nbytes
    return total


@functools.lru_cache(maxsize=None)
def _identity_jit(sharding, site: str, in_spec=None):
    """One compiled identity per (sharding, site, declared-input-spec) —
    per-call wrappers would re-trace an identical signature every call
    (a real retrace the audit sites would rightly flag)."""
    from paddle_tpu.analysis.retrace import SiteContract, audit_jit

    # collectives (the resharding all-gather/scatter the out_shardings
    # lower into) are the POINT of a placement site — the jaxpr auditor
    # reports them as INFO and the sharding auditor costs them against
    # the declared specs: out = the target sharding's spec; in = the
    # caller-declared source placement (None = unknown, costed 0)
    spec = getattr(sharding, "spec", ())
    return audit_jit(lambda a: a, site=site, out_shardings=sharding,
                     xla_contract=SiteContract(
                         allow_collectives=True,
                         in_specs=(in_spec,) if in_spec is not None
                         else None,
                         out_specs=(tuple(spec),),
                         mesh_axes=tuple(
                             (str(a), int(n)) for a, n in
                             dict(sharding.mesh.shape).items())
                         if getattr(sharding, "mesh", None) is not None
                         else ()))


def _mesh_spanning(v, sharding) -> bool:
    """True when the compiled identity may consume ``v`` directly: the
    array is either not fully addressable (multi-process — put_global
    could not even read it) or already lives on exactly the target
    mesh's devices.  A committed array on SOME OTHER device set (a
    single-device checkpoint staging buffer, a sub-mesh) would make the
    jit raise 'incompatible devices', so it takes the host path."""
    if not v.is_fully_addressable:
        return True
    return set(v.sharding.device_set) == set(sharding.mesh.devices.flat)


def _constrain(x, sharding):
    """Sharding constraint that works both under trace (the in-step
    reduce-scatter / all-gather) and eagerly (placement — the compiled
    reshard identity keeps mesh-resident device arrays on device and is
    multi-process safe; host values and off-mesh arrays go through
    put_global)."""
    import jax

    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    if isinstance(x, jax.Array) and _mesh_spanning(x, sharding):
        return _identity_jit(sharding, "zero.reshard")(x)
    return _put_global(x, sharding)


def _put_global(v, sharding):
    from paddle_tpu.parallel.api import put_global

    return put_global(v, sharding)
