"""Sharding helpers and the DataParallel wrapper.

Reference: MultiGradientMachine.h:41-165 (single-node DP with ring grad
gather / value scatter among trainer threads) and the pserver sync-SGD path
(ParameterServer2.cpp:362 addGradient). Both collapse here into: shard the
batch over the 'data' mesh axis, keep params replicated (or sharded for
ZeRO), and let XLA insert psum on the gradients.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.sequence import SequenceBatch


def shard_batch(mesh, value, axis: str = "data"):
    """Place a host batch sharded along its leading dim over ``axis``.

    SequenceBatch: the flat token buffer is sharded over capacity and the
    per-sequence vectors over num_seqs — both leading dims are sized per
    DataFeeder bucketing to be divisible by the axis size.
    """
    if isinstance(value, SequenceBatch):
        return SequenceBatch(
            data=shard_batch(mesh, value.data, axis),
            segment_ids=shard_batch(mesh, value.segment_ids, axis),
            lengths=shard_batch(mesh, value.lengths, axis),
            sub_segment_ids=None if value.sub_segment_ids is None
            else shard_batch(mesh, value.sub_segment_ids, axis),
        )
    spec = P(axis, *([None] * (np.ndim(value) - 1)))
    return jax.device_put(value, NamedSharding(mesh, spec))


def replicate(mesh, tree):
    return jax.device_put(tree, NamedSharding(mesh, P()))


def put_global(v, sharding):
    """Place a host array onto a (possibly multi-process) sharding.

    Single-process: plain device_put. Multi-process: device_put cannot
    address other hosts' devices, so build the global array from a
    callback over the full host copy every process holds (params and
    replicated feeds are host-identical across processes — the pserver
    sendBackParameter invariant)."""
    if jax.process_count() <= 1:
        return jax.device_put(v, sharding)
    host = np.asarray(v)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def param_sharding(mesh, params: Dict[str, jax.Array], specs=None,
                   zero_axis: Optional[str] = None):
    """Build NamedShardings for a param dict.

    Default: replicated. ``zero_axis``: shard the largest dim of every tensor
    over that axis when divisible (ZeRO-3-style weight sharding — the
    pserver block-partitioning analog, ParameterServer2.h:94-120).
    Per-param ParamAttr.sharding (axis names per dim) takes precedence.
    """
    out = {}
    axis_size = dict(zip(mesh.axis_names, mesh.devices.shape))
    for name, v in params.items():
        spec = None
        attr = None
        if specs is not None and name in specs:
            attr = specs[name].attr
        if attr is not None and attr.sharding is not None:
            # dims naming an axis this mesh does not have fall back to
            # replicated: one spec dict serves every mesh topology (an
            # expert-sharded FFN trains unsharded on a plain data mesh)
            spec = P(*(a if (a is None or a in axis_size) else None
                       for a in attr.sharding))
        elif zero_axis is not None:
            n = axis_size[zero_axis]
            dims = [None] * v.ndim
            for d in np.argsort(v.shape)[::-1]:
                if v.shape[d] % n == 0 and v.shape[d] >= n:
                    dims[int(d)] = zero_axis
                    break
            spec = P(*dims)
        else:
            spec = P()
        out[name] = NamedSharding(mesh, spec)
    return out


class DataParallel:
    """Convenience: place feeds/params for data-parallel training.

    Used by trainer.SGD when a mesh is passed; exposed for custom loops.
    """

    def __init__(self, mesh, axis: str = "data"):
        self.mesh = mesh
        self.axis = axis
        enforce_that(axis in mesh.axis_names, f"no axis {axis!r} in mesh",
                     context="DataParallel")

    def shard_feeds(self, feeds: Dict[str, object]) -> Dict[str, object]:
        return {k: shard_batch(self.mesh, v, self.axis) for k, v in feeds.items()}

    def replicate_params(self, params):
        return replicate(self.mesh, params)

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))
