"""Expert-parallel Mixture-of-Experts FFN — the EP compute path.

New-build extension (the reference predates MoE; its expert-parallel
machinery is the sparse/pserver row distribution this module's dispatch
generalizes — SURVEY §2.3 "large model dist train"): a Switch-style
top-1 / GShard-style top-2 MoE FFN whose experts are sharded over a
mesh axis, with the classic dispatch/combine all_to_all pattern from
the scaling-book recipe:

  tokens (sharded over the axis) --router--> per-expert capacity buffers
  --all_to_all--> each shard runs ITS experts' FFN on tokens from every
  shard --all_to_all--> gated combine back to token order.

``moe_ffn_reference`` is the collectives-free dense formulation used for
single-device runs and as the parity oracle; ``moe_ffn`` is the
shard_map/all_to_all version. Tokens over capacity are DROPPED (pass
through as zeros — callers add the residual), the Switch convention.
Top-2 routing renormalizes the two gates to sum to 1 (GShard); per-
expert capacity is UNCHANGED by ``top_k`` — k token-choices compete for
the same ``ceil(T/E * capacity_factor)`` slots, so raise the factor
toward ``k *`` the top-1 value when drops matter.

``MoEConfig`` is the model-zoo surface: it carries the routing
hyperparameters AND the placement plan that puts every expert weight's
leading E dim on the ``expert`` mesh axis through
``parallel.placement.plan_param_attrs`` — the one-placement-layer
story.  ``record_moe_stats`` lands the drop-rate/load statistics on the
obs metrics registry after a step.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.platform.enforce import enforce_that

from paddle_tpu.parallel.compat import no_rep_check_kw, shard_map

# the audited compiled-path site every expert-parallel dispatch runs
# through; its contract (below) declares the closed-form collective
# budget `python -m paddle_tpu.analysis sharding` checks
MOE_SITE = "parallel.moe"


def moe_contract(mesh, axis: str, e: int, cap: int, d: int,
                 with_stats: bool = False):
    """The REAL declared sharding contract for one EP dispatch geometry:
    tokens shard their leading dim over ``axis``, the router replicates,
    expert weights shard their leading E dim, outputs come back
    token-sharded with a replicated aux loss.

    The comm budget is the closed form of exactly the collectives the
    compiled program contains (the arXiv 2112.09017 cost model the
    auditor prices with — budget == estimate, so ANY extra collective
    trips the gate):

      - dispatch + combine all_to_all pair: each moves the per-shard
        [E, C, D] f32 capacity buffer, ``b = e*cap*d*4`` bytes, costed
        ``b*(n-1)/n`` per hop;
      - the two aux-stat pmeans ([E] f32 fraction / mean-prob), psum
        lowered: ``2*4e*(n-1)/n`` each;
      - the drop-rate pmean (scalar f32) when stats are requested.
    """
    from paddle_tpu.analysis.retrace import SiteContract
    from paddle_tpu.analysis.sharding import (all_reduce_bytes,
                                              all_to_all_bytes)

    n = int(mesh.shape[axis])
    comm = 2.0 * all_to_all_bytes(e * cap * d * 4, n)
    comm += 2.0 * all_reduce_bytes(4 * e, n)
    out_specs = ((axis,), ())
    if with_stats:
        comm += all_reduce_bytes(4, n)       # drop-rate scalar pmean
        out_specs = ((axis,), (), (), ())
    return SiteContract(
        allow_collectives=True,
        mesh_axes=tuple((a, int(mesh.shape[a])) for a in mesh.axis_names),
        comm_bytes=comm,
        in_specs=((axis,), (), (axis,), (axis,), (axis,), (axis,)),
        out_specs=out_specs)


@dataclass(frozen=True)
class MoEConfig:
    """Model-zoo MoE block configuration + expert placement.

    ``num_experts``/``expert_hidden`` size the block (``expert_hidden``
    0 lets the layer derive it from the model width); ``top_k`` selects
    Switch (1) or GShard (2) routing; ``axis`` names the mesh axis the
    expert weights' leading E dim shards over.  ``capacity_factor`` is
    per-expert and top_k-independent (see module docstring).
    """

    num_experts: int
    expert_hidden: int = 0
    capacity_factor: float = 1.25
    top_k: int = 1
    axis: str = "expert"
    aux_weight: float = 0.01

    def param_plan(self, prefix: str = "") -> Dict[str, Tuple]:
        """{param name: per-dim axis tuple} for the expert weights —
        the ``plan_param_attrs`` input that resolves this block through
        the one placement layer (router replicates: no entry)."""
        ax = self.axis
        return {f"{prefix}w1": (ax, None, None), f"{prefix}b1": (ax, None),
                f"{prefix}w2": (ax, None, None), f"{prefix}b2": (ax, None)}

    def param_attrs(self, prefix: str = "") -> Dict[str, object]:
        """{param name: ParamAttr} with the expert-axis sharding set —
        ready to attach to the zoo layer's ParamSpecs."""
        from paddle_tpu.parallel.placement import plan_param_attrs

        return {k: v.attr
                for k, v in plan_param_attrs(self.param_plan(prefix)).items()}


class MoEParams(NamedTuple):
    """Weights for a MoE FFN: router [D, E]; experts stacked on the
    leading axis — w1 [E, D, H], b1 [E, H], w2 [E, H, D], b2 [E, D]."""

    router: jax.Array
    w1: jax.Array
    b1: jax.Array
    w2: jax.Array
    b2: jax.Array


def init_moe_params(key, d_model: int, hidden: int, num_experts: int,
                    scale: float = 0.02) -> MoEParams:
    ks = jax.random.split(key, 3)
    return MoEParams(
        router=jax.random.normal(ks[0], (d_model, num_experts)) * scale,
        w1=jax.random.normal(ks[1], (num_experts, d_model, hidden)) * scale,
        b1=jnp.zeros((num_experts, hidden)),
        w2=jax.random.normal(ks[2], (num_experts, hidden, d_model)) * scale,
        b2=jnp.zeros((num_experts, d_model)))


def _route(x, router_w):
    """Top-1 routing: (expert [T], gate [T], probs [T, E])."""
    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    return expert, gate, probs


def _route_topk(x, router_w, k: int):
    """Top-k routing: (experts [T, k], gates [T, k], probs [T, E]).

    k == 1 keeps the raw Switch gate (softmax prob of the winner);
    k > 1 renormalizes the k winning gates to sum to 1 (GShard top-2
    convention) so the combined output stays on the activation scale.
    """
    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    gates, experts = jax.lax.top_k(probs, k)
    experts = experts.astype(jnp.int32)
    if k > 1:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return experts, gates, probs


def _aux_stats(probs: jax.Array, expert: jax.Array):
    """Per-batch routing statistics: (fraction routed to e, mean prob e)."""
    e = probs.shape[-1]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
    return jnp.mean(onehot, axis=0), jnp.mean(probs, axis=0)


def aux_load_balance_loss(probs: jax.Array, expert: jax.Array) -> jax.Array:
    """Switch aux loss: E * sum_e fraction_e * mean_prob_e (pushes routing
    toward uniform expert utilisation)."""
    fraction, mean_prob = _aux_stats(probs, expert)
    return probs.shape[-1] * jnp.sum(fraction * mean_prob)


def _dispatch_mask(expert, num_experts: int, capacity: int):
    """[T, E, C] one-hot dispatch tensor: token t occupies slot
    rank-of-t-within-its-expert of expert e; tokens past capacity drop."""
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)  # [T, E]
    pos = jnp.cumsum(onehot, axis=0) - 1                           # [T, E]
    keep = (pos < capacity) & (onehot > 0)
    slot = jnp.clip(pos, 0, capacity - 1)
    disp = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)       # [T,E,C]
    return disp * keep[..., None].astype(jnp.float32)


def _dispatch_mask_topk(experts, num_experts: int, capacity: int):
    """[T, k, E, C] dispatch tensor for top-k routing.

    Capacity slots are claimed CHOICE-MAJOR: every token's first choice
    ranks before any token's second choice (the GShard priority — a
    second choice never evicts a first choice).  k == 1 reduces exactly
    to :func:`_dispatch_mask`.
    """
    t, k = experts.shape
    onehot = jax.nn.one_hot(experts, num_experts, dtype=jnp.int32)  # [T,k,E]
    flat = jnp.swapaxes(onehot, 0, 1).reshape(k * t, num_experts)
    pos = (jnp.cumsum(flat, axis=0) - 1).reshape(k, t, num_experts)
    pos = jnp.swapaxes(pos, 0, 1)                                   # [T,k,E]
    keep = (pos < capacity) & (onehot > 0)
    slot = jnp.clip(pos, 0, capacity - 1)
    disp = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)       # [T,k,E,C]
    return disp * keep[..., None].astype(jnp.float32)


def _drop_rate(disp, t: int, k: int):
    """Fraction of (token, choice) dispatch slots that fell past their
    expert's capacity — 0.0 when nothing drops."""
    return 1.0 - jnp.sum(disp) / float(t * k)


def _expert_ffn(buf, w1, b1, w2, b2, act):
    """buf [E_loc, N, D] through each local expert's two-layer FFN."""
    h = act(jnp.einsum("end,edh->enh", buf, w1) + b1[:, None, :])
    return jnp.einsum("enh,ehd->end", h, w2) + b2[:, None, :]


def moe_ffn_reference(x: jax.Array, params: MoEParams,
                      capacity_factor: float = 1.25,
                      act=jax.nn.gelu, top_k: int = 1,
                      return_stats: bool = False):
    """Single-device dense formulation (and the parity oracle).

    x: [T, D] tokens. Returns (y [T, D], aux_loss scalar) — plus a
    ``{"drop_rate", "expert_fraction"}`` stats dict when
    ``return_stats`` (feed it to :func:`record_moe_stats`).  Tokens
    past an expert's capacity pass through as ZEROS (add the residual
    outside).
    """
    t, d = x.shape
    e = params.router.shape[1]
    cap = max(1, math.ceil(t / e * capacity_factor))
    experts, gates, probs = _route_topk(x, params.router, top_k)
    disp = _dispatch_mask_topk(experts, e, cap)            # [T, k, E, C]
    buf = jnp.einsum("tkec,td->ecd", disp,
                     x.astype(jnp.float32))                # [E, C, D]
    out = _expert_ffn(buf, params.w1, params.b1, params.w2, params.b2,
                      act)                                  # [E, C, D]
    wdisp = disp * gates[:, :, None, None]
    y = jnp.einsum("tkec,ecd->td", wdisp, out)             # gated combine
    aux = aux_load_balance_loss(probs, experts[:, 0])
    if not return_stats:
        return y.astype(x.dtype), aux
    fraction, _ = _aux_stats(probs, experts[:, 0])
    stats = {"drop_rate": _drop_rate(disp, t, top_k),
             "expert_fraction": fraction}
    return y.astype(x.dtype), aux, stats


def moe_ffn(mesh, x: jax.Array, params: MoEParams, axis: str = "expert",
            capacity_factor: float = 1.25, act=jax.nn.gelu,
            top_k: int = 1, return_stats: bool = False):
    """Expert-parallel MoE FFN: tokens AND experts sharded over ``axis``.

    x: [T, D] global tokens (T divisible by the axis size); expert weights
    shard on their leading E axis. Dispatch/combine ride two all_to_alls
    over ICI. Per-(shard, expert) capacity is
    ceil(T_local / E * capacity_factor) so capacity is enforced per
    SOURCE shard — the standard Switch sharded formulation (a globally
    unlucky routing can drop more tokens than the dense oracle; parity
    tests use uniform-ish routing or generous capacity).

    Returns (y [T, D] in token order, aux_loss scalar); with
    ``return_stats``, appends a ``{"drop_rate", "expert_fraction"}``
    dict of GLOBAL (pmean'd) routing statistics.
    """
    n = mesh.shape[axis]
    t, d = x.shape
    e = params.router.shape[1]
    enforce_that(t % n == 0, f"tokens {t} not divisible by {axis}={n}",
                 context="moe")
    enforce_that(e % n == 0, f"experts {e} not divisible by {axis}={n}",
                 context="moe")
    t_loc = t // n
    cap = max(1, math.ceil(t_loc / e * capacity_factor))
    fn = _moe_jit(mesh, axis, e, cap, int(d), act, int(top_k),
                  bool(return_stats))
    out = fn(x, params.router, params.w1, params.b1, params.w2,
             params.b2)
    if not return_stats:
        return out
    y, aux, drop, fraction = out
    return y, aux, {"drop_rate": drop, "expert_fraction": fraction}


@functools.lru_cache(maxsize=64)
def _moe_jit(mesh, axis: str, e: int, cap: int, d: int, act, top_k: int,
             with_stats: bool):
    """One audited jit per (mesh, axis, experts, capacity, width,
    activation, top_k, stats) — the zero.py identity idiom; bounded +
    stable-callable caveats as ``_pipeline_jit`` (``act`` keys by
    identity).  The geometry in the key is exactly what the closed-form
    comm budget needs, so the REAL contract is computed at wrap time."""
    n = mesh.shape[axis]

    def local(xl, router_w, w1, b1, w2, b2):
        # xl [T_loc, D]; w1 [E_loc, D, H] (this shard's experts)
        t_loc = xl.shape[0]
        experts, gates, probs = _route_topk(xl, router_w, top_k)
        disp = _dispatch_mask_topk(experts, e, cap)      # [T_loc, k, E, C]
        buf = jnp.einsum("tkec,td->ecd", disp,
                         xl.astype(jnp.float32))           # [E, C, D]
        # exchange: shard s sends buf rows of shard r's experts to r
        buf = buf.reshape(n, e // n, cap, d)
        buf = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                 tiled=False)              # [n, E_loc, C, D]
        # this shard now holds every source shard's buffers for ITS
        # experts: fold sources into the capacity dimension
        buf = jnp.swapaxes(buf, 0, 1).reshape(e // n, n * cap, d)
        out = _expert_ffn(buf, w1, b1, w2, b2, act)        # [E_loc, n*C, D]
        out = jnp.swapaxes(out.reshape(e // n, n, cap, d), 0, 1)
        out = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                 tiled=False)   # [owner_shard, E_loc, C, D]
        # flat [owner, local] order IS global expert id owner*(E/n)+local
        out = out.reshape(e, cap, d)                       # [E, C, D]
        wdisp = disp * gates[:, :, None, None]
        y = jnp.einsum("tkec,ecd->td", wdisp, out)
        # GLOBAL routing statistics (pmean the components, THEN combine —
        # a mean of per-shard products is not the global aux loss)
        fraction, mean_prob = _aux_stats(probs, experts[:, 0])
        fraction = jax.lax.pmean(fraction, axis)
        mean_prob = jax.lax.pmean(mean_prob, axis)
        aux = e * jnp.sum(fraction * mean_prob)
        if not with_stats:
            return y.astype(xl.dtype), aux
        drop = jax.lax.pmean(_drop_rate(disp, t_loc, top_k), axis)
        return y.astype(xl.dtype), aux, drop, fraction

    out_specs = (P(axis, None), P(), P(), P()) if with_stats \
        else (P(axis, None), P())
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(None, None), P(axis, None, None),
                  P(axis, None), P(axis, None, None), P(axis, None)),
        out_specs=out_specs,
        **no_rep_check_kw())

    from paddle_tpu.analysis.retrace import audit_jit

    return audit_jit(fn, site=MOE_SITE,
                     xla_contract=moe_contract(mesh, axis, e, cap, d,
                                               with_stats))


def record_moe_stats(stats, registry=None, prefix: str = "moe") -> None:
    """Land one step's routing statistics on the obs metrics registry
    (host-side: call OUTSIDE jit, on concrete step outputs):

      - ``{prefix}_drop_rate`` gauge — fraction of (token, choice)
        dispatch slots past capacity this step;
      - ``{prefix}_expert_load_imbalance`` gauge — max expert load
        relative to uniform (1.0 == perfectly balanced routing);
      - ``{prefix}_dropped_tokens`` counter — cumulative drop mass.
    """
    import numpy as np

    from paddle_tpu.obs.registry import default_registry

    reg = registry if registry is not None else default_registry()
    drop = float(stats["drop_rate"])
    reg.gauge(f"{prefix}_drop_rate",
              "fraction of (token, choice) MoE dispatch slots dropped "
              "past expert capacity in the last recorded step").set(drop)
    frac = stats.get("expert_fraction")
    if frac is not None:
        f = np.asarray(frac, dtype=np.float64)
        if f.size:
            reg.gauge(f"{prefix}_expert_load_imbalance",
                      "max expert routing fraction relative to uniform "
                      "(1.0 = balanced)").set(float(f.max() * f.size))
    if drop > 0.0:
        reg.counter(f"{prefix}_dropped_tokens",
                    "cumulative dropped MoE dispatch mass").inc(drop)
