"""Expert-parallel Mixture-of-Experts FFN — the EP compute path.

New-build extension (the reference predates MoE; its expert-parallel
machinery is the sparse/pserver row distribution this module's dispatch
generalizes — SURVEY §2.3 "large model dist train"): a Switch-style
top-1 MoE FFN whose experts are sharded over a mesh axis, with the
classic dispatch/combine all_to_all pattern from the scaling-book recipe:

  tokens (sharded over the axis) --router--> per-expert capacity buffers
  --all_to_all--> each shard runs ITS experts' FFN on tokens from every
  shard --all_to_all--> gated combine back to token order.

``moe_ffn_reference`` is the collectives-free dense formulation used for
single-device runs and as the parity oracle; ``moe_ffn`` is the
shard_map/all_to_all version. Tokens over capacity are DROPPED (pass
through as zeros — callers add the residual), the Switch convention.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from paddle_tpu.platform.enforce import enforce_that

from paddle_tpu.parallel.compat import no_rep_check_kw, shard_map

# the audited compiled-path site every expert-parallel dispatch runs
# through (see parallel/pipeline.py for the stub-contract rationale)
MOE_SITE = "parallel.moe"


def stub_contract(axis: str = "expert"):
    """Declared sharding contract for the EP dispatch: tokens shard
    their leading dim over ``axis``, the router replicates, expert
    weights shard their leading E dim, outputs come back token-sharded
    with a replicated aux loss; the two all_to_alls and the stats
    pmean are the point."""
    from paddle_tpu.analysis.retrace import SiteContract

    return SiteContract(
        allow_collectives=True,
        in_specs=((axis,), (), (axis,), (axis,), (axis,), (axis,)),
        out_specs=((axis,), ()))


class MoEParams(NamedTuple):
    """Weights for a top-1 MoE FFN: router [D, E]; experts stacked on the
    leading axis — w1 [E, D, H], b1 [E, H], w2 [E, H, D], b2 [E, D]."""

    router: jax.Array
    w1: jax.Array
    b1: jax.Array
    w2: jax.Array
    b2: jax.Array


def init_moe_params(key, d_model: int, hidden: int, num_experts: int,
                    scale: float = 0.02) -> MoEParams:
    ks = jax.random.split(key, 3)
    return MoEParams(
        router=jax.random.normal(ks[0], (d_model, num_experts)) * scale,
        w1=jax.random.normal(ks[1], (num_experts, d_model, hidden)) * scale,
        b1=jnp.zeros((num_experts, hidden)),
        w2=jax.random.normal(ks[2], (num_experts, hidden, d_model)) * scale,
        b2=jnp.zeros((num_experts, d_model)))


def _route(x, router_w):
    """Top-1 routing: (expert [T], gate [T], probs [T, E])."""
    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    return expert, gate, probs


def _aux_stats(probs: jax.Array, expert: jax.Array):
    """Per-batch routing statistics: (fraction routed to e, mean prob e)."""
    e = probs.shape[-1]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
    return jnp.mean(onehot, axis=0), jnp.mean(probs, axis=0)


def aux_load_balance_loss(probs: jax.Array, expert: jax.Array) -> jax.Array:
    """Switch aux loss: E * sum_e fraction_e * mean_prob_e (pushes routing
    toward uniform expert utilisation)."""
    fraction, mean_prob = _aux_stats(probs, expert)
    return probs.shape[-1] * jnp.sum(fraction * mean_prob)


def _dispatch_mask(expert, num_experts: int, capacity: int):
    """[T, E, C] one-hot dispatch tensor: token t occupies slot
    rank-of-t-within-its-expert of expert e; tokens past capacity drop."""
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)  # [T, E]
    pos = jnp.cumsum(onehot, axis=0) - 1                           # [T, E]
    keep = (pos < capacity) & (onehot > 0)
    slot = jnp.clip(pos, 0, capacity - 1)
    disp = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)       # [T,E,C]
    return disp * keep[..., None].astype(jnp.float32)


def _expert_ffn(buf, w1, b1, w2, b2, act):
    """buf [E_loc, N, D] through each local expert's two-layer FFN."""
    h = act(jnp.einsum("end,edh->enh", buf, w1) + b1[:, None, :])
    return jnp.einsum("enh,ehd->end", h, w2) + b2[:, None, :]


def moe_ffn_reference(x: jax.Array, params: MoEParams,
                      capacity_factor: float = 1.25,
                      act=jax.nn.gelu):
    """Single-device dense formulation (and the parity oracle).

    x: [T, D] tokens. Returns (y [T, D], aux_loss scalar). Tokens past an
    expert's capacity pass through as ZEROS (add the residual outside).
    """
    import math

    t, d = x.shape
    e = params.router.shape[1]
    cap = max(1, math.ceil(t / e * capacity_factor))
    expert, gate, probs = _route(x, params.router)
    disp = _dispatch_mask(expert, e, cap)                  # [T, E, C]
    buf = jnp.einsum("tec,td->ecd", disp,
                     x.astype(jnp.float32))                # [E, C, D]
    out = _expert_ffn(buf, params.w1, params.b1, params.w2, params.b2,
                      act)                                  # [E, C, D]
    y = jnp.einsum("tec,ecd->td", disp, out)               # undispatch
    y = y * gate[:, None]
    return y.astype(x.dtype), aux_load_balance_loss(probs, expert)


def moe_ffn(mesh, x: jax.Array, params: MoEParams, axis: str = "expert",
            capacity_factor: float = 1.25, act=jax.nn.gelu):
    """Expert-parallel MoE FFN: tokens AND experts sharded over ``axis``.

    x: [T, D] global tokens (T divisible by the axis size); expert weights
    shard on their leading E axis. Dispatch/combine ride two all_to_alls
    over ICI. Per-(shard, expert) capacity is
    ceil(T_local / E * capacity_factor) so capacity is enforced per
    SOURCE shard — the standard Switch sharded formulation (a globally
    unlucky routing can drop more tokens than the dense oracle; parity
    tests use uniform-ish routing or generous capacity).

    Returns (y [T, D] in token order, aux_loss scalar).
    """
    n = mesh.shape[axis]
    t, d = x.shape
    e = params.router.shape[1]
    enforce_that(t % n == 0, f"tokens {t} not divisible by {axis}={n}",
                 context="moe")
    enforce_that(e % n == 0, f"experts {e} not divisible by {axis}={n}",
                 context="moe")
    import math

    t_loc = t // n
    cap = max(1, math.ceil(t_loc / e * capacity_factor))
    fn = _moe_jit(mesh, axis, e, cap, act)
    return fn(x, params.router, params.w1, params.b1, params.w2,
              params.b2)


@functools.lru_cache(maxsize=64)
def _moe_jit(mesh, axis: str, e: int, cap: int, act):
    """One audited jit per (mesh, axis, experts, capacity, activation)
    — the zero.py identity idiom; bounded + stable-callable caveats as
    ``_pipeline_jit`` (``act`` keys by identity)."""
    n = mesh.shape[axis]

    def local(xl, router_w, w1, b1, w2, b2):
        # xl [T_loc, D]; w1 [E_loc, D, H] (this shard's experts)
        d = xl.shape[1]
        expert, gate, probs = _route(xl, router_w)
        disp = _dispatch_mask(expert, e, cap)              # [T_loc, E, C]
        buf = jnp.einsum("tec,td->ecd", disp,
                         xl.astype(jnp.float32))           # [E, C, D]
        # exchange: shard s sends buf rows of shard r's experts to r
        buf = buf.reshape(n, e // n, cap, d)
        buf = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                 tiled=False)              # [n, E_loc, C, D]
        # this shard now holds every source shard's buffers for ITS
        # experts: fold sources into the capacity dimension
        buf = jnp.swapaxes(buf, 0, 1).reshape(e // n, n * cap, d)
        out = _expert_ffn(buf, w1, b1, w2, b2, act)        # [E_loc, n*C, D]
        out = jnp.swapaxes(out.reshape(e // n, n, cap, d), 0, 1)
        out = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                 tiled=False)   # [owner_shard, E_loc, C, D]
        # flat [owner, local] order IS global expert id owner*(E/n)+local
        out = out.reshape(e, cap, d)                       # [E, C, D]
        y = jnp.einsum("tec,ecd->td", disp, out) * gate[:, None]
        # GLOBAL routing statistics (pmean the components, THEN combine —
        # a mean of per-shard products is not the global aux loss)
        fraction, mean_prob = _aux_stats(probs, expert)
        fraction = jax.lax.pmean(fraction, axis)
        mean_prob = jax.lax.pmean(mean_prob, axis)
        aux = e * jnp.sum(fraction * mean_prob)
        return y.astype(xl.dtype), aux

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(None, None), P(axis, None, None),
                  P(axis, None), P(axis, None, None), P(axis, None)),
        out_specs=(P(axis, None), P()),
        **no_rep_check_kw())

    from paddle_tpu.analysis.retrace import audit_jit

    return audit_jit(fn, site=MOE_SITE, xla_contract=stub_contract(axis))
