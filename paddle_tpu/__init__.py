"""paddle_tpu (bootstrap init — full surface restored as modules land)."""
from paddle_tpu import platform
from paddle_tpu.platform.device import init, device_count, default_mesh, is_initialized
from paddle_tpu.platform.flags import FLAGS
__version__ = "0.1.0"
