"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
2017-era PaddlePaddle (reference: xiaoyeye1117/Paddle), re-architected for
JAX/XLA/pallas/pjit.

The user surface mirrors the reference's ``paddle.v2`` API
(reference: python/paddle/v2/__init__.py) — ``init()``, ``layer``,
``optimizer``, ``trainer.SGD``, ``reader``, ``dataset``, ``infer`` — while the
engine underneath is jit-compiled XLA partitioned over an ICI/DCN device mesh
instead of C++ gradient machines and a parameter-server fleet.
"""

from paddle_tpu import platform
from paddle_tpu.platform import enforce
from paddle_tpu.platform.device import init, device_count, default_mesh, is_initialized
from paddle_tpu.platform.flags import FLAGS

from paddle_tpu import activation
from paddle_tpu import attr
from paddle_tpu import data_type
from paddle_tpu import initializer
from paddle_tpu import pooling
from paddle_tpu import layer
from paddle_tpu import networks
from paddle_tpu import optimizer
from paddle_tpu import evaluator
from paddle_tpu import trainer
from paddle_tpu import event
from paddle_tpu import parameters
from paddle_tpu import topology
from paddle_tpu import inference
from paddle_tpu import reader
from paddle_tpu import dataset
from paddle_tpu import minibatch
from paddle_tpu import parallel
from paddle_tpu import sequence
from paddle_tpu import serving
from paddle_tpu import resilience

from paddle_tpu.minibatch import batch
from paddle_tpu.parameters import Parameters
from paddle_tpu.inference import infer, Inference
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.sequence import SequenceBatch

__version__ = "0.1.0"

__all__ = [
    "init",
    "batch",
    "infer",
    "layer",
    "networks",
    "optimizer",
    "evaluator",
    "trainer",
    "event",
    "parameters",
    "topology",
    "reader",
    "dataset",
    "minibatch",
    "parallel",
    "activation",
    "attr",
    "data_type",
    "initializer",
    "pooling",
    "sequence",
    "serving",
    "resilience",
    "Parameters",
    "DataFeeder",
    "SequenceBatch",
    "FLAGS",
]
