"""Task-queue service: the Go master's state machine in Python.

Mirrors go/master/service.go —
  - dataset partitioning into chunk tasks         (service.go:106)
  - todo/pending/done queues with timeout requeue (service.go:313-356)
  - per-task failure count and discard            (service.go:368-448)
  - state snapshot persisted on every mutation    (service.go:207,
    etcd_client.go:96-129 — here a JSON file written atomically)
  - RequestSaveModel dedup so only one trainer
    saves the model at a time                     (service.go:474)
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field, asdict
from typing import Dict, List, Optional, Sequence, Tuple

from .recordio import recordio_index

MAX_TASK_FAILURES = 3


class LeaseTable:
    """Slot + token TTL leases — the etcd lease-id analog, factored out
    of :class:`Service` so the serving fleet's replica lifecycle
    (``paddle_tpu/serving/fleet.py``) and the training master's trainer
    membership run the SAME state machine.

    Semantics (go/pserver/etcd_client.go:67-166):

    - ``register`` claims the smallest free slot and mints a fresh
      token; slots are REUSED after expiry, so the token is what makes
      an owner unique across reclamations;
    - ``heartbeat`` renews only when the presented token matches the
      slot's CURRENT token AND the lease is still live — a zombie
      renewing by slot number alone (its lease lapsed, possibly
      reclaimed by a new owner) gets False and must re-register.  The
      deadline is re-checked directly in ``heartbeat`` (not only via the
      ``expire`` sweep), so a renewal racing slot reclamation can never
      resurrect an expired lease;
    - ``expire`` sweeps lapsed leases and returns the freed slots so the
      owner (task queue, fleet router) can requeue that member's
      in-flight work.  ``register``/``heartbeat``/``members`` sweep
      internally too, and those calls discard the return value — an
      owner that must never miss a freed slot (the master requeues the
      dead trainer's tasks) passes ``on_expire``, which fires for every
      freed slot on EVERY sweep, internal ones included.

    Not thread-safe by itself: :class:`Service` calls it under its own
    lock; the serving fleet is single-threaded on the engine tick loop.
    """

    def __init__(self, ttl_s: float, time_fn=time.time, on_expire=None,
                 tracer=None):
        self.ttl_s = float(ttl_s)
        self._time = time_fn
        self._on_expire = on_expire
        # obs hook (paddle_tpu.obs): lease transitions — register,
        # zombie-rejected renewal, expiry, drop — land on the fleet
        # trace timeline.  None (the default, and the training master's
        # setting) costs one is-None check per transition.  Tokens are
        # NEVER recorded: slots identify members on the timeline.
        self.tracer = tracer
        # slot -> (lease deadline, lease token); callers serialize:
        # Service wraps every call in its RLock, and the serving fleet
        # drives its own table from the single engine tick thread
        # guarded_by(serialized: callers hold Service RLock / tick loop)
        self._members: Dict[int, Tuple[float, str]] = {}

    def register(self, ttl_s: Optional[float] = None) -> Tuple[int, str]:
        import secrets

        self.expire()
        slot = 0
        while slot in self._members:
            slot += 1
        token = secrets.token_hex(8)
        self._members[slot] = (self._time() + float(ttl_s or self.ttl_s),
                               token)
        if self.tracer is not None:
            self.tracer.instant("lease_register", cat="lease", lease=slot)
        return slot, token

    def heartbeat(self, slot: int, token: str,
                  ttl_s: Optional[float] = None) -> bool:
        """Renew a lease.  False = the lease is gone: expired, or the
        slot was reclaimed by a new owner whose token doesn't match."""
        self.expire()
        now = self._time()
        ent = self._members.get(slot)
        if ent is None or ent[1] != token or ent[0] <= now:
            if self.tracer is not None:
                self.tracer.instant("lease_reject", cat="lease",
                                    lease=slot)
            return False
        self._members[slot] = (now + float(ttl_s or self.ttl_s), token)
        return True

    def alive(self, slot: int, token: str) -> bool:
        """Liveness probe without renewal (the fleet's per-tick death
        sweep reads this; only heartbeats extend the deadline)."""
        self.expire()
        ent = self._members.get(slot)
        return ent is not None and ent[1] == token

    def drop(self, slot: int, token: str) -> bool:
        """Explicitly release a lease (clean drain / fleet fencing of a
        killed replica).  Token-checked like heartbeat, so a zombie
        can't evict the slot's new owner."""
        ent = self._members.get(slot)
        if ent is None or ent[1] != token:
            return False
        del self._members[slot]
        if self.tracer is not None:
            self.tracer.instant("lease_drop", cat="lease", lease=slot)
        return True

    def members(self) -> List[int]:
        self.expire()
        return sorted(self._members)

    def expire(self) -> List[int]:
        """Sweep lapsed leases; returns the slots freed this call (and
        reports each to ``on_expire`` after the table is consistent, so
        the hook can re-register without racing the sweep)."""
        now = self._time()
        dead = [s for s, (dl, _) in self._members.items() if dl <= now]
        for slot in dead:
            del self._members[slot]
        if self.tracer is not None:
            for slot in dead:
                self.tracer.instant("lease_expire", cat="lease",
                                    lease=slot)
        if self._on_expire is not None:
            for slot in dead:
                self._on_expire(slot)
        return dead


@dataclass
class Chunk:
    path: str
    offset: int
    count: int


@dataclass
class Task:
    id: int
    epoch: int = 0
    num_failures: int = 0
    chunks: List[Chunk] = field(default_factory=list)


class Service:
    """In-memory task queue with optional file snapshot.

    ``time_fn`` is injectable for deterministic timeout tests (the Go
    tests drive timeouts the same way via internal hooks,
    service_internal_test.go).
    """

    def __init__(self, chunks_per_task: int = 8, timeout_s: float = 60.0,
                 max_failures: int = MAX_TASK_FAILURES,
                 snapshot_path: Optional[str] = None, time_fn=time.time):
        self.chunks_per_task = max(1, int(chunks_per_task))
        self.timeout_s = timeout_s
        self.max_failures = max_failures
        self.snapshot_path = snapshot_path
        self._time = time_fn
        self._lock = threading.RLock()

        self._todo: List[Task] = []   # guarded_by(_lock)
        # task id -> (task, deadline)
        # guarded_by(_lock)
        self._pending: Dict[int, Tuple[Task, float]] = {}
        self._done: List[Task] = []   # guarded_by(_lock)
        self._dataset_set = False   # guarded_by(_lock)
        self._dataset_paths: List[str] = []   # guarded_by(_lock)
        self._next_id = 0   # guarded_by(_lock)
        self._pass_no = 0   # guarded_by(_lock)
        # save-model dedup: time until which save requests are "taken"
        self._save_until = 0.0   # guarded_by(_lock)
        # trainer membership: the etcd Register/lease analog
        # (go/pserver/etcd_client.go:67-166 — each trainer holds an index
        # slot under a TTL lease; a missed heartbeat frees the slot and
        # requeues the trainer's in-flight tasks)
        self.lease_ttl_s = 3 * self.timeout_s if self.timeout_s else 180.0
        # the etcd Register/lease analog, shared with the serving fleet:
        # slots are REUSED after expiry, so a zombie trainer renewing by
        # slot number alone could hijack the slot's new owner —
        # heartbeats must present the token they registered with
        # guarded_by(_lock)
        self._leases = LeaseTable(self.lease_ttl_s, time_fn=time_fn,
                                  on_expire=self._requeue_dead_member)
        # task id -> owner slot (for prompt requeue on lease expiry)
        self._owners: Dict[int, Optional[int]] = {}   # guarded_by(_lock)

        if snapshot_path and os.path.exists(snapshot_path):
            self._recover(snapshot_path)

    # ---- dataset -----------------------------------------------------------

    def set_dataset(self, paths: Sequence[str]) -> int:
        """Partition recordio files into chunk tasks. Idempotent: only the
        first caller's dataset wins (service.go SetDataset does the same so
        N trainers can race to init)."""
        with self._lock:
            paths = list(paths)
            if self._dataset_set:
                if paths == self._dataset_paths:
                    return len(self._todo)
                # different dataset than the (possibly recovered) state:
                # re-partition from scratch rather than serving stale chunks
                self._todo, self._pending, self._done = [], {}, []
                self._next_id = 0
                self._pass_no = 0
            tasks: List[Task] = []
            for path in paths:
                offsets = recordio_index(path)
                i = 0
                while i < len(offsets):
                    n = min(self.chunks_per_task, len(offsets) - i)
                    tasks.append(Task(id=self._next_id, chunks=[
                        Chunk(path=path, offset=offsets[i], count=n)]))
                    self._next_id += 1
                    i += n
            self._todo = tasks
            self._dataset_set = True
            self._dataset_paths = paths
            self._snapshot()
            return len(tasks)

    # ---- membership (etcd Register/lease analog) ---------------------------

    def register(self, ttl_s: Optional[float] = None) -> Tuple[int, str]:
        """Claim the smallest free trainer slot under a lease
        (etcd_client.go:67-166's idx-slot transaction). Returns
        (slot, lease_token); heartbeats must present both. Re-registering
        after a crash gets a fresh slot+token; the dead slot's lease
        expires on its own and its tasks requeue."""
        with self._lock:
            # LeaseTable.register sweeps internally; the on_expire hook
            # requeues any freed member's tasks, so no extra sweep here
            return self._leases.register(ttl_s)

    def heartbeat(self, slot: int, token: str,
                  ttl_s: Optional[float] = None) -> bool:
        """Renew a lease. False = this trainer's lease is gone (expired, or
        the slot was reclaimed by a new owner — the token mismatch rejects
        the zombie even when the slot number is live again) — it was
        declared dead and must re-register and resume from checkpoint."""
        with self._lock:
            return self._leases.heartbeat(slot, token, ttl_s)

    def members(self) -> List[int]:
        with self._lock:
            return self._leases.members()

    # guarded_by(caller: _lock)
    def _expire_members(self) -> None:
        self._leases.expire()

    # guarded_by(caller: _lock)
    def _requeue_dead_member(self, slot: int) -> None:
        """on_expire hook: runs for every freed slot on EVERY lease
        sweep — including the ones LeaseTable does internally inside
        register/heartbeat/members, so a lease that lapses between our
        own sweep and the inner one still requeues promptly instead of
        waiting for the slow per-task timeout path.  Always called
        under self._lock (every LeaseTable call site holds it)."""
        # a dead trainer's tasks go back to the FRONT of todo: the
        # pass re-runs them promptly, preserving task order for the
        # surviving trainers (crash-resume determinism)
        held = [tid for tid, owner in self._owners.items()
                if owner == slot and tid in self._pending]
        for tid in sorted(held, reverse=True):
            task, _ = self._pending.pop(tid)
            task.num_failures += 1
            if task.num_failures >= self.max_failures:
                self._done.append(task)
                self._maybe_new_pass()
            else:
                self._todo.insert(0, task)
        if held:
            self._snapshot()

    # ---- task lifecycle ----------------------------------------------------

    def get_task(self, owner: Optional[int] = None) -> Optional[Task]:
        """Pop a todo task into pending (with deadline). Returns None when
        nothing is available right now — caller should retry or treat an
        all-done pass as end-of-data (see all_done). ``owner`` ties the
        lease to the task so a dead trainer's work requeues immediately."""
        with self._lock:
            self._check_timeouts()
            self._expire_members()
            if not self._todo:
                return None
            task = self._todo.pop(0)
            self._pending[task.id] = (task, self._time() + self.timeout_s)
            self._owners[task.id] = owner
            self._snapshot()
            return task

    def task_finished(self, task_id: int) -> bool:
        with self._lock:
            ent = self._pending.pop(task_id, None)
            self._owners.pop(task_id, None)
            if ent is None:
                return False
            task = ent[0]
            task.num_failures = 0
            self._done.append(task)
            self._maybe_new_pass()
            self._snapshot()
            return True

    def task_failed(self, task_id: int) -> bool:
        """Requeue a failed task, or discard it past the failure cap
        (service.go:448 discards and counts it done)."""
        with self._lock:
            ent = self._pending.pop(task_id, None)
            if ent is None:
                return False
            task = ent[0]
            task.num_failures += 1
            if task.num_failures >= self.max_failures:
                self._done.append(task)
                self._maybe_new_pass()
            else:
                self._todo.append(task)
            self._snapshot()
            return True

    def all_done(self) -> bool:
        """True when the current pass has been fully consumed."""
        with self._lock:
            self._check_timeouts()
            return self._dataset_set and not self._todo and not self._pending

    def new_pass(self) -> None:
        """Recycle done tasks into todo for the next epoch."""
        with self._lock:
            self._start_new_pass()
            self._snapshot()

    # ---- save-model dedup --------------------------------------------------

    def request_save_model(self, block_s: float) -> bool:
        """First trainer to ask within a window gets True (service.go:474)."""
        with self._lock:
            now = self._time()
            if now < self._save_until:
                return False
            self._save_until = now + block_s
            return True

    # ---- internals ---------------------------------------------------------

    # guarded_by(caller: _lock)
    def _check_timeouts(self) -> None:
        now = self._time()
        expired = [tid for tid, (_, dl) in self._pending.items() if dl <= now]
        for tid in expired:
            task, _ = self._pending.pop(tid)
            task.num_failures += 1
            if task.num_failures >= self.max_failures:
                self._done.append(task)
                self._maybe_new_pass()
            else:
                self._todo.append(task)
        if expired:
            self._snapshot()

    # guarded_by(caller: _lock)
    def _maybe_new_pass(self) -> None:
        if self._dataset_set and not self._todo and not self._pending:
            # pass complete; tasks stay in done until new_pass() recycles
            self._pass_no += 1

    # guarded_by(caller: _lock)
    def _start_new_pass(self) -> None:
        for t in self._done:
            t.epoch += 1
            t.num_failures = 0
        self._todo.extend(self._done)
        self._done = []

    # ---- snapshot / recover ------------------------------------------------

    # guarded_by(caller: _lock)
    def _state(self) -> dict:
        return {
            "todo": [asdict(t) for t in self._todo],
            "pending": [asdict(t) for t, _ in self._pending.values()],
            "done": [asdict(t) for t in self._done],
            "dataset_set": self._dataset_set,
            "dataset_paths": self._dataset_paths,
            "next_id": self._next_id,
            "pass_no": self._pass_no,
        }

    # guarded_by(caller: _lock)
    def _snapshot(self) -> None:
        """Persist the queue state atomically (etcd_client.go:96-129).

        tmp + fsync + rename: the tempfile gets a UNIQUE name (a fixed
        ``.tmp`` suffix would let two masters pointed at one path — or a
        snapshot racing a crash-restart's first write — clobber each
        other mid-write) and is fsynced before the rename, so a kill at
        ANY point leaves either the previous complete snapshot or the
        new complete one, never a truncated file.  A kill between write
        and rename only leaks a stray tempfile."""
        if not self.snapshot_path:
            return
        import tempfile

        d = os.path.dirname(os.path.abspath(self.snapshot_path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self._state(), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snapshot_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # guarded_by(caller: _lock)  (also run from __init__, pre-publication)
    def _recover(self, path: str) -> None:
        """Rebuild the queue from a snapshot; a corrupt/torn snapshot
        (pre-hardening truncation, disk damage) starts CLEAN instead of
        crashing — the dataset re-partitions on the next set_dataset,
        exactly like a first boot, and a grep-able line records that
        recovery discarded state."""
        try:
            with open(path) as f:
                st = json.load(f)

            def mk(d):
                return Task(id=d["id"], epoch=d["epoch"],
                            num_failures=d["num_failures"],
                            chunks=[Chunk(**c) for c in d["chunks"]])

            # pending tasks at crash time go back to todo (the Go master
            # does the same on snapshot recovery: leases died with the
            # process)
            todo = [mk(d) for d in st["todo"]] \
                + [mk(d) for d in st["pending"]]
            done = [mk(d) for d in st["done"]]
            dataset_set = bool(st["dataset_set"])
            dataset_paths = st.get("dataset_paths", [])
            next_id = int(st["next_id"])
            pass_no = int(st["pass_no"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"MASTER-SNAPSHOT-CORRUPT: {path} ({type(e).__name__}: "
                  f"{e}) — rebuilding the task queue from a clean state",
                  flush=True)
            return
        self._todo = todo
        self._done = done
        self._dataset_set = dataset_set
        self._dataset_paths = dataset_paths
        self._next_id = next_id
        self._pass_no = pass_no

    # ---- progress (the step-cursor's task-queue position) ------------------

    def progress(self) -> dict:
        """Queue position snapshot: how far the current pass has
        advanced.  The trainer's step-granular checkpoint cursor records
        this next to (pass, step, rng) so a resume report can show WHERE
        in the dataset the run died, and the resilience CLI surfaces it."""
        with self._lock:
            self._check_timeouts()
            return {"pass_no": self._pass_no,
                    "todo": len(self._todo),
                    "pending": len(self._pending),
                    "done": len(self._done)}


def dispatch(svc: "Service", method, params):
    """The RPC method table (go/master net/rpc surface analog) — shared by
    the TCP server handler and the client's in-process transport so the
    wire protocol has exactly one definition."""
    params = params or {}
    if method == "set_dataset":
        return svc.set_dataset(params["paths"])
    if method == "get_task":
        owner = params.get("owner")
        task = svc.get_task(None if owner is None else int(owner))
        if task is None:
            return None
        return {"id": task.id, "epoch": task.epoch,
                "chunks": [{"path": c.path, "offset": c.offset,
                            "count": c.count} for c in task.chunks]}
    if method == "task_finished":
        return svc.task_finished(int(params["task_id"]))
    if method == "task_failed":
        return svc.task_failed(int(params["task_id"]))
    if method == "all_done":
        return svc.all_done()
    if method == "new_pass":
        svc.new_pass()
        return True
    if method == "request_save_model":
        return svc.request_save_model(float(params.get("block_s", 60.0)))
    if method == "register":
        slot, token = svc.register(params.get("ttl_s"))
        return {"slot": slot, "token": token}
    if method == "heartbeat":
        return svc.heartbeat(int(params["slot"]), str(params["token"]),
                             params.get("ttl_s"))
    if method == "members":
        return svc.members()
    if method == "progress":
        return svc.progress()
    if method == "ping":
        return "pong"
    raise ValueError(f"unknown method {method!r}")
