"""Trainer-side master client (reference:
python/paddle/v2/master/client.py:15-80 over go/master/c/client.go).

``MasterClient(None)`` runs against an in-process Service (the
inmem_store analog used throughout the reference's tests); passing an
``"host:port"`` string talks to a MasterServer (Python or C++) over TCP.

``next_record()`` drives the task lifecycle: fetch a task, stream its
chunks from local recordio files, report task_finished, and return None
at end of pass.

Transient failures (dropped connections, a master mid-restart, an empty
todo queue while peers hold leases) are retried with CAPPED EXPONENTIAL
BACKOFF + DECORRELATED JITTER — ``sleep = min(cap, uniform(base,
3 * prev))`` — instead of a fixed-interval poll, so a restarting master
isn't hammered by a synchronized trainer fleet.  ``retry_budget`` bounds
consecutive failed attempts; exhausting it raises
:class:`MasterRetryExhausted` with the last underlying error, so a
wedged master surfaces as a clear trainer error instead of a silent
infinite loop.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Callable, List, Optional

from .recordio import recordio_read_chunk
from .service import Service, dispatch
from .server import send_msg, recv_msg


class MasterRetryExhausted(ConnectionError):
    """The client's retry budget ran out without a successful call."""


class _Backoff:
    """Capped exponential backoff with decorrelated jitter (the AWS
    architecture-blog flavor: each sleep draws uniform(base, 3 * prev),
    clamped to cap — successive clients decorrelate instead of
    thundering back in lockstep).  ``budget`` caps consecutive sleeps;
    ``reset()`` (on success) restores the full budget and the base
    interval.  ``sleep_fn`` is injectable so tests drive retries without
    wall-clock sleeping."""

    def __init__(self, base_s: float, cap_s: float,
                 budget: Optional[int] = None,
                 seed: Optional[int] = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        self.base_s = max(1e-4, float(base_s))
        self.cap_s = max(self.base_s, float(cap_s))
        self.budget = budget
        # seed=None -> OS entropy: every client in a fleet draws a
        # DIFFERENT jitter sequence (a shared fixed seed would put the
        # whole fleet back in lockstep, recreating the thundering herd
        # the jitter exists to break). Pass a seed for replayable tests.
        self._rng = random.Random(seed)
        self._sleep_fn = sleep_fn
        self.reset()

    def reset(self) -> None:
        self.attempts = 0
        self._prev = self.base_s

    def sleep(self, why: str = "") -> None:
        self.attempts += 1
        if self.budget is not None and self.attempts > self.budget:
            raise MasterRetryExhausted(
                f"master retry budget ({self.budget}) exhausted"
                f"{': ' + why if why else ''}")
        self._prev = min(self.cap_s,
                         self._rng.uniform(self.base_s, 3.0 * self._prev))
        self._sleep_fn(self._prev)


class _InprocTransport:
    def __init__(self, service: Optional[Service] = None):
        self.service = service or Service()

    def call(self, method: str, **params):
        return dispatch(self.service, method, params)


class _TcpTransport:
    """TCP transport with reconnect-on-failure.  A dropped connection
    (master restart, flaky network) triggers backoff + reconnect and a
    re-send of the in-flight call.  At-least-once caveat: a call that
    reached the master before the drop may execute twice — idempotent
    methods tolerate this (set_dataset dedups, task_finished/failed on a
    non-pending id is a no-op False).  ``get_task`` is NOT idempotent (a
    blind re-send would lease a SECOND task while the lost response's
    lease silently burns that task's failure budget on expiry), so a
    lost get_task response is reported as None — "nothing available" —
    and the caller's poll loop retries; the orphaned lease requeues via
    the server's normal timeout path.  ``register`` is re-sent: a lost
    response may strand one unowned slot, but the caller needs the
    slot/token to proceed and the stray slot self-heals when its TTL
    lease expires — the least-bad option without server-side request
    dedup."""

    _LEASING_METHODS = frozenset({"get_task"})

    def __init__(self, addr: str, timeout_s: float = 30.0,
                 backoff: Optional[_Backoff] = None):
        host, port = addr.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout_s = timeout_s
        self._backoff = backoff or _Backoff(0.05, 2.0)
        self._sock: Optional[socket.socket] = None
        self._send_attempted = False
        self._connect()

    def _connect(self) -> None:
        """(Re)establish the connection, backing off between attempts;
        raises MasterRetryExhausted when the budget runs out."""
        self.close()
        while True:
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout_s)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                return
            except OSError as e:
                self._sock = None
                self._backoff.sleep(f"connect to {self._addr}: {e}")

    def call(self, method: str, **params):
        while True:
            self._send_attempted = False
            try:
                if self._sock is None:
                    self._connect()
                return self.call_once(method, **params)
            except (ConnectionError, OSError) as e:
                self._backoff.sleep(f"call {method}: {e}")
                self._connect()
                # only once bytes may actually have left (the send was
                # attempted) is a leasing call ambiguous; a connect-time
                # failure provably never reached the master, so re-send
                if self._send_attempted and \
                        method in self._LEASING_METHODS:
                    return None

    def call_once(self, method: str, **params):
        """One attempt, no backoff and no reconnect — the shutdown path
        (a dead master must not stall ``close()`` through a retry
        budget)."""
        if self._sock is None:
            raise ConnectionError("not connected")
        self._send_attempted = True
        send_msg(self._sock, {"method": method, "params": params})
        resp = recv_msg(self._sock)
        if resp is None:
            raise ConnectionError("master connection closed")
        self._backoff.reset()
        if not resp.get("ok"):
            raise RuntimeError(f"master error: {resp.get('error')}")
        return resp.get("result")

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


DEFAULT_TRANSPORT_RETRY_BUDGET = 30


class MasterClient:
    """``retry_budget`` semantics: when left at None, TRANSPORT failures
    (connect / dropped call) still get a finite default budget
    (:data:`DEFAULT_TRANSPORT_RETRY_BUDGET` — a permanently-dead master
    must surface as :class:`MasterRetryExhausted`, not a silent forever
    loop), while the task POLL loop stays unbounded (waiting out peers
    that hold long-running tasks is legitimate, and the old fixed-poll
    behavior waited forever too).  An explicit ``retry_budget`` bounds
    both."""

    def __init__(self, addr: Optional[str] = None,
                 service: Optional[Service] = None,
                 poll_interval_s: float = 0.05,
                 retry_cap_s: float = 2.0,
                 retry_budget: Optional[int] = None,
                 sleep_fn: Callable[[float], None] = time.sleep):
        # two independent backoff states: transport-level reconnects and
        # the task-poll loop each get the full budget, both using
        # poll_interval_s as the base interval (OS-entropy jitter, so a
        # trainer fleet decorrelates)
        self._poll_backoff = _Backoff(poll_interval_s, retry_cap_s,
                                      budget=retry_budget,
                                      sleep_fn=sleep_fn)
        if addr:
            transport_budget = retry_budget if retry_budget is not None \
                else DEFAULT_TRANSPORT_RETRY_BUDGET
            self._t = _TcpTransport(addr, backoff=_Backoff(
                poll_interval_s, retry_cap_s, budget=transport_budget,
                sleep_fn=sleep_fn))
        else:
            self._t = _InprocTransport(service)
        self._records: List[bytes] = []
        self._task_id: Optional[int] = None
        self._slot: Optional[int] = None
        self._token: Optional[str] = None

    # -- polling -------------------------------------------------------------

    def poll_wait(self) -> None:
        """Back off before re-asking for work (the master had nothing —
        peers hold the pending tasks).  Jittered and counted against the
        poll retry budget, exactly like ``next_record``'s internal loop;
        callers driving ``try_next_task`` themselves (the elastic
        trainer) use this instead of a fixed sleep."""
        self._poll_backoff.sleep("waiting for an available task")

    def poll_reset(self) -> None:
        """Work arrived: restore the poll backoff to its base interval
        and refund the budget."""
        self._poll_backoff.reset()

    # -- dataset / records ---------------------------------------------------

    def set_dataset(self, paths) -> int:
        if isinstance(paths, str):
            paths = paths.split(",")
        return self._t.call("set_dataset", paths=list(paths))

    def next_record(self) -> Optional[bytes]:
        """Next record of the current pass, or None when the pass is done."""
        while not self._records:
            if not self._fetch_task():
                return None
        return self._records.pop(0)

    def try_next_task(self):
        """ONE non-blocking task-fetch attempt with NO implicit ack —
        the elastic trainer acks explicitly (ack_task) only after the
        covering checkpoint is durable, so a crash never acks unapplied
        work. Returns:

        - ("task", (task_id, epoch, records)) — a task to process;
        - ("empty", None) — nothing available NOW (other trainers hold
          pending tasks, or the caller itself holds unacked ones);
        - ("done", None)  — the pass is fully consumed.
        """
        task = self._t.call("get_task", owner=self._slot)
        if task is None:
            return (("done" if self._t.call("all_done") else "empty"), None)
        recs: List[bytes] = []
        try:
            for c in task["chunks"]:
                got = recordio_read_chunk(c["path"], c["offset"], c["count"])
                recs.extend(g if isinstance(g, bytes) else bytes(g)
                            for g in got)
        except OSError:
            self._t.call("task_failed", task_id=task["id"])
            return ("empty", None)
        return ("task", (task["id"], task.get("epoch", 0), recs))

    def ack_task(self, task_id: int) -> None:
        """Report a task finished (explicit-ack path of try_next_task)."""
        self._t.call("task_finished", task_id=task_id)
        if self._task_id == task_id:
            self._task_id = None

    def task_failed(self) -> None:
        """Report the in-flight task failed (fault-injection / error paths)."""
        if self._task_id is not None:
            self._t.call("task_failed", task_id=self._task_id)
            self._task_id = None
            self._records = []

    # -- membership (etcd Register/lease analog) -----------------------------

    def register(self, ttl_s: Optional[float] = None) -> int:
        """Join the job: claim a trainer slot under a lease. Tasks fetched
        afterwards are owned by this slot and requeue promptly if the
        lease lapses (go/pserver/etcd_client.go:67-166)."""
        got = self._t.call("register", ttl_s=ttl_s)
        self._slot, self._token = got["slot"], got["token"]
        return self._slot

    def heartbeat(self, ttl_s: Optional[float] = None) -> bool:
        """Renew the lease. False means this trainer was declared dead
        (lease lapsed — even if the slot number was since reclaimed by a
        new trainer, the token mismatch rejects the zombie) — it must
        re-register and resume from its last checkpoint."""
        if self._slot is None:
            return False
        ok = self._t.call("heartbeat", slot=self._slot, token=self._token,
                          ttl_s=ttl_s)
        if not ok:
            self._slot = None
            self._token = None
            self._task_id = None
            self._records = []
        return ok

    def members(self) -> List[int]:
        return self._t.call("members")

    def progress(self) -> dict:
        """Queue position of the current pass ({pass_no, todo, pending,
        done}) — the task-queue component of the step-granular
        checkpoint cursor, and what the resilience CLI reports while a
        supervised run recovers."""
        return self._t.call("progress")

    # -- pass control --------------------------------------------------------

    def begin_pass(self) -> None:
        """Recycle the task queue if the previous pass fully completed.
        Safe under multiple trainers: new_pass only fires when todo and
        pending are both empty, so exactly one epoch boundary happens."""
        if self._t.call("all_done"):
            self._t.call("new_pass")

    def new_pass(self) -> None:
        self._t.call("new_pass")

    def request_save_model(self, block_s: float = 60.0) -> bool:
        return self._t.call("request_save_model", block_s=block_s)

    def close(self) -> None:
        # release an in-flight task immediately rather than letting its
        # lease time out and re-serve already-consumed records.  ONE
        # attempt, no retry loop: shutdown against a dead master must
        # fail fast, not sit out the whole transport backoff budget
        try:
            if self._task_id is not None:
                once = getattr(self._t, "call_once", self._t.call)
                once("task_failed", task_id=self._task_id)
        except (ConnectionError, RuntimeError, OSError):
            pass
        self._task_id = None
        self._records = []
        if hasattr(self._t, "close"):
            self._t.close()

    # -- internals -----------------------------------------------------------

    def _fetch_task(self) -> bool:
        """Load the next task's records. False at end of pass."""
        if self._task_id is not None:
            self._t.call("task_finished", task_id=self._task_id)
            self._task_id = None
        while True:
            task = self._t.call("get_task", owner=self._slot)
            if task is not None:
                self.poll_reset()
                break
            if self._t.call("all_done"):
                self.poll_reset()
                return False
            # other workers hold pending tasks: poll with backoff+jitter
            self.poll_wait()
        recs: List[bytes] = []
        try:
            for c in task["chunks"]:
                got = recordio_read_chunk(c["path"], c["offset"], c["count"])
                recs.extend(g if isinstance(g, bytes) else bytes(g)
                            for g in got)
        except OSError:
            self._t.call("task_failed", task_id=task["id"])
            return True  # try another task
        self._task_id = task["id"]
        self._records = recs
        return True
