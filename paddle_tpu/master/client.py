"""Trainer-side master client (reference:
python/paddle/v2/master/client.py:15-80 over go/master/c/client.go).

``MasterClient(None)`` runs against an in-process Service (the
inmem_store analog used throughout the reference's tests); passing an
``"host:port"`` string talks to a MasterServer (Python or C++) over TCP.

``next_record()`` drives the task lifecycle: fetch a task, stream its
chunks from local recordio files, report task_finished, and return None
at end of pass.
"""

from __future__ import annotations

import socket
import time
from typing import List, Optional

from .recordio import recordio_read_chunk
from .service import Service, dispatch
from .server import send_msg, recv_msg


class _InprocTransport:
    def __init__(self, service: Optional[Service] = None):
        self.service = service or Service()

    def call(self, method: str, **params):
        return dispatch(self.service, method, params)


class _TcpTransport:
    def __init__(self, addr: str, timeout_s: float = 30.0):
        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def call(self, method: str, **params):
        send_msg(self._sock, {"method": method, "params": params})
        resp = recv_msg(self._sock)
        if resp is None:
            raise ConnectionError("master connection closed")
        if not resp.get("ok"):
            raise RuntimeError(f"master error: {resp.get('error')}")
        return resp.get("result")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class MasterClient:
    def __init__(self, addr: Optional[str] = None,
                 service: Optional[Service] = None,
                 poll_interval_s: float = 0.05):
        if addr:
            self._t = _TcpTransport(addr)
        else:
            self._t = _InprocTransport(service)
        self._poll = poll_interval_s
        self._records: List[bytes] = []
        self._task_id: Optional[int] = None
        self._slot: Optional[int] = None
        self._token: Optional[str] = None

    # -- dataset / records ---------------------------------------------------

    def set_dataset(self, paths) -> int:
        if isinstance(paths, str):
            paths = paths.split(",")
        return self._t.call("set_dataset", paths=list(paths))

    def next_record(self) -> Optional[bytes]:
        """Next record of the current pass, or None when the pass is done."""
        while not self._records:
            if not self._fetch_task():
                return None
        return self._records.pop(0)

    def try_next_task(self):
        """ONE non-blocking task-fetch attempt with NO implicit ack —
        the elastic trainer acks explicitly (ack_task) only after the
        covering checkpoint is durable, so a crash never acks unapplied
        work. Returns:

        - ("task", (task_id, epoch, records)) — a task to process;
        - ("empty", None) — nothing available NOW (other trainers hold
          pending tasks, or the caller itself holds unacked ones);
        - ("done", None)  — the pass is fully consumed.
        """
        task = self._t.call("get_task", owner=self._slot)
        if task is None:
            return (("done" if self._t.call("all_done") else "empty"), None)
        recs: List[bytes] = []
        try:
            for c in task["chunks"]:
                got = recordio_read_chunk(c["path"], c["offset"], c["count"])
                recs.extend(g if isinstance(g, bytes) else bytes(g)
                            for g in got)
        except OSError:
            self._t.call("task_failed", task_id=task["id"])
            return ("empty", None)
        return ("task", (task["id"], task.get("epoch", 0), recs))

    def ack_task(self, task_id: int) -> None:
        """Report a task finished (explicit-ack path of try_next_task)."""
        self._t.call("task_finished", task_id=task_id)
        if self._task_id == task_id:
            self._task_id = None

    def task_failed(self) -> None:
        """Report the in-flight task failed (fault-injection / error paths)."""
        if self._task_id is not None:
            self._t.call("task_failed", task_id=self._task_id)
            self._task_id = None
            self._records = []

    # -- membership (etcd Register/lease analog) -----------------------------

    def register(self, ttl_s: Optional[float] = None) -> int:
        """Join the job: claim a trainer slot under a lease. Tasks fetched
        afterwards are owned by this slot and requeue promptly if the
        lease lapses (go/pserver/etcd_client.go:67-166)."""
        got = self._t.call("register", ttl_s=ttl_s)
        self._slot, self._token = got["slot"], got["token"]
        return self._slot

    def heartbeat(self, ttl_s: Optional[float] = None) -> bool:
        """Renew the lease. False means this trainer was declared dead
        (lease lapsed — even if the slot number was since reclaimed by a
        new trainer, the token mismatch rejects the zombie) — it must
        re-register and resume from its last checkpoint."""
        if self._slot is None:
            return False
        ok = self._t.call("heartbeat", slot=self._slot, token=self._token,
                          ttl_s=ttl_s)
        if not ok:
            self._slot = None
            self._token = None
            self._task_id = None
            self._records = []
        return ok

    def members(self) -> List[int]:
        return self._t.call("members")

    # -- pass control --------------------------------------------------------

    def begin_pass(self) -> None:
        """Recycle the task queue if the previous pass fully completed.
        Safe under multiple trainers: new_pass only fires when todo and
        pending are both empty, so exactly one epoch boundary happens."""
        if self._t.call("all_done"):
            self._t.call("new_pass")

    def new_pass(self) -> None:
        self._t.call("new_pass")

    def request_save_model(self, block_s: float = 60.0) -> bool:
        return self._t.call("request_save_model", block_s=block_s)

    def close(self) -> None:
        # release an in-flight task immediately rather than letting its
        # lease time out and re-serve already-consumed records
        try:
            self.task_failed()
        except (ConnectionError, RuntimeError, OSError):
            pass
        if hasattr(self._t, "close"):
            self._t.close()

    # -- internals -----------------------------------------------------------

    def _fetch_task(self) -> bool:
        """Load the next task's records. False at end of pass."""
        if self._task_id is not None:
            self._t.call("task_finished", task_id=self._task_id)
            self._task_id = None
        while True:
            task = self._t.call("get_task", owner=self._slot)
            if task is not None:
                break
            if self._t.call("all_done"):
                return False
            time.sleep(self._poll)  # other workers hold pending tasks
        recs: List[bytes] = []
        try:
            for c in task["chunks"]:
                got = recordio_read_chunk(c["path"], c["offset"], c["count"])
                recs.extend(g if isinstance(g, bytes) else bytes(g)
                            for g in got)
        except OSError:
            self._t.call("task_failed", task_id=task["id"])
            return True  # try another task
        self._task_id = task["id"]
        self._records = recs
        return True
