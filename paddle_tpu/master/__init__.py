"""Elastic input service — the TPU-native analog of the reference's Go
master (reference: go/master/service.go, go/master/c/client.go,
python/paddle/v2/master/client.py:15-80).

The reference dispatches dataset *chunks* as tasks through three queues
(todo/pending/done) with timeout requeue, per-task failure caps, and an
etcd-persisted state snapshot.  Here the same task lifecycle lives in
:class:`Service` (pure Python, file-snapshot instead of etcd), served
either in-process (the ``inmem_store.go`` analog) or over TCP by
:class:`MasterServer` (a thin length-prefixed-JSON protocol that the C++
server in ``native/master`` also speaks).

Records themselves travel out-of-band: the master hands out chunk
*metadata* (path, offset, count) and the trainer-side
:class:`MasterClient` reads the recordio file locally — exactly the
reference's design (go/master/service.go:106 partitions chunks; the
trainer reads via the recordio library).
"""

from .recordio import recordio_write, recordio_read_chunk, recordio_index
from .service import Task, Service, LeaseTable, MAX_TASK_FAILURES
from .server import MasterServer
from .client import MasterClient, MasterRetryExhausted

__all__ = [
    "recordio_write",
    "recordio_read_chunk",
    "recordio_index",
    "Task",
    "Service",
    "LeaseTable",
    "MasterServer",
    "MasterClient",
    "MasterRetryExhausted",
    "MAX_TASK_FAILURES",
]
