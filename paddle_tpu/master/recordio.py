"""Minimal recordio: length-prefixed records in a flat file.

Format: per record, an 8-byte little-endian u64 payload length followed by
the payload bytes.  The reference uses the recordio chunk library
(go/master/service.go:106 partitions by chunks); ours indexes byte offsets
so the master can hand out (path, offset, count) chunk specs and clients
can seek directly.
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Tuple

_HDR = struct.Struct("<Q")


def recordio_write(path: str, records: Iterable[bytes]) -> int:
    """Write records; returns the number written."""
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            if isinstance(rec, str):
                rec = rec.encode("utf-8")
            f.write(_HDR.pack(len(rec)))
            f.write(rec)
            n += 1
    return n


def recordio_index(path: str) -> List[int]:
    """Byte offset of every record in the file."""
    offsets = []
    with open(path, "rb") as f:
        pos = 0
        while True:
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            offsets.append(pos)
            (n,) = _HDR.unpack(hdr)
            f.seek(n, 1)
            pos += _HDR.size + n
    return offsets


def recordio_read_chunk(path: str, offset: int, count: int) -> List[bytes]:
    """Read `count` consecutive records starting at byte `offset`."""
    out: List[bytes] = []
    with open(path, "rb") as f:
        f.seek(offset)
        for _ in range(count):
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break
            (n,) = _HDR.unpack(hdr)
            out.append(f.read(n))
    return out
