"""TCP front-end for the master Service.

Wire protocol (shared with the C++ server in native/master): each message
is a 4-byte little-endian u32 length followed by a UTF-8 JSON object.
Request:  {"method": str, "params": {...}}
Response: {"ok": bool, "result": ...} or {"ok": false, "error": str}

This is the ProtoServer/LightNetwork analog (reference:
paddle/pserver/ProtoServer.h:36-111, LightNetwork.h:40-175) with JSON in
place of protobuf — the payloads here are tiny control messages, not
tensors; tensor traffic in this framework rides XLA collectives instead.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Optional

from .service import Service, dispatch

_LEN = struct.Struct("<I")


def send_msg(sock: socket.socket, obj) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body.decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        svc: Service = self.server.service  # type: ignore[attr-defined]
        while True:
            try:
                req = recv_msg(self.request)
            except (ConnectionError, OSError):
                return
            if req is None:
                return
            try:
                result = self._dispatch(svc, req)
                resp = {"ok": True, "result": result}
            except Exception as e:  # surfaced to the client, not fatal
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            try:
                send_msg(self.request, resp)
            except (ConnectionError, OSError):
                return

    @staticmethod
    def _dispatch(svc: Service, req):
        return dispatch(svc, req.get("method"), req.get("params"))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MasterServer:
    """Threaded TCP server wrapping a Service; start()/stop() lifecycle."""

    def __init__(self, service: Optional[Service] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.service = service or Service()
        # the ThreadingTCPServer does its own internal locking; the
        # REFERENCE to it (and to the acceptor thread below) is only
        # rebound by the owner thread that calls start()/stop() —
        # handler threads reach the server through their own argument,
        # never through these fields
        # guarded_by(serialized: owner thread drives start()/stop())
        self._srv = _Server((host, port), _Handler)
        self._srv.service = self.service  # type: ignore[attr-defined]
        # guarded_by(serialized: owner thread drives start()/stop())
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> "MasterServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)
