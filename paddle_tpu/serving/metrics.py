"""Serving metrics: the counters the bench (and any scraper) reads.

Kept deliberately flat — ``snapshot()`` returns one JSON-able dict so
``bench.py``'s one-line-of-JSON contract and an external exporter see
the same numbers.  Time handling: the engine stamps events with its
clock (``time.monotonic`` or an injected fault-plan clock) and the
throughput window runs from the first submission to the last emitted
token, so idle tails (drained engine waiting for arrivals) don't
deflate tokens/s.

SLO counters (round 8): every terminal status is counted —
``completed`` / ``timed_out`` / ``cancelled`` / ``failed`` /
``rejected`` — plus ``shed`` (queued requests early-rejected because
their deadline became unmeetable), ``retries`` (decode ticks re-run
after a transient device error), queue-wait p95, and
``deadline_miss_rate`` = (timed_out + shed) / (completed + timed_out +
shed): of the demand that wanted completion, the fraction that missed.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Sequence

# latency percentiles run over a bounded recent window, not full
# history: a long-lived engine must not grow metric memory per request
# (mirrors the engine's max_retained eviction) nor pay an ever-larger
# sort per snapshot
_WINDOW = 4096


def _p95(xs: Sequence[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(0.95 * len(s)))]


class ServingMetrics:
    def __init__(self, pool_pages: int):
        self.pool_pages = max(1, pool_pages)
        self.submitted = 0
        self.rejected = 0
        self.completed = 0
        self.timed_out = 0
        self.cancelled = 0
        self.failed = 0
        self.shed = 0                 # early-rejected: deadline unmeetable
        self.retries = 0              # decode tick retries (transient errors)
        self.preemptions = 0
        self.ticks = 0
        self.tokens_generated = 0
        self.prefill_tokens = 0       # tokens actually forwarded at prefill
        # prefix caching (round 9)
        self.prefix_requested_tokens = 0  # cache_tokens summed at admission
        self.prefill_tokens_saved = 0     # of those, served from the cache
        self.cow_forks = 0            # copy-on-write page forks
        self.cache_evictions = 0      # gauge: cache's cumulative evictions
        self.queue_depth = 0          # gauge: last tick
        self.pages_in_use = 0         # gauge: last tick, LIVE holders only
        self.pages_cached = 0         # gauge: last tick, prefix-cache pages
        self.peak_pages_in_use = 0
        self.ttft_s = deque(maxlen=_WINDOW)
        self.queue_wait_s = deque(maxlen=_WINDOW)
        self._first_event_at: Optional[float] = None
        self._last_token_at: Optional[float] = None

    # ---- event hooks (called by the engine) ------------------------------

    def on_submit(self, now: float, accepted: bool) -> None:
        self.submitted += 1
        if not accepted:
            self.rejected += 1
        if self._first_event_at is None:
            self._first_event_at = now

    def on_prefill(self, n_tokens: int) -> None:
        self.prefill_tokens += n_tokens

    def on_prefix(self, requested: int, saved: int) -> None:
        """One admission's prefix-cache outcome: ``requested`` tokens
        wanted materializing, ``saved`` of them came stitched from the
        cache (0 on a miss or with caching off).  Re-admissions after
        preemption count again — saved recompute is still saved work."""
        self.prefix_requested_tokens += requested
        self.prefill_tokens_saved += saved

    def on_cow(self) -> None:
        self.cow_forks += 1

    def on_admit(self, queue_wait_s: float) -> None:
        self.queue_wait_s.append(max(0.0, queue_wait_s))

    def on_token(self, now: float, ttft_s: Optional[float] = None) -> None:
        self.tokens_generated += 1
        self._last_token_at = now
        if ttft_s is not None:
            self.ttft_s.append(ttft_s)

    def on_complete(self) -> None:
        self.completed += 1

    def on_timeout(self) -> None:
        self.timed_out += 1

    def on_cancel(self) -> None:
        self.cancelled += 1

    def on_fail(self) -> None:
        self.failed += 1

    def on_shed(self) -> None:
        self.shed += 1

    def on_retry(self) -> None:
        self.retries += 1

    def on_preempt(self, n: int) -> None:
        self.preemptions += n

    def on_tick(self, queue_depth: int, pages_in_use: int,
                pages_cached: int = 0, cache_evictions: int = 0) -> None:
        self.ticks += 1
        self.queue_depth = queue_depth
        self.pages_in_use = pages_in_use
        self.pages_cached = pages_cached
        self.cache_evictions = cache_evictions
        self.peak_pages_in_use = max(self.peak_pages_in_use, pages_in_use)

    # ---- scrape ----------------------------------------------------------

    def tokens_per_s(self) -> float:
        if (self._first_event_at is None or self._last_token_at is None or
                self._last_token_at <= self._first_event_at):
            return 0.0
        return self.tokens_generated / (self._last_token_at -
                                        self._first_event_at)

    def ttft_ms_mean(self) -> float:
        if not self.ttft_s:
            return 0.0
        return 1000.0 * sum(self.ttft_s) / len(self.ttft_s)

    def ttft_ms_p95(self) -> float:
        return 1000.0 * _p95(self.ttft_s)

    def queue_wait_ms_p95(self) -> float:
        return 1000.0 * _p95(self.queue_wait_s)

    def deadline_miss_rate(self) -> float:
        demand = self.completed + self.timed_out + self.shed
        if demand == 0:
            return 0.0
        return (self.timed_out + self.shed) / demand

    def prefix_hit_rate(self) -> float:
        """Token-level hit rate: of all the prefill tokens admissions
        asked for, the fraction served from the prefix cache."""
        if self.prefix_requested_tokens == 0:
            return 0.0
        return self.prefill_tokens_saved / self.prefix_requested_tokens

    def snapshot(self) -> Dict[str, float]:
        return {
            "tokens_per_s": round(self.tokens_per_s(), 2),
            "ttft_ms_mean": round(self.ttft_ms_mean(), 3),
            "ttft_ms_p95": round(self.ttft_ms_p95(), 3),
            "queue_wait_ms_p95": round(self.queue_wait_ms_p95(), 3),
            "tokens_generated": self.tokens_generated,
            "prefill_tokens": self.prefill_tokens,
            "prefix_hit_rate": round(self.prefix_hit_rate(), 4),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "cow_forks": self.cow_forks,
            "cache_evictions": self.cache_evictions,
            "pages_cached": self.pages_cached,
            "requests_submitted": self.submitted,
            "requests_rejected": self.rejected,
            "requests_completed": self.completed,
            "requests_timed_out": self.timed_out,
            "requests_cancelled": self.cancelled,
            "requests_failed": self.failed,
            "requests_shed": self.shed,
            "deadline_miss_rate": round(self.deadline_miss_rate(), 4),
            "retries": self.retries,
            "preemptions": self.preemptions,
            "ticks": self.ticks,
            "queue_depth": self.queue_depth,
            "page_occupancy": round(self.pages_in_use / self.pool_pages, 4),
            "page_occupancy_peak": round(
                self.peak_pages_in_use / self.pool_pages, 4),
        }
