"""Serving metrics: the counters the bench (and any scraper) reads.

Kept deliberately flat — ``snapshot()`` returns one JSON-able dict so
``bench.py``'s one-line-of-JSON contract and an external exporter see
the same numbers.  Time handling: the engine stamps events with its
clock (``time.monotonic`` or an injected fault-plan clock) and the
throughput window runs from the first submission to the last emitted
token, so idle tails (drained engine waiting for arrivals) don't
deflate tokens/s.

SLO counters (round 8): every terminal status is counted —
``completed`` / ``timed_out`` / ``cancelled`` / ``failed`` /
``rejected`` — plus ``shed`` (queued requests early-rejected because
their deadline became unmeetable), ``retries`` (decode ticks re-run
after a transient device error), queue-wait p95, and
``deadline_miss_rate`` = (timed_out + shed) / (completed + timed_out +
shed): of the demand that wanted completion, the fraction that missed.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Sequence

# latency percentiles run over a bounded recent window, not full
# history: a long-lived engine must not grow metric memory per request
# (mirrors the engine's max_retained eviction) nor pay an ever-larger
# sort per snapshot
_WINDOW = 4096


def _p95(xs: Sequence[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(0.95 * len(s)))]


class ServingMetrics:
    def __init__(self, pool_pages: int):
        self.pool_pages = max(1, pool_pages)
        self.submitted = 0
        self.rejected = 0
        self.completed = 0
        self.timed_out = 0
        self.cancelled = 0
        self.failed = 0
        self.shed = 0                 # early-rejected: deadline unmeetable
        self.retries = 0              # decode tick retries (transient errors)
        self.preemptions = 0
        self.ticks = 0
        self.tokens_generated = 0
        self.prefill_tokens = 0       # tokens actually forwarded at prefill
        # unified-step shape (round 12): dispatches and row mix — the
        # whole point of the ragged kernel is fewer dispatches per unit
        # of work, so the bench reads these directly
        self.step_dispatches = 0      # unified-step device dispatches
        self.decode_rows = 0          # decode/verify rows shipped across
        #                               steps (k1 per speculating slot)
        self.decode_slots = 0         # slot participations (one per
        #                               running slot per step)
        self.prefill_rows = 0         # prefill-chunk rows shipped (padded)
        self.prefill_pad_rows = 0     # of the bucket, padding/alignment
        # speculative decoding (round 18)
        self.spec_ticks = 0           # verify ticks with >= 1 drafted token
        self.spec_tokens_proposed = 0  # drafted tokens shipped to verify
        self.spec_tokens_accepted = 0  # of those, accepted
        self.spec_rollbacks = 0       # verify walks that rejected >= 1 draft
        self.spec_suspended = 0       # slot-ticks speculation was suspended
        #                               (page pressure / no lookahead room)
        self.spec_cow_forks = 0       # verify-time COW forks (shared tail)
        self.draft_steps = 0          # draft-model dispatches (gauge)
        self.draft_time_s = 0.0       # wall time inside them (gauge)
        # prefix caching (round 9)
        self.prefix_requested_tokens = 0  # cache_tokens summed at admission
        self.prefill_tokens_saved = 0     # of those, served from the cache
        self.cow_forks = 0            # copy-on-write page forks
        self.cache_evictions = 0      # gauge: cache's cumulative evictions
        # hierarchical host tier (round 21): gauges stamped from
        # HostPageTier.snapshot() each tick / healthz — zeros with the
        # tier off, so the scrape schema is stable either way
        self.pages_host = 0           # gauge: host-resident spilled pages
        self.host_swap_ins = 0        # verified pages promoted to device
        self.host_swap_outs = 0       # pages ever spilled (staged)
        self.host_hits = 0            # swap-in events serving a request
        self.host_corrupt = 0         # checksum failures (never served)
        self.host_dropped = 0         # host-LRU drops / forgets
        self.spill_stall_ticks = 0    # pump ticks lost to slow host I/O
        self.queue_depth = 0          # gauge: last tick
        self.pages_in_use = 0         # gauge: last tick, LIVE holders only
        self.pages_cached = 0         # gauge: last tick, prefix-cache pages
        self.peak_pages_in_use = 0
        self.ttft_s = deque(maxlen=_WINDOW)
        self.queue_wait_s = deque(maxlen=_WINDOW)
        # multi-tenant series (round 17): deadline misses (timed_out +
        # shed) and queue-wait windows keyed by tenant — published as
        # LABELED series so one scrape surface splits SLO attainment by
        # tenant without N registries
        self.tenant_deadline_misses: Dict[str, int] = {}
        self.tenant_queue_wait_s: Dict[str, deque] = {}
        self._first_event_at: Optional[float] = None
        self._last_token_at: Optional[float] = None

    # ---- event hooks (called by the engine) ------------------------------

    def on_submit(self, now: float, accepted: bool) -> None:
        self.submitted += 1
        if not accepted:
            self.rejected += 1
        if self._first_event_at is None:
            self._first_event_at = now

    def on_prefill(self, n_tokens: int) -> None:
        self.prefill_tokens += n_tokens

    def on_step(self, n_decode_rows: int, n_prefill_rows: int,
                n_pad_rows: int, n_slots: Optional[int] = None) -> None:
        """One unified-step dispatch: how many decode/verify rows and
        (padded) prefill rows rode it, and how much of the prefill
        bucket was padding.  ``n_slots`` is the running-slot
        participation count — equal to the row count without
        speculation, 1/k1 of it with (each speculating slot ships k1
        verify rows).  ``fuse_tick=False`` (the v1 two-dispatch
        control) calls this twice per busy tick — the dispatch-count
        delta IS the A/B."""
        self.step_dispatches += 1
        self.decode_rows += n_decode_rows
        self.decode_slots += n_slots if n_slots is not None \
            else n_decode_rows
        self.prefill_rows += n_prefill_rows
        self.prefill_pad_rows += max(0, n_pad_rows)

    def on_prefix(self, requested: int, saved: int) -> None:
        """One admission's prefix-cache outcome: ``requested`` tokens
        wanted materializing, ``saved`` of them came stitched from the
        cache (0 on a miss or with caching off).  Re-admissions after
        preemption count again — saved recompute is still saved work."""
        self.prefix_requested_tokens += requested
        self.prefill_tokens_saved += saved

    def on_cow(self) -> None:
        self.cow_forks += 1

    def on_spec(self, proposed: int, accepted: int) -> None:
        """One slot's verify outcome this tick: ``proposed`` drafts rode
        the widened step, ``accepted`` of them survived the walk (a
        shortfall is a rollback)."""
        if proposed > 0:
            self.spec_ticks += 1
        self.spec_tokens_proposed += proposed
        self.spec_tokens_accepted += accepted
        if accepted < proposed:
            self.spec_rollbacks += 1

    def on_spec_suspend(self, n: int = 1) -> None:
        self.spec_suspended += n

    def on_spec_cow(self) -> None:
        self.spec_cow_forks += 1
        self.cow_forks += 1

    def on_draft(self, steps: int, seconds: float) -> None:
        """Absolute draft-proposer counters (gauges, stamped per tick)."""
        self.draft_steps = steps
        self.draft_time_s = seconds

    def on_admit(self, queue_wait_s: float) -> None:
        self.queue_wait_s.append(max(0.0, queue_wait_s))

    def on_tenant_admit(self, tenant: str, queue_wait_s: float) -> None:
        """Per-tenant half of :meth:`on_admit` (separate hook so legacy
        callers without tenant identity change nothing)."""
        self.tenant_queue_wait_s.setdefault(
            tenant, deque(maxlen=_WINDOW)).append(max(0.0, queue_wait_s))

    def on_tenant_miss(self, tenant: str) -> None:
        """A deadline miss (TIMED_OUT or shed) billed to ``tenant``."""
        self.tenant_deadline_misses[tenant] = \
            self.tenant_deadline_misses.get(tenant, 0) + 1

    def on_token(self, now: float, ttft_s: Optional[float] = None) -> None:
        self.tokens_generated += 1
        self._last_token_at = now
        if ttft_s is not None:
            self.ttft_s.append(ttft_s)

    def on_complete(self) -> None:
        self.completed += 1

    def on_timeout(self) -> None:
        self.timed_out += 1

    def on_cancel(self) -> None:
        self.cancelled += 1

    def on_fail(self) -> None:
        self.failed += 1

    def on_shed(self) -> None:
        self.shed += 1

    def on_retry(self) -> None:
        self.retries += 1

    def on_preempt(self, n: int) -> None:
        self.preemptions += n

    def on_host_tier(self, snap: Dict[str, int], host_hits: int) -> None:
        """Stamp the host-tier gauges from ``HostPageTier.snapshot()``
        plus the engine's hit counter (a hit is a swap-in EVENT that
        served a request; the tier only sees pages)."""
        self.pages_host = snap.get("pages_host", 0)
        self.host_swap_ins = snap.get("host_swap_ins", 0)
        self.host_swap_outs = snap.get("host_swap_outs", 0)
        self.host_corrupt = snap.get("host_corrupt", 0)
        self.host_dropped = snap.get("host_dropped", 0)
        self.spill_stall_ticks = snap.get("spill_stall_ticks", 0)
        self.host_hits = int(host_hits)

    def on_tick(self, queue_depth: int, pages_in_use: int,
                pages_cached: int = 0, cache_evictions: int = 0) -> None:
        self.ticks += 1
        self.queue_depth = queue_depth
        self.pages_in_use = pages_in_use
        self.pages_cached = pages_cached
        self.cache_evictions = cache_evictions
        self.peak_pages_in_use = max(self.peak_pages_in_use, pages_in_use)

    # ---- scrape ----------------------------------------------------------

    def tokens_per_s(self) -> float:
        if (self._first_event_at is None or self._last_token_at is None or
                self._last_token_at <= self._first_event_at):
            return 0.0
        return self.tokens_generated / (self._last_token_at -
                                        self._first_event_at)

    def ttft_ms_mean(self) -> float:
        if not self.ttft_s:
            return 0.0
        return 1000.0 * sum(self.ttft_s) / len(self.ttft_s)

    def ttft_ms_p95(self) -> float:
        return 1000.0 * _p95(self.ttft_s)

    def queue_wait_ms_p95(self) -> float:
        return 1000.0 * _p95(self.queue_wait_s)

    def deadline_miss_rate(self) -> float:
        demand = self.completed + self.timed_out + self.shed
        if demand == 0:
            return 0.0
        return (self.timed_out + self.shed) / demand

    def spec_acceptance_rate(self) -> float:
        """Of all drafted tokens shipped to verify, the fraction
        accepted — the number the 2-3x decode-multiplication claim
        rides on (tokens per verify tick = 1 + rate * k)."""
        if self.spec_tokens_proposed == 0:
            return 0.0
        return self.spec_tokens_accepted / self.spec_tokens_proposed

    def prefix_hit_rate(self) -> float:
        """Token-level hit rate: of all the prefill tokens admissions
        asked for, the fraction served from the prefix cache."""
        if self.prefix_requested_tokens == 0:
            return 0.0
        return self.prefill_tokens_saved / self.prefix_requested_tokens

    def publish(self, registry, **labels) -> None:
        """Publish every :meth:`snapshot` value into an obs
        :class:`~paddle_tpu.obs.registry.MetricsRegistry` as gauges
        named ``serving_<key>`` (labels — typically ``replica=idx`` —
        keep multi-engine series apart).  Duck-typed on the registry so
        this module stays importable without obs."""
        for k, v in self.snapshot().items():
            registry.gauge("serving_" + k).labels(**labels).set(v)
        # tenant-labeled series (round 17): the per-tenant SLO split on
        # the SAME registry — publish is idempotent (gauges), so a
        # healthz probe and a scraper read identical numbers
        for t, n in self.tenant_deadline_misses.items():
            registry.gauge(
                "serving_deadline_miss_total",
                "deadline misses (timed_out + shed) by tenant"
            ).labels(tenant=t, **labels).set(n)
        for t, w in self.tenant_queue_wait_s.items():
            registry.gauge(
                "serving_queue_wait_ms",
                "p95 admission queue wait by tenant (recent window)"
            ).labels(tenant=t, **labels).set(round(1000.0 * _p95(w), 3))

    def snapshot(self) -> Dict[str, float]:
        return {
            "tokens_per_s": round(self.tokens_per_s(), 2),
            "ttft_ms_mean": round(self.ttft_ms_mean(), 3),
            "ttft_ms_p95": round(self.ttft_ms_p95(), 3),
            "queue_wait_ms_p95": round(self.queue_wait_ms_p95(), 3),
            "tokens_generated": self.tokens_generated,
            "prefill_tokens": self.prefill_tokens,
            "step_dispatches": self.step_dispatches,
            "decode_rows": self.decode_rows,
            "decode_slots": self.decode_slots,
            "prefill_rows": self.prefill_rows,
            "prefill_pad_rows": self.prefill_pad_rows,
            "prefix_hit_rate": round(self.prefix_hit_rate(), 4),
            "spec_ticks": self.spec_ticks,
            "spec_tokens_proposed": self.spec_tokens_proposed,
            "spec_tokens_accepted": self.spec_tokens_accepted,
            "spec_acceptance_rate": round(self.spec_acceptance_rate(), 4),
            "spec_rollbacks": self.spec_rollbacks,
            "spec_suspended": self.spec_suspended,
            "spec_cow_forks": self.spec_cow_forks,
            "draft_steps": self.draft_steps,
            "draft_time_s": round(self.draft_time_s, 6),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "cow_forks": self.cow_forks,
            "cache_evictions": self.cache_evictions,
            "pages_cached": self.pages_cached,
            "pages_host": self.pages_host,
            "host_swap_ins": self.host_swap_ins,
            "host_swap_outs": self.host_swap_outs,
            "host_hits": self.host_hits,
            "host_corrupt": self.host_corrupt,
            "host_dropped": self.host_dropped,
            "spill_stall_ticks": self.spill_stall_ticks,
            "requests_submitted": self.submitted,
            "requests_rejected": self.rejected,
            "requests_completed": self.completed,
            "requests_timed_out": self.timed_out,
            "requests_cancelled": self.cancelled,
            "requests_failed": self.failed,
            "requests_shed": self.shed,
            "deadline_miss_rate": round(self.deadline_miss_rate(), 4),
            "retries": self.retries,
            "preemptions": self.preemptions,
            "ticks": self.ticks,
            "queue_depth": self.queue_depth,
            "page_occupancy": round(self.pages_in_use / self.pool_pages, 4),
            "page_occupancy_peak": round(
                self.peak_pages_in_use / self.pool_pages, 4),
        }


class FleetMetrics:
    """Fleet-level counters (round 11): what the fleet bench and an
    external scraper read about the WHOLE deployment, as opposed to the
    per-replica :class:`ServingMetrics` each engine keeps.

    The load-bearing invariants live here as plain counters so the
    conservation check can assert them:

    - ``duplicate_completions`` MUST stay 0 — one fleet rid completes at
      most once, no matter how many replicas died under it;
    - ``resubmits`` counts death-driven re-dispatches (budgeted by the
      router; exhaustion ends in FAILED, never an infinite loop);
    - ``fleet_tokens_per_s`` runs over EMITTED tokens — the exactly-once
      stream the router forwards — so a request replayed on a survivor
      after a kill counts each token once, not once per attempt.
    """

    def __init__(self):
        self.submitted = 0
        self.completed = 0
        self.timed_out = 0
        self.cancelled = 0
        self.failed = 0
        self.rejected = 0            # refused at (re-)dispatch: no capacity
        self.shed = 0                # engine-judged unmeetable deadline
        self.resubmits = 0           # death-driven re-dispatches
        self.duplicate_completions = 0   # idempotence violation: MUST be 0
        self.routed = 0              # successful dispatches (incl. resubmit)
        self.affinity_hits = 0       # of those, routed to the prefix owner
        self.tokens_emitted = 0      # exactly-once stream, all requests
        self.replicas_joined = 0
        self.replicas_dead = 0       # killed / lease-expired
        self.replicas_drained = 0    # clean DRAINING -> DEAD retirements
        # page-migration plane (round 16).  The conservation invariant:
        # every started migration ends exactly one way —
        #   migrations_started == applied + fallbacks + aborted
        # (applied = chain spliced into the destination; fallback = blob
        # dropped in flight, destination re-prefills; aborted = the
        # source request reached a terminal status before the transfer
        # cleared admission).
        self.migrations_started = 0
        self.migrations_applied = 0
        self.migration_fallbacks = 0
        self.migrations_aborted = 0
        self.pages_migrated = 0      # pages spliced by applied handoffs
        self.migration_bytes = 0     # host-blob payload bytes, applied only
        self.cross_replica_seeds = 0  # prefix exports that warmed a peer
        self.seed_pages = 0
        self.seed_bytes = 0
        self.migration_resubmits = 0  # death resubmits that re-adopted pages
        # crash-warm restart (round 21): a dead replica's host tier
        # outlives its engine; restart_replica re-verifies and re-adopts
        # it instead of starting cold
        self.warm_restarts = 0        # restart_replica calls that adopted
        self.pages_restored = 0       # host pages verified + re-adopted
        # multi-tenant split (round 17): exactly-once emitted tokens by
        # tenant — same stream as ``tokens_emitted``, partitioned so the
        # scrape surface can bill goodput per tenant
        self.tenant_tokens: Dict[str, int] = {}
        self._first_event_at: Optional[float] = None
        self._last_token_at: Optional[float] = None

    # ---- event hooks (called by the FleetRouter) --------------------------

    def on_submit(self, now: float) -> None:
        self.submitted += 1
        if self._first_event_at is None:
            self._first_event_at = now

    def on_route(self, affinity: bool) -> None:
        self.routed += 1
        if affinity:
            self.affinity_hits += 1

    def on_resubmit(self) -> None:
        self.resubmits += 1

    def on_migration_start(self) -> None:
        self.migrations_started += 1

    def on_migration_applied(self, pages: int, nbytes: int) -> None:
        self.migrations_applied += 1
        self.pages_migrated += int(pages)
        self.migration_bytes += int(nbytes)

    def on_migration_fallback(self) -> None:
        self.migration_fallbacks += 1

    def on_migration_aborted(self) -> None:
        self.migrations_aborted += 1

    def on_seed(self, pages: int, nbytes: int) -> None:
        self.cross_replica_seeds += 1
        self.seed_pages += int(pages)
        self.seed_bytes += int(nbytes)

    def on_migration_resubmit(self) -> None:
        self.migration_resubmits += 1

    def on_warm_restart(self, pages: int) -> None:
        self.warm_restarts += 1
        self.pages_restored += int(pages)

    def on_token(self, now: float, tenant: Optional[str] = None) -> None:
        self.tokens_emitted += 1
        if tenant is not None:
            self.tenant_tokens[tenant] = self.tenant_tokens.get(tenant, 0) + 1
        self._last_token_at = now

    def on_terminal(self, status, shed: bool = False) -> None:
        if shed:
            self.shed += 1
            return
        key = {"completed": "completed", "timed_out": "timed_out",
               "cancelled": "cancelled", "failed": "failed",
               "rejected": "rejected"}[str(status)]
        setattr(self, key, getattr(self, key) + 1)

    # ---- scrape ----------------------------------------------------------

    def fleet_tokens_per_s(self) -> float:
        if (self._first_event_at is None or self._last_token_at is None or
                self._last_token_at <= self._first_event_at):
            return 0.0
        return self.tokens_emitted / (self._last_token_at -
                                      self._first_event_at)

    def deadline_miss_rate(self) -> float:
        """Of the demand that wanted completion, the fraction that
        missed — same definition as the per-engine metric, but over
        fleet terminal statuses.  An engine-side TIMED_OUT is harvested
        as fleet-terminal even on a dying replica (deadlines carry over
        as absolute times, so the resubmit could never make it): it
        counts as a miss, never as timeout-then-recover."""
        demand = self.completed + self.timed_out + self.shed
        if demand == 0:
            return 0.0
        return (self.timed_out + self.shed) / demand

    def publish(self, registry, **labels) -> None:
        """Publish every :meth:`snapshot` value (already
        ``fleet_``-prefixed) into an obs registry as gauges — the
        fleet-level half of the one-scrape-surface contract."""
        for k, v in self.snapshot().items():
            registry.gauge(k).labels(**labels).set(v)
        # tenant-labeled goodput (round 17): the exactly-once token
        # stream split by tenant, one labeled gauge per tenant on the
        # same registry (idempotent re-publish, like every fleet gauge)
        for t, n in self.tenant_tokens.items():
            registry.gauge(
                "fleet_tokens_total",
                "exactly-once emitted tokens by tenant"
            ).labels(tenant=t, **labels).set(n)

    def snapshot(self) -> Dict[str, float]:
        return {
            "fleet_tokens_per_s": round(self.fleet_tokens_per_s(), 2),
            "fleet_tokens_emitted": self.tokens_emitted,
            "fleet_submitted": self.submitted,
            "fleet_completed": self.completed,
            "fleet_timed_out": self.timed_out,
            "fleet_cancelled": self.cancelled,
            "fleet_failed": self.failed,
            "fleet_rejected": self.rejected,
            "fleet_shed": self.shed,
            "fleet_deadline_miss_rate": round(self.deadline_miss_rate(), 4),
            "fleet_resubmits": self.resubmits,
            "fleet_duplicate_completions": self.duplicate_completions,
            "fleet_routed": self.routed,
            "fleet_affinity_hits": self.affinity_hits,
            "fleet_replicas_joined": self.replicas_joined,
            "fleet_replicas_dead": self.replicas_dead,
            "fleet_replicas_drained": self.replicas_drained,
            "fleet_migrations_started": self.migrations_started,
            "fleet_migrations_applied": self.migrations_applied,
            "fleet_migration_fallbacks": self.migration_fallbacks,
            "fleet_migrations_aborted": self.migrations_aborted,
            "fleet_pages_migrated": self.pages_migrated,
            "fleet_migration_bytes": self.migration_bytes,
            "fleet_cross_replica_seeds": self.cross_replica_seeds,
            "fleet_seed_pages": self.seed_pages,
            "fleet_seed_bytes": self.seed_bytes,
            "fleet_migration_resubmits": self.migration_resubmits,
            "fleet_warm_restarts": self.warm_restarts,
            "fleet_pages_restored": self.pages_restored,
        }
