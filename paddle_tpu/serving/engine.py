"""ServingEngine: the user-facing paged-KV continuous-batching API.

Usage::

    model = DecoderLM(vocab_size=512, num_layers=2, num_heads=2,
                      head_dim=16)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=16,
                        num_pages=96, max_pages_per_seq=8, max_slots=8)
    rid = eng.submit([7, 12, 3], max_tokens=32,
                     on_token=lambda tok: print(tok))
    results = eng.run()          # {rid: [generated tokens...]}
    eng.metrics.snapshot()       # tokens/s, TTFT, occupancy, ...

The engine owns exactly two compiled functions:

- a **bucketed prefill** (one jit specialization per padded length in
  the bucket ladder): full causal self-attention over the prompt —
  through ``ops.attention.flash_attention`` when the bucket is
  kernel-shaped, ``mha_reference`` otherwise — that writes the prompt's
  K/V into the request's pages and emits the first token from the
  last-position logits;
- a **fused decode step** over ALL running sequences per tick: embed the
  last emitted tokens, append their K/V into each sequence's current
  page, and attend over the paged cache (``paged_decode_attention``).

Decoding is greedy (argmax) — the deterministic contract the parity
tests pin; sampling policies layer on top later.

The model plugs in through the small :class:`DecodeModel` contract
rather than a ``Topology``: serving needs per-layer access to Q/K/V
*before* attention runs (the cache sits between them), which the opaque
layer graph doesn't expose.  :class:`DecoderLM` is the built-in
reference implementation (and the bench model); any object with the same
methods works, so a topology-built transformer can be adapted by
exposing its projection weights.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.attention import flash_attention, mha_reference
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving.decode_attention import paged_decode_attention
from paddle_tpu.serving.kv_cache import (NULL_PAGE, KVPages, PagedKVConfig,
                                         PagePool, append_token,
                                         init_kv_pages, write_prompt)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Request, SchedulerConfig,
                                          bucket_for)

__all__ = ["DecodeModel", "DecoderLM", "ServingEngine",
           "greedy_decode_reference"]


class DecodeModel:
    """Structural contract the engine drives (duck-typed; subclassing is
    optional).  All methods must be jax-traceable and shape-polymorphic
    over leading batch/sequence dims:

    - ``num_layers``, ``num_heads``, ``head_dim``, ``vocab_size``
    - ``embed(params, tokens, positions) -> [..., E]``
    - ``qkv(params, layer, x) -> (q, k, v)`` each ``[..., H, D]``
    - ``attn_out(params, layer, ctx, x) -> [..., E]`` — attention output
      ``ctx`` [..., H, D] combined with the residual stream ``x``
      (projection, residual, FFN — whatever the architecture does after
      attention)
    - ``logits(params, x) -> [..., vocab_size]``
    """

    num_layers: int
    num_heads: int
    head_dim: int
    vocab_size: int


def _rms(x, eps: float = 1e-6):
    return x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1,
                                      keepdims=True) + eps)


class DecoderLM(DecodeModel):
    """A compact pre-norm decoder-only transformer LM implementing the
    :class:`DecodeModel` contract — the built-in serving/bench model.
    Parameter-free RMSNorm keeps the param dict to embeddings +
    projections."""

    def __init__(self, vocab_size: int, num_layers: int = 2,
                 num_heads: int = 2, head_dim: int = 16,
                 ffn_mult: int = 4, max_positions: int = 1024):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.ffn_dim = ffn_mult * self.embed_dim
        self.max_positions = max_positions

    def init_params(self, key) -> Dict[str, jax.Array]:
        e, f, v = self.embed_dim, self.ffn_dim, self.vocab_size
        keys = jax.random.split(key, 2 + 6 * self.num_layers + 1)
        ki = iter(keys)

        def mat(shape, scale):
            return (jax.random.normal(next(ki), shape, jnp.float32) * scale)

        p = {"emb": mat((v, e), 0.02), "pos": mat((self.max_positions, e),
                                                  0.02)}
        for l in range(self.num_layers):
            p[f"l{l}.wq"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wk"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wv"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wo"] = mat((e, e), e ** -0.5)
            p[f"l{l}.w1"] = mat((e, f), e ** -0.5)
            p[f"l{l}.w2"] = mat((f, e), f ** -0.5)
        p["out"] = mat((e, v), e ** -0.5)
        return p

    def embed(self, params, tokens, positions):
        return params["emb"][tokens] + params["pos"][positions]

    def qkv(self, params, layer, x):
        h, d = self.num_heads, self.head_dim
        xn = _rms(x)
        shape = x.shape[:-1] + (h, d)
        q = (xn @ params[f"l{layer}.wq"]).reshape(shape)
        k = (xn @ params[f"l{layer}.wk"]).reshape(shape)
        v = (xn @ params[f"l{layer}.wv"]).reshape(shape)
        return q, k, v

    def attn_out(self, params, layer, ctx, x):
        flat = ctx.reshape(x.shape[:-1] + (self.embed_dim,))
        a = x + flat @ params[f"l{layer}.wo"]
        return a + jax.nn.gelu(_rms(a) @ params[f"l{layer}.w1"]) \
            @ params[f"l{layer}.w2"]

    def logits(self, params, x):
        return _rms(x) @ params["out"]


def greedy_decode_reference(model: DecodeModel, params, prompt: List[int],
                            max_tokens: int, eos_id: int) -> List[int]:
    """The NON-paged oracle: a host loop that re-runs the full causal
    forward over the whole history each step (``mha_reference``, no KV
    cache at all) and greedily extends.  Slow by construction — it
    exists as the parity target for the engine's paged path."""
    tokens = list(prompt)
    out: List[int] = []
    for _ in range(max_tokens):
        t = jnp.asarray(tokens, jnp.int32)[None]          # [1, T]
        pos = jnp.arange(len(tokens), dtype=jnp.int32)[None]
        x = model.embed(params, t, pos)
        for l in range(model.num_layers):
            q, k, v = model.qkv(params, l, x)
            ctx = mha_reference(q, k, v, causal=True)
            x = model.attn_out(params, l, ctx, x)
        nxt = int(jnp.argmax(model.logits(params, x[0, -1])))
        out.append(nxt)
        tokens.append(nxt)
        if nxt == eos_id:
            break
    return out


def _parse_buckets(spec: str) -> Tuple[int, ...]:
    return tuple(sorted(int(t) for t in spec.split(",") if t.strip()))


class ServingEngine:
    """Paged-KV continuous-batching inference engine (see module doc)."""

    def __init__(self, model: DecodeModel, params, *, eos_id: int,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_pages_per_seq: Optional[int] = None,
                 max_slots: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None,
                 dtype=jnp.float32,
                 use_kernel: Optional[bool] = None):
        self.model = model
        self.params = params
        self.eos_id = int(eos_id)
        page_size = int(page_size or FLAGS.serving_page_size)
        num_pages = int(num_pages or FLAGS.serving_max_pages)
        max_slots = int(max_slots or FLAGS.serving_max_slots)
        if max_pages_per_seq is None:
            # default: one sequence may claim up to half the usable pool
            max_pages_per_seq = max(1, (num_pages - 1) // 2)
        self.kv_cfg = PagedKVConfig(
            num_layers=model.num_layers, num_heads=model.num_heads,
            head_dim=model.head_dim, page_size=page_size,
            num_pages=num_pages, max_pages_per_seq=int(max_pages_per_seq),
            dtype=dtype)
        self._kv: KVPages = init_kv_pages(self.kv_cfg)
        self.pool = PagePool(num_pages)
        self.scheduler = ContinuousBatchingScheduler(
            self.pool, SchedulerConfig(
                max_slots=max_slots, page_size=page_size,
                max_pages_per_seq=int(max_pages_per_seq),
                max_queue=max_queue))
        self.metrics = ServingMetrics(pool_pages=self.pool.num_usable)
        self._use_kernel = use_kernel
        self._buckets = tuple(sorted(int(b) for b in buckets)) if buckets \
            else _parse_buckets(FLAGS.serving_prefill_buckets)
        self._max_slots = max_slots
        # donate the incoming KV pool: every call overwrites self._kv
        # with the returned pool, so XLA may update pages in place —
        # without this the decode tick copies the whole pool and peak
        # HBM doubles the documented cost.  CPU doesn't support donation
        # (it would just warn), hence the gate.
        self._donate_kv = (1,) if jax.default_backend() != "cpu" else ()
        self._decode_fn = jax.jit(self._build_decode_fn(),
                                  donate_argnums=self._donate_kv)
        self._prefill_fns: Dict[int, Callable] = {}
        self._results: Dict[int, List[int]] = {}
        self._requests: Dict[int, Request] = {}

    # ---- compiled device functions --------------------------------------

    def _build_decode_fn(self):
        model, cfg = self.model, self.kv_cfg
        page, use_kernel = cfg.page_size, self._use_kernel

        def fn(params, kv: KVPages, tokens, positions, page_table, lens,
               active):
            # tokens/positions/lens/active: [B]; page_table: [B, Pm].
            # One fused decode step: embed, per-layer append + paged
            # attention, logits.  Inactive rows write the null page and
            # produce garbage logits the host ignores.
            b = tokens.shape[0]
            x = model.embed(params, tokens, positions)
            page_ids = jnp.where(
                active, page_table[jnp.arange(b), lens // page], NULL_PAGE)
            offs = lens % page
            att_lens = jnp.where(active, lens + 1, 0)
            for l in range(cfg.num_layers):
                q, k, v = model.qkv(params, l, x)
                kv = append_token(kv, l, k, v, page_ids, offs)
                ctx = paged_decode_attention(
                    q, kv.k[l], kv.v[l], page_table, att_lens,
                    use_kernel=use_kernel)
                x = model.attn_out(params, l, ctx, x)
            return model.logits(params, x), kv

        return fn

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        model, cfg = self.model, self.kv_cfg
        page = cfg.page_size
        # kernel-shaped buckets prefill through the flash kernel; the
        # rest (short buckets, odd head dims) use the plain reference
        use_flash = (bucket % 128 == 0 and
                     (cfg.head_dim * cfg.num_heads) % 8 == 0)

        def raw(params, kv: KVPages, tokens, n, page_row):
            # tokens: [bucket] i32 (padded); n: scalar i32 true length;
            # page_row: [Pm] i32 — this request's page table row.
            pos = jnp.arange(bucket, dtype=jnp.int32)
            x = model.embed(params, tokens[None], pos[None])   # [1, T, E]
            tmask = pos < n
            dest = jnp.where(tmask, page_row[pos // page], NULL_PAGE)
            offs = pos % page
            seg = jnp.where(tmask, 0, 1)[None].astype(jnp.int32)
            for l in range(cfg.num_layers):
                q, k, v = model.qkv(params, l, x)              # [1, T, H, D]
                kv = write_prompt(kv, l, k[0], v[0], dest, offs)
                if use_flash:
                    ctx = flash_attention(q, k, v, segment_ids=seg,
                                          causal=True)
                else:
                    ctx = mha_reference(q, k, v, segment_ids=seg,
                                        causal=True)
                x = model.attn_out(params, l, ctx, x)
            last = jnp.take(x[0], jnp.maximum(n - 1, 0), axis=0)
            return model.logits(params, last), kv

        fn = jax.jit(raw, donate_argnums=self._donate_kv)
        self._prefill_fns[bucket] = fn
        return fn

    # ---- user surface ----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_tokens: int,
               on_token: Optional[Callable[[int], None]] = None,
               now: Optional[float] = None) -> Optional[int]:
        """Queue a request.  Returns its rid, or None if rejected
        (infeasible size, or queue backpressure)."""
        req = Request(prompt=list(int(t) for t in prompt),
                      max_tokens=int(max_tokens), on_token=on_token)
        t = time.monotonic() if now is None else now
        ok = self.scheduler.submit(req, now=t)
        self.metrics.on_submit(t, ok)
        if not ok:
            return None
        self._requests[req.rid] = req
        return req.rid

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def step(self, now: Optional[float] = None) -> bool:
        """One engine tick: admit + prefill, grow/preempt, one fused
        decode over all running sequences.  Returns True if any work
        remains."""
        now = time.monotonic() if now is None else now
        sched, m = self.scheduler, self.metrics
        # growth/preemption BEFORE admission: a tick must not pay for a
        # new request's prefill and then immediately preempt it (the
        # youngest) to grow older sequences.  admit() reserves the first
        # decode append's page, so fresh admissions never need same-tick
        # growth either.
        m.on_preempt(len(sched.ensure_decode_pages()))
        for req in sched.admit():
            self._do_prefill(req)
        running = [r for r in sched.running_requests()
                   if r.status == "running"]
        if running:
            self._do_decode(running)
        m.on_tick(sched.queue_depth, self.pool.num_in_use)
        return self.has_work

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, List[int]]:
        """Tick until drained (or ``max_ticks``); returns
        {rid: generated tokens} for everything completed so far."""
        ticks = 0
        while self.has_work:
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return dict(self._results)

    def result(self, rid: int) -> Optional[List[int]]:
        return self._results.get(rid)

    # ---- internals -------------------------------------------------------

    def _do_prefill(self, req: Request) -> None:
        toks = req.cache_tokens
        n = len(toks)
        bucket = bucket_for(n, self._buckets, self.kv_cfg.max_seq_len)
        padded = np.zeros((bucket,), np.int32)
        padded[:n] = toks
        row = np.full((self.kv_cfg.max_pages_per_seq,), NULL_PAGE, np.int32)
        row[:len(req.pages)] = req.pages
        logits, self._kv = self._prefill_fn(bucket)(
            self.params, self._kv, jnp.asarray(padded),
            jnp.asarray(n, jnp.int32), jnp.asarray(row))
        req.cache_len = n
        self.metrics.on_prefill(n)
        tok = int(np.argmax(np.asarray(logits)))  # forces device sync
        # stamp AFTER the sync so TTFT includes the prefill compute
        self._emit(req, tok, time.monotonic())

    def _do_decode(self, running: List[Request]) -> None:
        b = self._max_slots
        cfg = self.kv_cfg
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        lens = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        table = np.full((b, cfg.max_pages_per_seq), NULL_PAGE, np.int32)
        for req in running:
            s = req.slot
            tokens[s] = req.generated[-1]
            positions[s] = req.cache_len
            lens[s] = req.cache_len
            active[s] = True
            table[s, :len(req.pages)] = req.pages
        logits, self._kv = self._decode_fn(
            self.params, self._kv, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(table), jnp.asarray(lens),
            jnp.asarray(active))
        logits = np.asarray(logits)   # forces device sync
        now = time.monotonic()        # emission time includes the compute
        for req in running:
            req.cache_len += 1
            self._emit(req, int(np.argmax(logits[req.slot])), now)

    def _emit(self, req: Request, tok: int, now: float) -> None:
        req.generated.append(tok)
        ttft = None
        if req.first_token_at is None:
            req.first_token_at = now
            ttft = max(0.0, now - (req.submitted_at or now))
        self.metrics.on_token(now, ttft)
        if req.on_token is not None:
            req.on_token(tok)
        if tok == self.eos_id or len(req.generated) >= req.max_tokens:
            req.finished_at = now
            self.scheduler.release(req)
            self._results[req.rid] = list(req.generated)
            self.metrics.on_complete()
