"""ServingEngine: the user-facing paged-KV continuous-batching API.

Usage::

    model = DecoderLM(vocab_size=512, num_layers=2, num_heads=2,
                      head_dim=16)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=16,
                        num_pages=96, max_pages_per_seq=8, max_slots=8)
    rid = eng.submit([7, 12, 3], max_tokens=32, deadline_s=2.0,
                     on_token=lambda tok: print(tok))
    results = eng.run()          # {rid: [generated tokens...]}
    eng.status(rid)              # RequestStatus.COMPLETED
    eng.metrics.snapshot()       # tokens/s, TTFT, SLO counters, ...
    eng.healthz()                # liveness/conservation snapshot

The engine owns exactly two compiled functions:

- a **bucketed prefill** (one jit specialization per padded length in
  the bucket ladder): full causal self-attention over the prompt —
  through ``ops.attention.flash_attention`` when the bucket is
  kernel-shaped, ``mha_reference`` otherwise — that writes the prompt's
  K/V into the request's pages and emits the first token from the
  last-position logits;
- a **fused decode step** over ALL running sequences per tick: embed the
  last emitted tokens, append their K/V into each sequence's current
  page, and attend over the paged cache (``paged_decode_attention``).

Decoding is greedy (argmax) — the deterministic contract the parity
tests pin; sampling policies layer on top later.

Robustness layer (round 8): every request moves through a real
:class:`RequestStatus` lifecycle with optional queue/total deadlines and
``cancel(rid)``; timed-out and cancelled requests release their slot and
pages immediately.  The decode tick carries a finite-logits guard that
fails ONLY the poisoned slot (the rest of the fused batch keeps
running), retries transiently-failing ticks, and a progress watchdog
fails slots stuck past ``serving_watchdog_ticks``.  Deadlocked demand is
shed: queued requests whose deadline is provably unmeetable are
early-rejected instead of burning prefill work.  All failure paths are
driven deterministically by a :class:`~paddle_tpu.serving.faults.FaultPlan`
(injectable clock, decode-step errors, NaN logits, page pressure) and a
free-list conservation check runs after every drain.

Prefix caching + chunked prefill (round 9): with
``FLAGS.serving_prefix_cache`` on (the default), admission splits every
prompt into ``cached_prefix_pages + tail`` against a chained-hash
:class:`~paddle_tpu.serving.kv_cache.PrefixCache` — the prefix pages are
refcount-shared (charged zero new pages), the tail prefills with its
positions offset by the cached length, and a full-cover hit
copy-on-write-forks the last shared page and recomputes only the final
token.  Prompts longer than ``FLAGS.serving_prefill_chunk`` prefill one
chunk per tick, interleaved with the fused decode step, so a long
prompt in the queue no longer degrades running slots' latency.

The model plugs in through the small :class:`DecodeModel` contract
rather than a ``Topology``: serving needs per-layer access to Q/K/V
*before* attention runs (the cache sits between them), which the opaque
layer graph doesn't expose.  :class:`DecoderLM` is the built-in
reference implementation (and the bench model); any object with the same
methods works, so a topology-built transformer can be adapted by
exposing its projection weights.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.analysis.retrace import audit_jit, auditor
from paddle_tpu.obs.registry import MetricsRegistry
from paddle_tpu.obs.trace import NULL_TRACER, tracer_for
from paddle_tpu.ops.attention import (DEFAULT_MASK_VALUE, flash_attention,
                                      mha_reference)
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving.decode_attention import paged_decode_attention
from paddle_tpu.serving.faults import (FaultPlan, InjectedDeviceError,
                                       PageLeakError)
from paddle_tpu.serving.kv_cache import (NULL_PAGE, KVPages, PagedKVConfig,
                                         PagePool, PrefixCache, append_token,
                                         fork_page, gather_kv, init_kv_pages,
                                         write_prompt, zero_pages)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Request, RequestStatus,
                                          SchedulerConfig, bucket_for)

__all__ = ["DecodeModel", "DecoderLM", "ServingEngine",
           "greedy_decode_reference"]


class DecodeModel:
    """Structural contract the engine drives (duck-typed; subclassing is
    optional).  All methods must be jax-traceable and shape-polymorphic
    over leading batch/sequence dims:

    - ``num_layers``, ``num_heads``, ``head_dim``, ``vocab_size``
    - ``embed(params, tokens, positions) -> [..., E]``
    - ``qkv(params, layer, x) -> (q, k, v)`` each ``[..., H, D]``
    - ``attn_out(params, layer, ctx, x) -> [..., E]`` — attention output
      ``ctx`` [..., H, D] combined with the residual stream ``x``
      (projection, residual, FFN — whatever the architecture does after
      attention)
    - ``logits(params, x) -> [..., vocab_size]``
    """

    num_layers: int
    num_heads: int
    head_dim: int
    vocab_size: int


def _rms(x, eps: float = 1e-6):
    return x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1,
                                      keepdims=True) + eps)


class DecoderLM(DecodeModel):
    """A compact pre-norm decoder-only transformer LM implementing the
    :class:`DecodeModel` contract — the built-in serving/bench model.
    Parameter-free RMSNorm keeps the param dict to embeddings +
    projections."""

    def __init__(self, vocab_size: int, num_layers: int = 2,
                 num_heads: int = 2, head_dim: int = 16,
                 ffn_mult: int = 4, max_positions: int = 1024):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.ffn_dim = ffn_mult * self.embed_dim
        self.max_positions = max_positions

    def init_params(self, key) -> Dict[str, jax.Array]:
        e, f, v = self.embed_dim, self.ffn_dim, self.vocab_size
        keys = jax.random.split(key, 2 + 6 * self.num_layers + 1)
        ki = iter(keys)

        def mat(shape, scale):
            return (jax.random.normal(next(ki), shape, jnp.float32) * scale)

        p = {"emb": mat((v, e), 0.02), "pos": mat((self.max_positions, e),
                                                  0.02)}
        for l in range(self.num_layers):
            p[f"l{l}.wq"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wk"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wv"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wo"] = mat((e, e), e ** -0.5)
            p[f"l{l}.w1"] = mat((e, f), e ** -0.5)
            p[f"l{l}.w2"] = mat((f, e), f ** -0.5)
        p["out"] = mat((e, v), e ** -0.5)
        return p

    def embed(self, params, tokens, positions):
        return params["emb"][tokens] + params["pos"][positions]

    def qkv(self, params, layer, x):
        h, d = self.num_heads, self.head_dim
        xn = _rms(x)
        shape = x.shape[:-1] + (h, d)
        q = (xn @ params[f"l{layer}.wq"]).reshape(shape)
        k = (xn @ params[f"l{layer}.wk"]).reshape(shape)
        v = (xn @ params[f"l{layer}.wv"]).reshape(shape)
        return q, k, v

    def attn_out(self, params, layer, ctx, x):
        flat = ctx.reshape(x.shape[:-1] + (self.embed_dim,))
        a = x + flat @ params[f"l{layer}.wo"]
        return a + jax.nn.gelu(_rms(a) @ params[f"l{layer}.w1"]) \
            @ params[f"l{layer}.w2"]

    def logits(self, params, x):
        return _rms(x) @ params["out"]


def greedy_decode_reference(model: DecodeModel, params, prompt: List[int],
                            max_tokens: int, eos_id: int) -> List[int]:
    """The NON-paged oracle: a host loop that re-runs the full causal
    forward over the whole history each step (``mha_reference``, no KV
    cache at all) and greedily extends.  Slow by construction — it
    exists as the parity target for the engine's paged path."""
    tokens = list(prompt)
    out: List[int] = []
    for _ in range(max_tokens):
        # per-step host syncs are the POINT of this oracle: it trades
        # throughput for an unarguable reference trajectory
        t = jnp.asarray(tokens, jnp.int32)[None]   # lint: allow(host-sync)
        pos = jnp.arange(len(tokens), dtype=jnp.int32)[None]
        x = model.embed(params, t, pos)
        for l in range(model.num_layers):
            q, k, v = model.qkv(params, l, x)
            ctx = mha_reference(q, k, v, causal=True)
            x = model.attn_out(params, l, ctx, x)
        nxt = int(jnp.argmax(model.logits(params, x[0, -1])))  # lint: allow(host-sync)
        out.append(nxt)
        tokens.append(nxt)
        if nxt == eos_id:
            break
    return out


def _parse_buckets(spec: str) -> Tuple[int, ...]:
    return tuple(sorted(int(t) for t in spec.split(",") if t.strip()))


class ServingEngine:
    """Paged-KV continuous-batching inference engine (see module doc)."""

    def __init__(self, model: DecodeModel, params, *, eos_id: int,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_pages_per_seq: Optional[int] = None,
                 max_slots: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None,
                 dtype=jnp.float32,
                 use_kernel: Optional[bool] = None,
                 queue_deadline_s: Optional[float] = None,
                 preempt_budget: Optional[int] = None,
                 watchdog_ticks: Optional[int] = None,
                 decode_retries: int = 2,
                 transient_errors: Tuple[type, ...] = (InjectedDeviceError,),
                 max_retained: int = 10000,
                 prefix_cache: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 faults: Optional[FaultPlan] = None,
                 time_fn: Optional[Callable[[], float]] = None,
                 tracer=None, registry: Optional[MetricsRegistry] = None):
        self.model = model
        self.params = params
        self.eos_id = int(eos_id)
        page_size = int(page_size or FLAGS.serving_page_size)
        num_pages = int(num_pages or FLAGS.serving_max_pages)
        max_slots = int(max_slots or FLAGS.serving_max_slots)
        if max_pages_per_seq is None:
            # default: one sequence may claim up to half the usable pool
            max_pages_per_seq = max(1, (num_pages - 1) // 2)
        if queue_deadline_s is None:
            queue_deadline_s = float(FLAGS.serving_queue_deadline_s)
        if preempt_budget is None:
            preempt_budget = int(FLAGS.serving_preempt_budget)
        if watchdog_ticks is None:
            watchdog_ticks = int(FLAGS.serving_watchdog_ticks)
        self.queue_deadline_s = queue_deadline_s or None   # 0 = disabled
        self.watchdog_ticks = int(watchdog_ticks)          # 0 = disabled
        self.decode_retries = max(0, int(decode_retries))
        # which exceptions the decode tick treats as transient and
        # retries.  Default: only the fault-plan's injected error.  The
        # retry is sound ONLY for errors raised before the decode
        # executes (the fault plan's injection point): once the jitted
        # step has run, the donated KV pool may already be consumed, so
        # retrying a real mid-execution XLA failure needs KV
        # snapshot/rebuild this engine does not do — don't widen the set
        # to device errors without adding that.
        self.transient_errors = tuple(transient_errors)
        self.max_retained = max(1, int(max_retained))
        self.faults = faults
        # clock precedence: fault-plan clock > explicit time_fn > monotonic
        if faults is not None and faults.clock is not None:
            self._time = faults.clock
        else:
            self._time = time_fn or time.monotonic
        self.kv_cfg = PagedKVConfig(
            num_layers=model.num_layers, num_heads=model.num_heads,
            head_dim=model.head_dim, page_size=page_size,
            num_pages=num_pages, max_pages_per_seq=int(max_pages_per_seq),
            dtype=dtype)
        self._kv: KVPages = init_kv_pages(self.kv_cfg)
        self.pool = PagePool(num_pages)
        if prefix_cache is None:
            prefix_cache = bool(FLAGS.serving_prefix_cache)
        if prefill_chunk is None:
            prefill_chunk = int(FLAGS.serving_prefill_chunk)
        self._prefill_chunk = max(0, int(prefill_chunk))
        self.cache: Optional[PrefixCache] = None
        if prefix_cache:
            hash_fn = faults.cache_hash_fn() if faults is not None else None
            self.cache = PrefixCache(self.pool, page_size, hash_fn=hash_fn)
        self.scheduler = ContinuousBatchingScheduler(
            self.pool, SchedulerConfig(
                max_slots=max_slots, page_size=page_size,
                max_pages_per_seq=int(max_pages_per_seq),
                max_queue=max_queue,
                preempt_budget=preempt_budget if preempt_budget > 0
                else None),
            cache=self.cache, time_fn=self._time)
        self.metrics = ServingMetrics(pool_pages=self.pool.num_usable)
        # obs: tracer (FLAGS.obs_trace-gated at construction — a fleet
        # rebinds its shared, replica-scoped tracer via set_tracer) and
        # the unified metrics registry the per-stage latency histograms
        # and healthz publish into
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._reg_labels: Dict[str, str] = {}
        self._tracer = NULL_TRACER
        self._postmortems_dumped: set = set()
        self.set_tracer(tracer if tracer is not None
                        else tracer_for(self._time, registry=self.registry))
        self._use_kernel = use_kernel
        self._buckets = tuple(sorted(int(b) for b in buckets)) if buckets \
            else _parse_buckets(FLAGS.serving_prefill_buckets)
        self._max_slots = max_slots
        # donate the incoming KV pool: every call overwrites self._kv
        # with the returned pool, so XLA may update pages in place —
        # without this the decode tick copies the whole pool and peak
        # HBM doubles the documented cost.  CPU doesn't support donation
        # (it would just warn), hence the gate.
        self._donate_kv = (1,) if jax.default_backend() != "cpu" else ()
        # audit_jit == jax.jit unless FLAGS.jit_audit is on, in which
        # case each named site's compiles are counted by the retrace
        # auditor (paddle_tpu.analysis.retrace): the fused decode step
        # must compile exactly once, prefill once per bucket shape
        self._decode_fn = audit_jit(self._build_decode_fn(),
                                    site="serving.decode",
                                    donate_argnums=self._donate_kv)
        # COW fork + failure scrub: kv is argument 0 in both (same
        # donation gate as above)
        self._fork_fn = audit_jit(
            fork_page, site="serving.fork_page",
            donate_argnums=(0,) if self._donate_kv else ())
        self._zero_fn = audit_jit(
            zero_pages, site="serving.zero_pages",
            donate_argnums=(0,) if self._donate_kv else ())
        self._prefill_fns: Dict[int, Callable] = {}
        self._chunk_fns: Dict[int, Callable] = {}
        self._results: Dict[int, List[int]] = {}
        self._requests: Dict[int, Request] = {}
        # terminal rids in retirement order; oldest evicted past
        # max_retained so a long-running engine's memory stays bounded
        self._retired: Deque[int] = deque()
        self._tick = 0
        self._last_tick_at: Optional[float] = None
        self._prev_tick_busy = False
        self._tick_dur_ema = 0.0      # drives the unmeetable-deadline shed
        self._draining = False        # drain(): REJECT new submits

    # ---- observability wiring -------------------------------------------

    def set_tracer(self, tracer) -> None:
        """(Re)bind the engine's span tracer — the fleet calls this with
        its shared tracer scoped to the replica index.  The pool,
        scheduler and prefix cache get the raw hook (None when tracing
        is off, so their hot paths pay one is-None check); when the
        retrace auditor is active the tracer also receives its
        ``jit_compile`` events."""
        self._tracer = tracer if tracer is not None else NULL_TRACER
        hook = self._tracer if self._tracer.enabled else None
        self.pool.tracer = hook
        self.scheduler.tracer = hook
        if self.cache is not None:
            self.cache.tracer = hook
        if hook is not None and getattr(FLAGS, "jit_audit", False):
            auditor().attach_tracer(self._tracer.base)

    def set_registry(self, registry: MetricsRegistry, **labels) -> None:
        """(Re)bind the unified metrics registry (fleet: one registry,
        per-replica labels).  All later stage observations and healthz
        publishes land there."""
        self.registry = registry
        self._reg_labels = {k: str(v) for k, v in labels.items()}

    def _observe_stage(self, stage: str, seconds: float) -> None:
        """Per-stage latency attribution (queue / prefill / decode) on
        the engine's injected clock — the registry half of the span
        timeline, cheap enough to stay on unconditionally."""
        self.registry.histogram(
            "serving_stage_seconds",
            "request time per lifecycle stage").labels(
            stage=stage, **self._reg_labels).observe(max(0.0, seconds))

    def _dump_postmortem(self, reason: str) -> None:
        """Flight-recorder dump on a tripped conservation invariant —
        once per reason per engine, so a prober that calls healthz in a
        leaky steady state doesn't spray one file per probe."""
        if reason not in self._postmortems_dumped:
            self._postmortems_dumped.add(reason)
            self._tracer.dump_postmortem(reason)

    # ---- compiled device functions --------------------------------------

    def _build_decode_fn(self):
        model, cfg = self.model, self.kv_cfg
        page, use_kernel = cfg.page_size, self._use_kernel

        def fn(params, kv: KVPages, tokens, positions, page_table, lens,
               active):
            # tokens/positions/lens/active: [B]; page_table: [B, Pm].
            # One fused decode step: embed, per-layer append + paged
            # attention, logits.  Inactive rows write the null page and
            # produce garbage logits the host ignores.
            b = tokens.shape[0]
            x = model.embed(params, tokens, positions)
            page_ids = jnp.where(
                active, page_table[jnp.arange(b), lens // page], NULL_PAGE)
            offs = lens % page
            att_lens = jnp.where(active, lens + 1, 0)
            for l in range(cfg.num_layers):
                q, k, v = model.qkv(params, l, x)
                kv = append_token(kv, l, k, v, page_ids, offs)
                ctx = paged_decode_attention(
                    q, kv.k[l], kv.v[l], page_table, att_lens,
                    use_kernel=use_kernel)
                x = model.attn_out(params, l, ctx, x)
            return model.logits(params, x), kv

        return fn

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        model, cfg = self.model, self.kv_cfg
        page = cfg.page_size
        # kernel-shaped buckets prefill through the flash kernel; the
        # rest (short buckets, odd head dims) use the plain reference
        use_flash = (bucket % 128 == 0 and
                     (cfg.head_dim * cfg.num_heads) % 8 == 0)

        def raw(params, kv: KVPages, tokens, n, page_row):
            # tokens: [bucket] i32 (padded); n: scalar i32 true length;
            # page_row: [Pm] i32 — this request's page table row.
            pos = jnp.arange(bucket, dtype=jnp.int32)
            x = model.embed(params, tokens[None], pos[None])   # [1, T, E]
            tmask = pos < n
            dest = jnp.where(tmask, page_row[pos // page], NULL_PAGE)
            offs = pos % page
            seg = jnp.where(tmask, 0, 1)[None].astype(jnp.int32)
            for l in range(cfg.num_layers):
                q, k, v = model.qkv(params, l, x)              # [1, T, H, D]
                kv = write_prompt(kv, l, k[0], v[0], dest, offs)
                if use_flash:
                    ctx = flash_attention(q, k, v, segment_ids=seg,
                                          causal=True)
                else:
                    ctx = mha_reference(q, k, v, segment_ids=seg,
                                        causal=True)
                x = model.attn_out(params, l, ctx, x)
            last = jnp.take(x[0], jnp.maximum(n - 1, 0), axis=0)
            return model.logits(params, last), kv

        fn = audit_jit(raw, site="serving.prefill",
                       donate_argnums=self._donate_kv)
        self._prefill_fns[bucket] = fn
        return fn

    def _chunk_fn(self, bucket: int):
        """Prefill one CHUNK of a prompt whose earlier tokens are already
        materialized in pages (a cached prefix, a COW-forked page, or
        previous chunks).  The chunk's K/V is scattered into its pages
        first, then attention runs over the request's whole gathered page
        row with an offset-causal mask — kv position ``t`` is visible to
        the query at absolute position ``start + i`` iff ``t <= start+i``
        — so prior context and in-chunk causality come from ONE masked
        attention, with no separate cross/self paths to keep in sync."""
        fn = self._chunk_fns.get(bucket)
        if fn is not None:
            return fn
        model, cfg = self.model, self.kv_cfg
        page, pm = cfg.page_size, cfg.max_pages_per_seq
        scale = float(cfg.head_dim) ** -0.5

        def raw(params, kv: KVPages, tokens, n, start, page_row):
            # tokens: [bucket] i32 (padded chunk); n: scalar i32 true
            # chunk length; start: scalar i32 absolute position of
            # tokens[0]; page_row: [Pm] i32 — this request's page table.
            pos = jnp.arange(bucket, dtype=jnp.int32)
            abs_pos = start + pos
            x = model.embed(params, tokens[None], abs_pos[None])  # [1,T,E]
            tmask = pos < n
            dest = jnp.where(tmask, page_row[abs_pos // page], NULL_PAGE)
            offs = abs_pos % page
            kv_pos = jnp.arange(pm * page, dtype=jnp.int32)
            mask = kv_pos[None, :] <= abs_pos[:, None]       # [T, Pm*page]
            # positions beyond this chunk's end hold garbage (stale page
            # contents, the null page): zero their gathered K/V rather
            # than trusting the mask alone — softmax gives them weight
            # exactly 0, but 0 * inf in the PV product would still be NaN
            valid = (kv_pos < start + n)[None, :, None, None]
            wmask = tmask[:, None, None]
            for l in range(cfg.num_layers):
                q, k, v = model.qkv(params, l, x)            # [1, T, H, D]
                # padded rows attend over REAL keys (no segment split
                # here), so their values can be junk: write zeros to the
                # shared null page, never computed junk
                kv = write_prompt(kv, l, jnp.where(wmask, k[0], 0.0),
                                  jnp.where(wmask, v[0], 0.0), dest, offs)
                kg, vg = gather_kv(kv, l, page_row[None])    # [1,Pm*pg,H,D]
                kg = jnp.where(valid, kg, 0.0)
                vg = jnp.where(valid, vg, 0.0)
                s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                               kg.astype(jnp.float32)) * scale
                s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
                p = jax.nn.softmax(s, axis=-1)
                ctx = jnp.einsum("bhqk,bkhd->bqhd", p,
                                 vg.astype(jnp.float32)).astype(q.dtype)
                x = model.attn_out(params, l, ctx, x)
            last = jnp.take(x[0], jnp.maximum(n - 1, 0), axis=0)
            return model.logits(params, last), kv

        fn = audit_jit(raw, site="serving.chunk_prefill",
                       donate_argnums=self._donate_kv)
        self._chunk_fns[bucket] = fn
        return fn

    # ---- user surface ----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_tokens: int,
               on_token: Optional[Callable[[int], None]] = None,
               now: Optional[float] = None,
               queue_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None) -> int:
        """Queue a request and return its rid — ALWAYS, even when the
        request is refused (infeasible size or queue backpressure): a
        refused rid carries status ``REJECTED``, so callers distinguish
        "rejected at submit" from "in flight" from "unknown rid" via
        ``status``/``result`` instead of a bare ``None`` sentinel.

        ``queue_deadline_s`` bounds time waiting for admission (engine
        default: ``FLAGS.serving_queue_deadline_s``); ``deadline_s``
        bounds submit-to-last-token.  Either lapsing marks the request
        ``TIMED_OUT`` and frees everything it held."""
        req = Request(prompt=list(int(t) for t in prompt),
                      max_tokens=int(max_tokens), on_token=on_token)
        t = self._time() if now is None else now
        if queue_deadline_s is None:
            # engine-wide default; self.queue_deadline_s is None when
            # the flag is 0 (the 0-means-off semantic lives on the FLAG,
            # not on the per-request parameters)
            queue_deadline_s = self.queue_deadline_s
        if queue_deadline_s is not None:
            req.queue_deadline_at = t + float(queue_deadline_s)
        if deadline_s is not None:
            req.deadline_at = t + float(deadline_s)
        # for BOTH per-request overrides, None = no deadline and an
        # explicit 0.0 is an already-spent budget (times out next tick)
        if self._draining:
            # drain mode: admission is closed.  The request is REJECTED
            # up front — queued and running work keeps going, but no new
            # demand enters (the fleet router reads this as "route
            # elsewhere").
            req.submitted_at = t
            req.status = RequestStatus.REJECTED
            ok = False
        else:
            ok = self.scheduler.submit(req, now=t)
        self.metrics.on_submit(t, ok)
        self._requests[req.rid] = req
        self._tracer.instant("submit", rid=req.rid, tokens=len(req.prompt),
                             max_tokens=req.max_tokens, accepted=ok)
        if not ok:
            self._retire(req)
        return req.rid

    def _finish(self, req: Request, status: RequestStatus, now: float,
                shed: bool = False) -> None:
        """THE terminal-transition path (every non-completed exit and
        completion itself funnel through here): return the slot and
        pages — or leave the queue — stamp, count, retire.  One copy of
        the invariant, so no path can forget eviction or a counter."""
        if status is RequestStatus.FAILED and req.pages:
            # a FAILED request may have written non-finite K/V; scrub
            # the suspect pages so re-granted ones can't leak inf into
            # the next owner's masked attention reads.  Suspect = the
            # request's UNCACHED pages: cached pages were finite-vouched
            # at insertion (a failing chunk's were just forgotten) and
            # may be shared right now — decode appends and failing
            # chunks only ever write uncached ones.
            suspect = [p for p in req.pages if not self.pool.is_cached(p)]
            if suspect:
                self._kv = self._zero_fn(self._kv,
                                         jnp.asarray(suspect, jnp.int32))
        if req.slot is not None:
            self.scheduler.release(req, status)
        else:
            self.scheduler.drop_queued(req, status)
        req.finished_at = now
        hook = self.metrics.on_shed if shed else {
            RequestStatus.COMPLETED: self.metrics.on_complete,
            RequestStatus.TIMED_OUT: self.metrics.on_timeout,
            RequestStatus.CANCELLED: self.metrics.on_cancel,
            RequestStatus.FAILED: self.metrics.on_fail,
        }[status]
        hook()
        if req.first_token_at is not None:
            self._observe_stage("decode", now - req.first_token_at)
        self._tracer.instant("terminal", rid=req.rid, status=str(status),
                             shed=shed, tokens=len(req.generated))
        self._retire(req)

    def _retire(self, req: Request) -> None:
        """Record a terminal transition; evict the oldest terminal
        requests (and their results) past ``max_retained`` so request
        history doesn't grow without bound on a long-running engine.
        ``status``/``result`` raise KeyError for evicted rids, same as
        never-issued ones."""
        self._retired.append(req.rid)
        while len(self._retired) > self.max_retained:
            old = self._retired.popleft()
            self._requests.pop(old, None)
            self._results.pop(old, None)

    def cancel(self, rid: int, now: Optional[float] = None) -> bool:
        """Cancel a request.  Queued/preempted requests leave the queue;
        a running one releases its slot and pages immediately (its page
        writes are garbage the next owner overwrites).  Returns False if
        the request already reached a terminal status; raises KeyError
        for an unknown rid."""
        req = self._requests[rid]
        if req.finished:
            return False
        now = self._time() if now is None else now
        self._finish(req, RequestStatus.CANCELLED, now)
        return True

    def status(self, rid: int) -> RequestStatus:
        """Lifecycle status of ``rid``; raises KeyError for a rid this
        engine never issued."""
        return self._requests[rid].status

    def drain(self, on: bool = True) -> None:
        """Toggle drain mode: while draining, every new ``submit`` is
        REJECTED immediately, but requests already queued or running
        finish normally (admission from the existing queue continues —
        the drain stops new DEMAND, not accepted work).  ``drain(False)``
        reopens admission (a replica rejoining a fleet)."""
        self._draining = bool(on)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def step(self, now: Optional[float] = None) -> bool:
        """One engine tick: shed expired/unmeetable work, grow/preempt,
        admit + prefill, one fused decode over all running sequences
        (with transient-error retry, finite-logits isolation, and the
        progress watchdog).  Returns True if any work remains."""
        tick, sched, m = self._tick, self.scheduler, self.metrics
        if self.faults is not None:
            self.faults.tick_begin(tick)
            self.faults.apply_page_pressure(tick, self.pool)
            self.faults.apply_cache_storm(tick, self.cache)
        now = self._time() if now is None else now
        # the shed estimator learns tick duration only from ticks that
        # followed a BUSY tick: in a continuous serving loop those run
        # back-to-back so the gap is compute time, while idle gaps (a
        # server polling step() with nothing in flight) would inflate
        # the EMA and shed whole bursts spuriously
        if (self._last_tick_at is not None and now > self._last_tick_at
                and self._prev_tick_busy):
            dur = now - self._last_tick_at
            self._tick_dur_ema = dur if self._tick_dur_ema == 0.0 else \
                0.5 * self._tick_dur_ema + 0.5 * dur
        self._last_tick_at = now
        self._enforce_deadlines(now)
        # growth/preemption BEFORE admission: a tick must not pay for a
        # new request's prefill and then immediately preempt it (the
        # youngest) to grow older sequences.  admit() reserves the first
        # decode append's page, so fresh admissions never need same-tick
        # growth either.
        m.on_preempt(len(sched.ensure_decode_pages()))
        admitted = sched.admit()
        for req in admitted:
            if req.admitted_at is None:
                # queue wait is a first-admission stat: re-admissions
                # after preemption would fold running time into it
                wait = now - (req.submitted_at
                              if req.submitted_at is not None else now)
                m.on_admit(wait)
                self._observe_stage("queue", wait)
                req.admitted_at = now
            req.last_progress_tick = tick
            self._tracer.instant("admit", rid=req.rid, slot=req.slot,
                                 cached=req.cached_len, tick=tick)
            self._begin_prefill(req)
        # ONE chunk per prefilling request per tick: a freshly-admitted
        # request takes its first chunk now, earlier admissions resume —
        # and the fused decode below still runs every tick, so a long
        # prefill no longer stalls running slots' inter-token latency
        prefilling = [r for r in sched.running_requests()
                      if r.status is RequestStatus.RUNNING and r.prefilling]
        for req in prefilling:
            with self._tracer.span("prefill_chunk", rid=req.rid,
                                   slot=req.slot, start=req.cache_len,
                                   tick=tick):
                self._prefill_step(req)
        running = [r for r in sched.running_requests()
                   if r.status is RequestStatus.RUNNING
                   and not r.prefilling and r.generated]
        if running:
            with self._tracer.span("decode_tick", tick=tick,
                                   n=len(running)):
                self._decode_with_retry(running, tick)
        self._prev_tick_busy = (bool(running) or bool(admitted) or
                                bool(prefilling))
        self._watchdog_sweep(tick)
        m.on_tick(sched.queue_depth, self.pool.num_live,
                  self.pool.num_cached,
                  self.cache.evictions if self.cache is not None else 0)
        self._tick = tick + 1
        return self.has_work

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, List[int]]:
        """Tick until drained (or ``max_ticks``); returns
        {rid: generated tokens} for everything completed so far.  A full
        drain releases any fault-plan page pressure and asserts free-list
        conservation (:class:`PageLeakError` on violation)."""
        ticks = 0
        while self.has_work:
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        if not self.has_work:
            if self.faults is not None:
                self.faults.release_pressure(self.pool)
            self.check_page_conservation()
        return dict(self._results)

    def result(self, rid: int) -> Optional[List[int]]:
        """Generated tokens for a COMPLETED rid; None while the request
        is in flight or if it ended in a non-completed terminal status
        (disambiguate via ``status``); KeyError for a rid the engine
        never issued or already evicted past ``max_retained``."""
        if rid not in self._requests:
            raise KeyError(rid)
        return self._results.get(rid)

    # ---- invariants / health --------------------------------------------

    def check_page_conservation(self) -> None:
        """Two-part conservation (raises :class:`PageLeakError`, whose
        message carries a grep-able token either way):

        - ``PAGE-LEAK`` — every usable page is either on the free list
          or tracked in use (live or cached-reclaimable);
        - ``REF-LEAK`` — the pool's total refcount equals the references
          actually held: one per page-table entry of every running or
          queued request, one per fault-plan pressure page.  Cached
          pages parked at refcount 0 hold none, so sharing, COW forks,
          preemption-unref and eviction all have to balance exactly."""
        pool = self.pool
        if pool.num_free + pool.num_in_use != pool.num_usable:
            # flight recorder: the leak report ships WITH the event
            # history that produced it (no-op when tracing is off)
            self._dump_postmortem("PAGE-LEAK")
            raise PageLeakError(
                f"PAGE-LEAK: free={pool.num_free} in_use={pool.num_in_use} "
                f"usable={pool.num_usable}")
        live = (list(self.scheduler.running.values()) +
                list(self.scheduler.queue))
        held = sum(len(r.pages) for r in live)
        # an admission-time COW pin (fork source awaiting the copy) is a
        # held reference too, until the engine's fork consumes it
        held += sum(1 for r in live if r.cow_src is not None)
        if self.faults is not None:
            held += len(self.faults.held_pages)
        if held != pool.total_refs:
            self._dump_postmortem("REF-LEAK")
            raise PageLeakError(
                f"REF-LEAK: held={held} refs={pool.total_refs} "
                f"cached={pool.num_cached} free={pool.num_free} "
                f"usable={pool.num_usable}")

    def load(self) -> Dict[str, object]:
        """Cheap load probe: the same queue_depth / running /
        free_pages numbers ``healthz`` reports, WITHOUT the
        conservation scan healthz pays for its ``ok`` bit.  The fleet
        router reads this once per candidate replica per submit, so it
        must stay O(1); ``healthz`` remains the full diagnostic for
        external probers."""
        return {"queue_depth": self.scheduler.queue_depth,
                "running": len(self.scheduler.running),
                "free_pages": self.pool.num_free,
                "draining": self._draining}

    def healthz(self) -> Dict[str, object]:
        """One-call liveness snapshot for an external prober.  O(live
        requests), not O(history): terminal counts come from the metrics
        counters, live states from the bounded queue/slot scans."""
        m = self.metrics
        counts: Dict[str, int] = {}
        for key, val in (("completed", m.completed),
                         ("timed_out", m.timed_out),
                         ("cancelled", m.cancelled),
                         ("failed", m.failed),
                         ("rejected", m.rejected + m.shed)):
            if val:
                counts[key] = val
        for req in (list(self.scheduler.queue) +
                    list(self.scheduler.running.values())):
            counts[req.status.value] = counts.get(req.status.value, 0) + 1
        try:
            self.check_page_conservation()
            leak = False
        except PageLeakError:
            leak = True
        # the unified-registry surface: publish this engine's counters,
        # then hand back the registry's flat snapshot so one healthz
        # probe reads the same numbers a scraper would
        self.metrics.publish(self.registry, **self._reg_labels)
        return {
            "ok": not leak,
            "metrics": self.registry.snapshot(),
            "tick": self._tick,
            "queue_depth": self.scheduler.queue_depth,
            "running": len(self.scheduler.running),
            "draining": self._draining,
            # first-class load signals for a fleet router's balancing /
            # overflow decision (queue_depth above + free_pages here):
            # admission headroom without reaching into pool internals.
            # pages_free stays as the historical alias.
            "free_pages": self.pool.num_free,
            "pages_free": self.pool.num_free,
            # in_use = live sequence holders; cached/reclaimable pages
            # are reported separately so a prober can assert the cache
            # drains to steady state (live 0, cached >= 0 all evictable)
            "pages_in_use": self.pool.num_live,
            "pages_cached": self.pool.num_cached,
            "pages_reclaimable": self.pool.num_reclaimable,
            # `is not None`, not truthiness: PrefixCache defines __len__,
            # so an empty-but-active cache is falsy
            "cache_hits": self.cache.hits if self.cache is not None else 0,
            "cache_misses": (self.cache.misses
                             if self.cache is not None else 0),
            "page_leak": leak,
            "status_counts": counts,
            "deadline_miss_rate": round(self.metrics.deadline_miss_rate(),
                                        4),
        }

    # ---- internals -------------------------------------------------------

    def _enforce_deadlines(self, now: float) -> None:
        sched = self.scheduler
        # running requests past their total deadline: free immediately
        for req in list(sched.running.values()):
            if req.deadline_at is not None and now >= req.deadline_at:
                self._finish(req, RequestStatus.TIMED_OUT, now)
        for req in sched.queued_requests():
            # the queue deadline is an ADMISSION SLO: once a request has
            # been admitted it is satisfied forever — a preempted request
            # back in the queue is judged only by its total deadline
            expired = (req.deadline_at is not None and
                       now >= req.deadline_at) or \
                      (req.admitted_at is None and
                       req.queue_deadline_at is not None and
                       now >= req.queue_deadline_at)
            if expired:
                self._finish(req, RequestStatus.TIMED_OUT, now)
                continue
            # load shedding, on the WORST-CASE length assumption: at one
            # token per tick (the engine's best rate), a request that
            # runs to its full max_tokens cannot finish by its deadline.
            # An early EOS could beat the estimate — callers who rely on
            # early stopping should size max_tokens to what they
            # actually expect, since it is the only length signal the
            # engine has before decoding.
            if (req.deadline_at is not None and self._tick_dur_ema > 0.0
                    and now + req.tokens_remaining * self._tick_dur_ema
                    > req.deadline_at):
                self._finish(req, RequestStatus.REJECTED, now, shed=True)

    def _decode_with_retry(self, running: List[Request], tick: int) -> None:
        attempt = 0
        while True:
            try:
                if self.faults is not None and \
                        self.faults.decode_should_fail(tick, attempt):
                    raise InjectedDeviceError(f"injected @ tick {tick} "
                                              f"attempt {attempt}")
                self._do_decode(running)
                return
            except self.transient_errors:
                attempt += 1
                if attempt > self.decode_retries:
                    return   # tick lost; the watchdog counts the stall
                self.metrics.on_retry()

    def _watchdog_sweep(self, tick: int) -> None:
        if self.watchdog_ticks <= 0:
            return
        sched = self.scheduler
        for req in list(sched.running.values()):
            if tick - req.last_progress_tick >= self.watchdog_ticks:
                self._finish(req, RequestStatus.FAILED, self._time())

    def _begin_prefill(self, req: Request) -> None:
        """Stitch-time work for a newly (re-)admitted request: record
        the prefix-cache outcome, run the COW fork, and arm the chunked
        prefill (its first chunk runs this same tick)."""
        toks = req.cache_tokens
        req.prefilling = True
        req.chain_hash, req.chain_blocks = None, 0   # fresh insert cursor
        self.metrics.on_prefix(len(toks), req.cached_len)
        if req.cow_src is not None:
            # full-cover hit: the tail's only token rewrites a position
            # INSIDE the last shared page, so fork it into the request's
            # first private page before anything is written
            dst = req.pages[req.cache_len // self.kv_cfg.page_size]
            self._kv = self._fork_fn(self._kv,
                                     jnp.asarray(req.cow_src, jnp.int32),
                                     jnp.asarray(dst, jnp.int32))
            # the fork consumed the source: drop the admission-time pin
            # that kept it from being evicted before the copy ran
            self.pool.free([req.cow_src])
            req.cow_src = None
            self.metrics.on_cow()

    def _prefill_step(self, req: Request) -> None:
        """Advance one prefill chunk — or the whole prompt on the
        single-shot fast path (no cached prefix, fits in one chunk).  On
        the final chunk the last position's logits emit the first token
        and the request joins the fused decode batch.

        Every chunk's logits go through the finite guard BEFORE its full
        pages are indexed (a chunk's last-position logits attend over
        every K/V written so far, so finiteness transitively vouches for
        the whole chain): without the per-chunk check, suspect K/V from
        an overflowing prompt would be hittable for the whole multi-tick
        prefill window, and a sharer admitted in that window would
        stitch it before the final-chunk rollback ran.  The sync this
        costs is one host readback per chunk — the tick already pays one
        for decode."""
        toks = req.cache_tokens
        total = len(toks)
        start = req.cache_len
        chunk = self._prefill_chunk
        cfg = self.kv_cfg
        row = np.full((cfg.max_pages_per_seq,), NULL_PAGE, np.int32)
        row[:len(req.pages)] = req.pages
        if start == 0 and (chunk <= 0 or total <= chunk):
            # fast path: one-shot bucketed prefill (flash when shaped)
            bucket = bucket_for(total, self._buckets, cfg.max_seq_len)
            padded = np.zeros((bucket,), np.int32)
            padded[:total] = toks
            logits, self._kv = self._prefill_fn(bucket)(
                self.params, self._kv, jnp.asarray(padded),
                jnp.asarray(total, jnp.int32), jnp.asarray(row))
            req.cache_len = total
            self.metrics.on_prefill(total)
        else:
            end = total if chunk <= 0 else min(total, start + chunk)
            n = end - start
            bucket = bucket_for(n, self._buckets, cfg.max_seq_len)
            padded = np.zeros((bucket,), np.int32)
            padded[:n] = toks[start:end]
            logits, self._kv = self._chunk_fn(bucket)(
                self.params, self._kv, jnp.asarray(padded),
                jnp.asarray(n, jnp.int32), jnp.asarray(start, jnp.int32),
                jnp.asarray(row))
            req.cache_len = end
            self.metrics.on_prefill(n)
        req.last_progress_tick = self._tick   # chunks are progress too
        logits = np.asarray(logits)   # forces device sync
        # stamp AFTER the sync so TTFT includes the prefill compute
        now = self._time()
        if not np.isfinite(logits).all():
            if self.cache is not None:
                # roll back entries ONLY for pages the FAILING chunk
                # wrote (from the pre-chunk position onward): earlier
                # chunks passed their own finite guard and their cached
                # pages may already be stitched by a concurrent sharer —
                # forgetting them would route them into the FAILED scrub
                # below and zero-wipe K/V the sharer is reading
                self.cache.forget(req.pages[start // cfg.page_size:])
            req.prefilling = False
            self._finish(req, RequestStatus.FAILED, now)
            return
        if self.cache is not None:
            # newly-completed FULL pages — now finite-vouched — become
            # hittable immediately, so even a preempted or mid-prefill
            # prompt re-prefills cheaply.  The chain cursor makes each
            # chunk's insert O(chunk), not O(prefix-so-far).
            req.chain_hash, req.chain_blocks = self.cache.insert(
                toks, req.pages, req.cache_len,
                from_block=req.chain_blocks, prev_hash=req.chain_hash)
        if req.cache_len < total:
            return                            # more chunks, later ticks
        req.prefilling = False
        self._emit(req, int(np.argmax(logits)), now)

    def _do_decode(self, running: List[Request]) -> None:
        b = self._max_slots
        cfg = self.kv_cfg
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        lens = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        table = np.full((b, cfg.max_pages_per_seq), NULL_PAGE, np.int32)
        for req in running:
            s = req.slot
            tokens[s] = req.generated[-1]
            positions[s] = req.cache_len
            lens[s] = req.cache_len
            active[s] = True
            table[s, :len(req.pages)] = req.pages
        logits, self._kv = self._decode_fn(
            self.params, self._kv, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(table), jnp.asarray(lens),
            jnp.asarray(active))
        logits = np.asarray(logits)   # forces device sync
        if self.faults is not None and self.faults.nan_rids:
            poisoned = [r for r in running
                        if r.rid in self.faults.nan_rids]
            if poisoned:              # only then pay for a writable copy
                logits = logits.copy()
                for req in poisoned:
                    logits[req.slot] = np.nan
        now = self._time()            # emission time includes the compute
        for req in running:
            if req.status is not RequestStatus.RUNNING:
                continue    # cancelled from another slot's on_token
            row = logits[req.slot]
            if not np.isfinite(row).all():
                # poisoned slot: fail ONLY this request — its pages go
                # back, the fused batchmates keep decoding untouched
                self._finish(req, RequestStatus.FAILED, now)
                continue
            req.cache_len += 1
            self._emit(req, int(np.argmax(row)), now)

    def _emit(self, req: Request, tok: int, now: float) -> None:
        req.generated.append(tok)
        req.last_progress_tick = self._tick
        ttft = None
        if req.first_token_at is None:
            req.first_token_at = now
            ttft = max(0.0, now - (req.submitted_at
                                   if req.submitted_at is not None else now))
            self._observe_stage("prefill", now - (
                req.admitted_at if req.admitted_at is not None else now))
            self._tracer.instant("first_token", rid=req.rid, slot=req.slot)
        self.metrics.on_token(now, ttft)
        if req.on_token is not None:
            req.on_token(tok)
            if req.finished:
                return   # the callback cancelled this request: keep it
        if tok == self.eos_id or len(req.generated) >= req.max_tokens:
            self._results[req.rid] = list(req.generated)
            self._finish(req, RequestStatus.COMPLETED, now)
