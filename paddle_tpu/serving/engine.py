"""ServingEngine: the user-facing paged-KV continuous-batching API.

Usage::

    model = DecoderLM(vocab_size=512, num_layers=2, num_heads=2,
                      head_dim=16)
    params = model.init_params(jax.random.PRNGKey(0))
    eng = ServingEngine(model, params, eos_id=1, page_size=16,
                        num_pages=96, max_pages_per_seq=8, max_slots=8)
    rid = eng.submit([7, 12, 3], max_tokens=32, deadline_s=2.0,
                     on_token=lambda tok: print(tok))
    results = eng.run()          # {rid: [generated tokens...]}
    eng.status(rid)              # RequestStatus.COMPLETED
    eng.metrics.snapshot()       # tokens/s, TTFT, SLO counters, ...
    eng.healthz()                # liveness/conservation snapshot

The engine owns exactly ONE compiled tick function family (round 12):
the **unified step**, jitted once per ``(decode_bucket,
prefill_bucket)`` pair — the decode bucket is the fixed ``max_slots``
row count, the prefill bucket the padded total of this tick's packed
prefill-chunk rows (0 on decode-only ticks).  One dispatch embeds the
tick's decode tokens AND every in-flight prefill chunk, scatters all
their K/V into pages (quantizing on write when the pool is int8 — see
``FLAGS.serving_kv_dtype``), and runs ONE ragged paged attention
(``ragged_paged_attention``: sequence-packed rows, GQA head-group
packing, in-register dequant) over the whole mixed batch — where the
v1 engine paid two dispatches and two softmax passes per tick with
in-flight prefill.  ``fuse_tick=False`` keeps the v1 two-dispatch
shape as a bench control (same math, token-identical).

Decoding is greedy (argmax) by default — the deterministic contract
the parity tests pin.  ``submit(..., sampling=SamplingParams(...))``
turns on real sampling (temperature/top-k/top-p with seeded
per-position RNG streams, bit-reproducible across replays), and
``spec_mode="ngram"|"draft"`` (round 18) turns on speculative
decoding: a proposer drafts up to ``spec_k`` tokens per slot per
tick, the SAME unified step verifies all ``k+1`` positions per slot
(the jit ladder gains the ``k`` dimension: one compile per
``(prefill_bucket, k+1)`` pair), the longest agreeing prefix is
accepted — greedy stays token-identical to the oracle — and rejected
tokens roll back via COW-guarded page forks plus
``scheduler.rollback_pages``, so speculation composes with prefix
caching without ever dirtying a shared page.

Robustness layer (round 8): every request moves through a real
:class:`RequestStatus` lifecycle with optional queue/total deadlines and
``cancel(rid)``; timed-out and cancelled requests release their slot and
pages immediately.  The decode tick carries a finite-logits guard that
fails ONLY the poisoned slot (the rest of the fused batch keeps
running), retries transiently-failing ticks, and a progress watchdog
fails slots stuck past ``serving_watchdog_ticks``.  Deadlocked demand is
shed: queued requests whose deadline is provably unmeetable are
early-rejected instead of burning prefill work.  All failure paths are
driven deterministically by a :class:`~paddle_tpu.serving.faults.FaultPlan`
(injectable clock, decode-step errors, NaN logits, page pressure) and a
free-list conservation check runs after every drain.

Prefix caching + chunked prefill (round 9): with
``FLAGS.serving_prefix_cache`` on (the default), admission splits every
prompt into ``cached_prefix_pages + tail`` against a chained-hash
:class:`~paddle_tpu.serving.kv_cache.PrefixCache` — the prefix pages are
refcount-shared (charged zero new pages), the tail prefills with its
positions offset by the cached length, and a full-cover hit
copy-on-write-forks the last shared page and recomputes only the final
token.  Prompts longer than ``FLAGS.serving_prefill_chunk`` prefill one
chunk per tick — since round 12 riding the SAME unified dispatch as the
decode rows rather than a second one — so a long prompt in the queue
no longer degrades running slots' latency.

Tensor-parallel serving (round 13): ``ServingEngine(mesh=, tp_axis=)``
places the model megatron-style over a ``model`` mesh axis — attention
heads (and GQA KV heads) + FFN columns column-parallel, the output/FFN-
down projections row-parallel with ONE psum each per layer — using the
model's ``shard_plan()`` as the single placement source of truth, and
the paged pool shards its KV-head dim the same way
(``[L, pages, page, H_kv/TP, D]``, int8 scales riding along), so every
pool byte number becomes per-chip and the same budget admits tp x the
pages.  The unified step, chunk prefill, ``fork_page``/``zero_pages``
and the decode kernel (via ``shard_map``) all run on the sharded
layout; the flipped :class:`SiteContract`s carry the closed-form psum
budget so ``python -m paddle_tpu.analysis sharding`` proves the decode
hot path stays reduce-not-gather.  ``mesh=None`` keeps the exact
replicated engine (and the exact PR 10 ``P()``/comm=0 contracts).

The model plugs in through the small :class:`DecodeModel` contract
rather than a ``Topology``: serving needs per-layer access to Q/K/V
*before* attention runs (the cache sits between them), which the opaque
layer graph doesn't expose.  :class:`DecoderLM` is the built-in
reference implementation (and the bench model); any object with the same
methods works, so a topology-built transformer can be adapted by
exposing its projection weights.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.analysis.retrace import SiteContract, audit_jit, auditor
from paddle_tpu.obs.registry import MetricsRegistry
from paddle_tpu.obs.trace import NULL_TRACER, tracer_for
from paddle_tpu.ops.attention import mha_reference
from paddle_tpu.platform.flags import FLAGS
from paddle_tpu.serving.decode_attention import (
    BLOCK_ROWS, _ragged_reference_blocked, attention_path,
    expand_decode_rows, ragged_paged_attention, ragged_paged_attention_tp)
from paddle_tpu.serving.faults import (FaultPlan, InjectedDeviceError,
                                       PageLeakError)
from paddle_tpu.serving.kv_cache import (NULL_PAGE, _CHAIN_SEED, HostPageTier,
                                         KVPages, PagedKVConfig, PagePool,
                                         PrefixCache, append_token,
                                         dequantize_kv, fork_page,
                                         init_kv_pages, kv_pool_specs,
                                         pages_for_budget, pages_spanned,
                                         read_pages, resolve_kv_dtype,
                                         write_pages, zero_pages)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.speculate import (DraftProposer, NGramProposer,
                                          SamplingParams, accept_tokens,
                                          next_token)
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Request, RequestStatus,
                                          SchedulerConfig, bucket_for,
                                          pack_prefill_chunks)

__all__ = ["DecodeModel", "DecoderLM", "SamplingParams", "ServingEngine",
           "greedy_decode_reference", "validate_tp"]

_SPEC_MODES = ("off", "ngram", "draft")


class DecodeModel:
    """Structural contract the engine drives (duck-typed; subclassing is
    optional).  All methods must be jax-traceable and shape-polymorphic
    over leading batch/sequence dims:

    - ``num_layers``, ``num_heads``, ``head_dim``, ``vocab_size``
    - ``num_kv_heads`` (optional, defaults to ``num_heads``): GQA — K/V
      carry this many heads (``<= num_heads``, dividing it); query head
      ``h`` reads KV head ``h // (num_heads // num_kv_heads)``.  The
      paged pool stores KV heads only and the ragged kernel loads each
      K/V page once per head GROUP instead of once per query head.
    - ``embed(params, tokens, positions) -> [..., E]``
    - ``qkv(params, layer, x) -> (q, k, v)`` — q ``[..., H, D]``, k/v
      ``[..., H_kv, D]``
    - ``attn_out(params, layer, ctx, x) -> [..., E]`` — attention output
      ``ctx`` [..., H, D] combined with the residual stream ``x``
      (projection, residual, FFN — whatever the architecture does after
      attention)
    - ``logits(params, x) -> [..., vocab_size]``

    Tensor-parallel serving (``ServingEngine(mesh=...)``) additionally
    needs:

    - ``shard_plan(axis="model", tp=None) -> {param name: per-dim
      PartitionSpec tuple}`` — the megatron placement (attention heads +
      FFN columns over ``axis``, row-parallel down projections); and
    - ``bind_tp(mesh, axis) -> model`` (optional) — return a TP-bound
      VIEW of the model whose forward asserts the plan's activation
      shardings (sharding constraints after each projection) so the
      row-parallel blocks lower to exactly one psum each.  Must NOT
      mutate ``self``: the same model object may back a replicated
      engine in the same process (the A/B benches do exactly that).
    """

    num_layers: int
    num_heads: int
    head_dim: int
    vocab_size: int
    num_kv_heads: int  # optional on duck-typed models (= num_heads)


def validate_tp(model: "DecodeModel", tp: int, axis: str = "model") -> None:
    """Fail fast — with a fix in the message — on a model whose
    geometry cannot split ``tp`` ways over ``axis``: attention sharding
    moves whole query/KV heads per chip and FFN sharding whole columns,
    so every one of those counts must divide.  Checked at BOTH
    ``ServingEngine(mesh=...)`` construction and ``shard_plan()``, so a
    bad plan can't reach placement from either direction."""
    from paddle_tpu.platform.enforce import enforce_that

    tp = int(tp)
    enforce_that(tp >= 1, f"tensor-parallel degree must be >= 1, got {tp}",
                 context="serving-tp")
    if tp == 1:
        return
    h = int(model.num_heads)
    kvh = int(getattr(model, "num_kv_heads", 0) or h)
    enforce_that(
        h % tp == 0,
        f"num_heads ({h}) is not divisible by the {axis!r} mesh axis "
        f"size ({tp}): tensor parallelism places whole attention heads "
        f"per chip — pick a tp that divides {h}, or resize the model",
        context="serving-tp")
    enforce_that(
        tp <= kvh,
        f"GQA corner: tp={tp} exceeds num_kv_heads ({kvh}) — a KV head "
        "cannot split below one per chip and this engine does not "
        f"replicate KV heads across the {axis!r} axis; lower tp to at "
        f"most {kvh}, or serve a model with more KV heads",
        context="serving-tp")
    enforce_that(
        kvh % tp == 0,
        f"num_kv_heads ({kvh}) is not divisible by the {axis!r} mesh "
        f"axis size ({tp}): the paged KV pool shards whole KV heads per "
        f"chip — pick a tp that divides {kvh}", context="serving-tp")
    ffn = int(getattr(model, "ffn_dim", 0) or 0)
    if ffn:
        enforce_that(
            ffn % tp == 0,
            f"FFN width ({ffn}) is not divisible by the {axis!r} mesh "
            f"axis size ({tp}): the column-parallel up projection places "
            f"whole FFN columns per chip — pick a tp that divides {ffn}",
            context="serving-tp")


def _rms(x, eps: float = 1e-6):
    return x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1,
                                      keepdims=True) + eps)


class DecoderLM(DecodeModel):
    """A compact pre-norm decoder-only transformer LM implementing the
    :class:`DecodeModel` contract — the built-in serving/bench model.
    Parameter-free RMSNorm keeps the param dict to embeddings +
    projections."""

    def __init__(self, vocab_size: int, num_layers: int = 2,
                 num_heads: int = 2, head_dim: int = 16,
                 ffn_mult: int = 4, max_positions: int = 1024,
                 num_kv_heads: Optional[int] = None):
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = int(num_kv_heads or num_heads)
        if num_heads % self.num_kv_heads != 0:
            raise ValueError(f"num_kv_heads ({self.num_kv_heads}) must "
                             f"divide num_heads ({num_heads})")
        self.head_dim = head_dim
        self.embed_dim = num_heads * head_dim
        self.kv_dim = self.num_kv_heads * head_dim
        self.ffn_dim = ffn_mult * self.embed_dim
        self.max_positions = max_positions
        # tensor-parallel binding (None = unbound; see bind_tp)
        self._tp_mesh = None
        self._tp_axis = None

    # ---- tensor-parallel placement (the megatron plan) -------------------

    def shard_plan(self, axis: str = "model",
                   tp: Optional[int] = None) -> Dict[str, Tuple]:
        """Megatron-style tensor-parallel placement over ``axis``:
        Q/K/V and FFN-up projections are COLUMN-parallel (output
        features — i.e. heads / FFN columns — sharded, no collective on
        the forward matmul); the attention-output and FFN-down
        projections are ROW-parallel (input features sharded, the
        contraction emits ONE psum per block); embeddings, positions
        and the vocab head stay replicated.  Returns ``{param name:
        per-dim PartitionSpec tuple}`` — the single source of truth the
        engine turns into ``NamedSharding``s, the ZeRO composition
        turns into explicit ``ParamAttr.sharding``s, and the serving
        :class:`~paddle_tpu.analysis.retrace.SiteContract` declares to
        the sharding auditor.  ``tp`` (when given) validates
        divisibility up front with actionable errors."""
        if tp is not None:
            validate_tp(self, tp, axis)
        plan: Dict[str, Tuple] = {"emb": (), "pos": (), "out": ()}
        for l in range(self.num_layers):
            plan[f"l{l}.wq"] = (None, axis)
            plan[f"l{l}.wk"] = (None, axis)
            plan[f"l{l}.wv"] = (None, axis)
            plan[f"l{l}.wo"] = (axis, None)
            plan[f"l{l}.w1"] = (None, axis)
            plan[f"l{l}.w2"] = (axis, None)
        return plan

    def bind_tp(self, mesh, axis: str = "model") -> "DecoderLM":
        """Return a TP-bound VIEW of this model: same config, but the
        forward asserts the plan's activation placements with sharding
        constraints — heads sharded after Q/K/V, FFN columns sharded
        after the up projection, and an explicit replicated constraint
        after each ROW-parallel matmul, which is the megatron ``g``:
        GSPMD lowers it to exactly one psum per block instead of
        deferring partial sums into the nonlinearities.  ``self`` is
        NOT mutated — the unbound original can keep backing a
        replicated engine in the same process."""
        import copy

        m = copy.copy(self)
        m._tp_mesh, m._tp_axis = mesh, axis
        return m

    def _tp_sharded(self, x, dim_from_last: int):
        """Constrain ``x`` sharded over the TP axis on the dim
        ``dim_from_last`` positions from the end (no-op unbound)."""
        if self._tp_mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        dims = [None] * x.ndim
        dims[x.ndim - 1 - dim_from_last] = self._tp_axis
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self._tp_mesh, P(*dims)))

    def _tp_psum(self, x):
        """The megatron ``g`` after a row-parallel matmul: constrain
        the partial-sum output replicated, forcing the one psum per
        block (no-op unbound)."""
        if self._tp_mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self._tp_mesh, P()))

    def init_params(self, key) -> Dict[str, jax.Array]:
        e, f, v = self.embed_dim, self.ffn_dim, self.vocab_size
        kv = self.kv_dim
        keys = jax.random.split(key, 2 + 6 * self.num_layers + 1)
        ki = iter(keys)

        def mat(shape, scale):
            return (jax.random.normal(next(ki), shape, jnp.float32) * scale)

        p = {"emb": mat((v, e), 0.02), "pos": mat((self.max_positions, e),
                                                  0.02)}
        for l in range(self.num_layers):
            p[f"l{l}.wq"] = mat((e, e), e ** -0.5)
            p[f"l{l}.wk"] = mat((e, kv), e ** -0.5)
            p[f"l{l}.wv"] = mat((e, kv), e ** -0.5)
            p[f"l{l}.wo"] = mat((e, e), e ** -0.5)
            p[f"l{l}.w1"] = mat((e, f), e ** -0.5)
            p[f"l{l}.w2"] = mat((f, e), f ** -0.5)
        p["out"] = mat((e, v), e ** -0.5)
        return p

    def embed(self, params, tokens, positions):
        return params["emb"][tokens] + params["pos"][positions]

    def qkv(self, params, layer, x):
        h, kvh, d = self.num_heads, self.num_kv_heads, self.head_dim
        xn = _rms(x)
        q = (xn @ params[f"l{layer}.wq"]).reshape(x.shape[:-1] + (h, d))
        k = (xn @ params[f"l{layer}.wk"]).reshape(x.shape[:-1] + (kvh, d))
        v = (xn @ params[f"l{layer}.wv"]).reshape(x.shape[:-1] + (kvh, d))
        # TP: heads live sharded over the model axis (no-ops unbound)
        return (self._tp_sharded(q, 1), self._tp_sharded(k, 1),
                self._tp_sharded(v, 1))

    def attn_out(self, params, layer, ctx, x):
        flat = ctx.reshape(x.shape[:-1] + (self.embed_dim,))
        # row-parallel output projection: contraction over the sharded
        # feature dim -> partial sums -> ONE psum (the _tp_psum
        # constraint), then the replicated residual add
        a = x + self._tp_psum(flat @ params[f"l{layer}.wo"])
        up = self._tp_sharded(_rms(a) @ params[f"l{layer}.w1"], 0)
        # row-parallel FFN-down projection: the block's second psum
        return a + self._tp_psum(jax.nn.gelu(up) @ params[f"l{layer}.w2"])

    def logits(self, params, x):
        return _rms(x) @ params["out"]


def greedy_decode_reference(model: DecodeModel, params, prompt: List[int],
                            max_tokens: int, eos_id: int) -> List[int]:
    """The NON-paged oracle: a host loop that re-runs the full causal
    forward over the whole history each step (``mha_reference``, no KV
    cache at all) and greedily extends.  Slow by construction — it
    exists as the parity target for the engine's paged path."""
    tokens = list(prompt)
    out: List[int] = []
    for _ in range(max_tokens):
        # per-step host syncs are the POINT of this oracle: it trades
        # throughput for an unarguable reference trajectory
        t = jnp.asarray(tokens, jnp.int32)[None]   # lint: allow(host-sync)
        pos = jnp.arange(len(tokens), dtype=jnp.int32)[None]
        x = model.embed(params, t, pos)
        for l in range(model.num_layers):
            q, k, v = model.qkv(params, l, x)
            ctx = mha_reference(q, k, v, causal=True)
            x = model.attn_out(params, l, ctx, x)
        nxt = int(jnp.argmax(model.logits(params, x[0, -1])))  # lint: allow(host-sync)
        out.append(nxt)
        tokens.append(nxt)
        if nxt == eos_id:
            break
    return out


def _parse_buckets(spec: str) -> Tuple[int, ...]:
    return tuple(sorted(int(t) for t in spec.split(",") if t.strip()))


class ServingEngine:
    """Paged-KV continuous-batching inference engine (see module doc)."""

    def __init__(self, model: DecodeModel, params, *, eos_id: int,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_pages_per_seq: Optional[int] = None,
                 max_slots: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None,
                 dtype=None, kv_dtype=None,
                 pool_bytes: Optional[int] = None,
                 fuse_tick: bool = True,
                 use_kernel: Optional[bool] = None,
                 queue_deadline_s: Optional[float] = None,
                 preempt_budget: Optional[int] = None,
                 watchdog_ticks: Optional[int] = None,
                 decode_retries: int = 2,
                 transient_errors: Tuple[type, ...] = (InjectedDeviceError,),
                 max_retained: int = 10000,
                 prefix_cache: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 faults: Optional[FaultPlan] = None,
                 time_fn: Optional[Callable[[], float]] = None,
                 tracer=None, registry: Optional[MetricsRegistry] = None,
                 mesh=None, tp_axis: str = "model",
                 spec_mode: Optional[str] = None,
                 spec_k: Optional[int] = None,
                 spec_ngram: Optional[int] = None,
                 draft_model=None, draft_params=None,
                 draft_pool_pages: Optional[int] = None,
                 xla_peak_bytes: Optional[int] = None,
                 xla_flops: Optional[float] = None,
                 xla_comm_bytes: Optional[float] = None,
                 role: str = "unified",
                 host_tier_bytes: Optional[int] = None,
                 swap_in_budget: Optional[int] = None,
                 host_kv_dtype: Optional[str] = None):
        from paddle_tpu.platform.enforce import enforce_that

        self.eos_id = int(eos_id)
        # fleet class (round 16): "prefill" replicas hand requests off to
        # "decode" replicas after the first token via the page-migration
        # plane (serving/migrate.py); "unified" runs both phases.  The
        # engine itself treats every role identically — the role is an
        # advertised routing attribute the FleetRouter reads.
        self.role = str(role)
        enforce_that(self.role in ("prefill", "decode", "unified"),
                     f"role must be prefill/decode/unified, got {role!r}",
                     context="serving")
        page_size = int(page_size or FLAGS.serving_page_size)
        max_slots = int(max_slots or FLAGS.serving_max_slots)
        # KV storage dtype: explicit kv_dtype > legacy dtype param >
        # FLAGS.serving_kv_dtype.  int8 turns on quantized pages.
        if kv_dtype is None:
            kv_dtype = dtype if dtype is not None else FLAGS.serving_kv_dtype
        kv_dtype = resolve_kv_dtype(kv_dtype)
        num_kv_heads = int(getattr(model, "num_kv_heads", 0)
                           or model.num_heads)
        # tensor-parallel placement (ROADMAP item 1): with a mesh, the
        # megatron shard_plan places attention heads + FFN columns over
        # the `model` axis, the paged pool shards its KV-head dim the
        # same way, and every byte/contract below becomes per-chip.
        self.mesh = mesh
        self.tp_axis = str(tp_axis)
        self.tp = 1
        self._shard_plan: Optional[Dict[str, Tuple]] = None
        self.param_sharding = None
        if mesh is not None:
            enforce_that(
                self.tp_axis in mesh.axis_names,
                f"mesh has no {self.tp_axis!r} axis (axes: "
                f"{tuple(mesh.axis_names)}) — build one with "
                "make_mesh((tp,), ('model',))", context="serving-tp")
            self.tp = int(mesh.shape[self.tp_axis])
            validate_tp(model, self.tp, self.tp_axis)
            enforce_that(
                hasattr(model, "shard_plan"),
                "ServingEngine(mesh=...) needs the model to expose "
                "shard_plan(axis, tp) (see the DecodeModel contract); "
                f"{type(model).__name__} does not", context="serving-tp")
            enforce_that(
                isinstance(params, dict),
                "tensor-parallel placement needs a flat {name: array} "
                "param dict (the shard_plan key space)",
                context="serving-tp")
            self._shard_plan = {k: tuple(v) for k, v in
                                model.shard_plan(axis=self.tp_axis,
                                                 tp=self.tp).items()}
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.param_sharding = {
                name: NamedSharding(mesh,
                                    P(*self._shard_plan.get(name, ())))
                for name in params}
            params = {name: jax.device_put(v, self.param_sharding[name])
                      for name, v in params.items()}
            if hasattr(model, "bind_tp"):
                # a TP-bound VIEW (bind_tp must not mutate): the bound
                # forward asserts the activation shardings, so each
                # row-parallel block lowers to exactly one psum
                model = model.bind_tp(mesh, self.tp_axis)
        self.model = model
        self.params = params
        if num_pages is None and pool_bytes is not None:
            # size the pool by BYTES — PER CHIP: smaller KV dtypes admit
            # proportionally more pages, and tensor parallelism tp x
            # more again (each chip stores 1/tp of every page's KV
            # heads).  The scheduler charges admission in pages, so both
            # multipliers flow straight into admissible concurrency.
            num_pages = pages_for_budget(
                pool_bytes, model.num_layers, model.num_heads,
                model.head_dim, page_size, kv_dtype,
                num_kv_heads=num_kv_heads, tp=self.tp)
        num_pages = int(num_pages or FLAGS.serving_max_pages)
        if max_pages_per_seq is None:
            # default: one sequence may claim up to half the usable pool
            max_pages_per_seq = max(1, (num_pages - 1) // 2)
        if queue_deadline_s is None:
            queue_deadline_s = float(FLAGS.serving_queue_deadline_s)
        if preempt_budget is None:
            preempt_budget = int(FLAGS.serving_preempt_budget)
        if watchdog_ticks is None:
            watchdog_ticks = int(FLAGS.serving_watchdog_ticks)
        self.queue_deadline_s = queue_deadline_s or None   # 0 = disabled
        self.watchdog_ticks = int(watchdog_ticks)          # 0 = disabled
        self.decode_retries = max(0, int(decode_retries))
        # which exceptions the decode tick treats as transient and
        # retries.  Default: only the fault-plan's injected error.  The
        # retry is sound ONLY for errors raised before the decode
        # executes (the fault plan's injection point): once the jitted
        # step has run, the donated KV pool may already be consumed, so
        # retrying a real mid-execution XLA failure needs KV
        # snapshot/rebuild this engine does not do — don't widen the set
        # to device errors without adding that.
        self.transient_errors = tuple(transient_errors)
        self.max_retained = max(1, int(max_retained))
        self.faults = faults
        # clock precedence: fault-plan clock > explicit time_fn > monotonic
        if faults is not None and faults.clock is not None:
            self._time = faults.clock
        else:
            self._time = time_fn or time.monotonic
        self.kv_cfg = PagedKVConfig(
            num_layers=model.num_layers, num_heads=model.num_heads,
            head_dim=model.head_dim, page_size=page_size,
            num_pages=num_pages, max_pages_per_seq=int(max_pages_per_seq),
            dtype=kv_dtype, num_kv_heads=num_kv_heads, tp=self.tp)
        self._kv: KVPages = init_kv_pages(self.kv_cfg, mesh=self.mesh,
                                          axis=self.tp_axis)
        self.pool = PagePool(num_pages)
        if prefix_cache is None:
            prefix_cache = bool(FLAGS.serving_prefix_cache)
        if prefill_chunk is None:
            prefill_chunk = int(FLAGS.serving_prefill_chunk)
        self._prefill_chunk = max(0, int(prefill_chunk))
        self.cache: Optional[PrefixCache] = None
        if prefix_cache:
            hash_fn = faults.cache_hash_fn() if faults is not None else None
            self.cache = PrefixCache(self.pool, page_size, hash_fn=hash_fn)
        # hierarchical host tier (round 21): evicted reclaimable pages
        # demote to host RAM (checksummed) instead of being destroyed;
        # lookups that run off the device index swap the continuation
        # back in, verified, charged like chunk prefill.  Off unless a
        # byte budget is set (flag default 0 keeps prior behavior).
        self.host_tier: Optional[HostPageTier] = None
        self._swap_in_budget = int(
            swap_in_budget if swap_in_budget is not None
            else FLAGS.serving_swap_in_budget)
        self._host_hits = 0   # swap-in events that promoted >= 1 page
        host_bytes = int(host_tier_bytes if host_tier_bytes is not None
                         else FLAGS.serving_host_tier_bytes)
        if self.cache is not None and host_bytes > 0:
            self.host_tier = HostPageTier(
                host_bytes,
                dtype=str(host_kv_dtype if host_kv_dtype is not None
                          else FLAGS.serving_host_kv_dtype),
                faults=faults)
            self.cache.host_tier = self.host_tier
            # read at call time: self._kv is rebound every step
            self.cache.page_reader = \
                lambda pages: read_pages(self._kv, pages)
        self.scheduler = ContinuousBatchingScheduler(
            self.pool, SchedulerConfig(
                max_slots=max_slots, page_size=page_size,
                max_pages_per_seq=int(max_pages_per_seq),
                max_queue=max_queue,
                preempt_budget=preempt_budget if preempt_budget > 0
                else None),
            cache=self.cache, time_fn=self._time)
        self.metrics = ServingMetrics(pool_pages=self.pool.num_usable)
        # obs: tracer (FLAGS.obs_trace-gated at construction — a fleet
        # rebinds its shared, replica-scoped tracer via set_tracer) and
        # the unified metrics registry the per-stage latency histograms
        # and healthz publish into
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._reg_labels: Dict[str, str] = {}
        self._tracer = NULL_TRACER
        self._postmortems_dumped: set = set()
        self.set_tracer(tracer if tracer is not None
                        else tracer_for(self._time, registry=self.registry))
        # dispatch path, decided ONCE through the single chooser (the
        # per-call decision of v1 is gone): kernel iff the shapes are
        # native-compile-clean on this backend, or forced by the caller
        if use_kernel is None:
            self._ragged_kernel = attention_path(
                self.kv_cfg.head_dim, self.kv_cfg.page_size,
                num_heads=self.kv_cfg.num_heads,
                num_kv_heads=self.kv_cfg.kv_heads,
                quantized=self.kv_cfg.quantized) == "kernel"
        else:
            self._ragged_kernel = bool(use_kernel)
        self._buckets = tuple(sorted(int(b) for b in buckets)) if buckets \
            else _parse_buckets(FLAGS.serving_prefill_buckets)
        self._max_slots = max_slots
        self._fuse_tick = bool(fuse_tick)
        # prefill-row packing: the kernel needs each sequence's rows
        # padded to whole BLOCK_ROWS blocks; the per-tick row budget
        # bounds the (decode_bucket, prefill_bucket) jit-pair ladder
        self._row_align = BLOCK_ROWS if self._ragged_kernel else 1
        top = max(self._buckets) if self._buckets else \
            self.kv_cfg.max_seq_len
        chunk_rows = self._prefill_chunk if self._prefill_chunk > 0 \
            else self.kv_cfg.max_seq_len
        chunk_rows = -(-chunk_rows // self._row_align) * self._row_align
        self._prefill_budget = max(top, chunk_rows)
        # speculative decoding (round 18): a proposer drafts up to
        # spec_k tokens per running slot per tick and ONE widened step
        # verifies all k+1 positions (each speculative slot contributes
        # k+1 rows instead of 1), accepting the longest agreeing prefix
        # and rolling rejected tokens back via COW-guarded page forks.
        # k+1 is a jit dimension: the step ladder is keyed
        # (prefill_bucket, k1), one compile per pair.
        self.spec_mode = str(spec_mode if spec_mode is not None
                             else FLAGS.serving_spec_mode)
        enforce_that(self.spec_mode in _SPEC_MODES,
                     f"spec_mode must be one of {_SPEC_MODES}, got "
                     f"{self.spec_mode!r}", context="serving-spec")
        self.spec_k = int(spec_k if spec_k is not None
                          else FLAGS.serving_spec_k)
        enforce_that(self.spec_mode == "off" or self.spec_k >= 1,
                     "spec_k must be >= 1 when speculation is on",
                     context="serving-spec")
        self._proposer = None
        if self.spec_mode == "ngram":
            self._proposer = NGramProposer(n=spec_ngram)
        elif self.spec_mode == "draft":
            enforce_that(
                draft_model is not None and draft_params is not None,
                "spec_mode='draft' needs ServingEngine(draft_model=, "
                "draft_params=) — a small DecodeModel sharing the "
                "target's vocabulary", context="serving-spec")
            enforce_that(
                int(draft_model.vocab_size) == int(model.vocab_size),
                f"draft vocab ({draft_model.vocab_size}) must equal the "
                f"target vocab ({model.vocab_size})",
                context="serving-spec")
            self._proposer = DraftProposer(
                draft_model, draft_params, page_size=page_size,
                num_pages=int(draft_pool_pages or num_pages),
                max_pages_per_seq=int(max_pages_per_seq),
                max_slots=max_slots)
        # verify rows per decode slot: 1 (plain decode) + spec_k drafts
        self._k1 = 1 + (self.spec_k if self._proposer is not None else 0)
        # donate the incoming KV pool: every call overwrites self._kv
        # with the returned pool, so XLA may update pages in place —
        # without this the decode tick copies the whole pool and peak
        # HBM doubles the documented cost.  Declared UNCONDITIONALLY:
        # audit_jit strips donation before the underlying jax.jit on
        # CPU (which can't donate and would only warn), so a CPU tier-1
        # run still declares — and the jaxpr auditor still verifies —
        # the TPU donation contract.  The old per-backend gate here left
        # the contract invisible (and untested) on CPU.
        self._donate_kv = (1,)
        # compiled-path contracts, declared next to the jit sites they
        # bind (checked by `python -m paddle_tpu.analysis xla`): the KV
        # pool must be donated and alias back out, per-tick sites must
        # not host-sync or pay collectives, narrow KV dtypes may
        # intentionally dequantize into f32 attention math, and the
        # per-signature footprint stays under an order-of-magnitude
        # budget — generous slack constants make the budgets guardrails
        # against asymptotic surprises (a duplicated pool, an O(B*S^2)
        # broadcast), not cycle predictions.  Callers with exact models
        # tighten them via ServingEngine(xla_peak_bytes=, xla_flops=).
        param_bytes = param_count = 0
        for leaf in jax.tree.leaves(params):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                n = int(np.prod(leaf.shape)) if leaf.shape else 1
                param_count += n
                param_bytes += n * jnp.dtype(leaf.dtype).itemsize
        # the widened step's worst-case row stack: k1 verify rows per
        # slot plus the packed prefill budget
        rows = max_slots * self._k1 + self._prefill_budget
        e = model.num_heads * model.head_dim
        # peak budgets reason about LOGICAL (global) avals — the xla
        # auditor's live-set estimator sums full aval bytes and cannot
        # see GSPMD's per-chip split — so scale the per-chip pool bytes
        # back up by tp (healthz keeps reporting the per-chip number)
        kv_bytes = self.kv_cfg.kv_bytes() * self.tp
        act_bytes = 4 * rows * (8 * e * model.num_layers
                                + model.vocab_size)
        kv_name = jnp.dtype(self.kv_cfg.dtype).name
        allow_upcast = (kv_name,) if kv_name != "float32" else ()
        if FLAGS.attn_pv_f32:
            allow_upcast += ("bfloat16",)
        # sharding contract (checked by `python -m paddle_tpu.analysis
        # sharding`).  Replicated engine (mesh=None): every argument and
        # output pins P() with a zero collective-byte budget per tick —
        # a replicated plan moves 0 bytes over links, so any inferred
        # collective busts the budget.  Tensor-parallel engine: params
        # carry the shard_plan per leaf, the KV pool (args AND outputs)
        # shards its head dim over the model axis, and the budget is the
        # CLOSED-FORM megatron cost — two row-parallel psums per layer,
        # 2*b*(N-1)/N each over the [rows, E] f32 activation — so the
        # gate proves the decode hot path stays reduce-not-gather: one
        # implicit all-gather anywhere and the audited estimate leaves
        # the closed form.  Override via ServingEngine(xla_comm_bytes=).
        comm_budget = xla_comm_bytes if xla_comm_bytes is not None \
            else self.tp_step_comm_bytes(rows)
        kv_comm = xla_comm_bytes if xla_comm_bytes is not None else 0.0
        if self.mesh is None:
            step_in: Tuple = ((),)
            step_out: Tuple = ((),)
            kv_in: Tuple = ((),)
            kv_out: Tuple = ((),)
            mesh_axes: Tuple = ()
            expect = ()
        else:
            kvspec = kv_pool_specs(self.tp_axis)
            # per-leaf param specs (keyed by name: the auditor resolves
            # dict entries against the pytree path) + the pool spec for
            # both the donated input and the aliased output
            step_in = (dict(self._shard_plan), kvspec) + ((),) * 9
            step_out = ((), ()) + (kvspec,) * 4
            kv_in = (kvspec, (), ())
            kv_out = (kvspec,) * 4
            mesh_axes = ((self.tp_axis, self.tp),)
            expect = (0, 1)      # params and pool must arrive sharded
        self._step_contract = SiteContract(
            per_tick=True, donate=(1,), allow_upcast=allow_upcast,
            peak_bytes=xla_peak_bytes if xla_peak_bytes is not None else
            2 * kv_bytes + 8 * param_bytes + 16 * act_bytes + (1 << 26),
            flops=xla_flops if xla_flops is not None else
            64.0 * rows * (param_count
                           + self.kv_cfg.max_seq_len * e) + 1e9,
            in_specs=step_in, out_specs=step_out, mesh_axes=mesh_axes,
            comm_bytes=comm_budget, expect_sharded=expect)
        kv_contract = SiteContract(
            per_tick=True, donate=(0,),
            peak_bytes=2 * kv_bytes + (1 << 24),
            in_specs=kv_in, out_specs=kv_out, mesh_axes=mesh_axes,
            comm_bytes=kv_comm)
        # audit_jit == jax.jit unless FLAGS.jit_audit is on, in which
        # case each named site's compiles are counted by the retrace
        # auditor (paddle_tpu.analysis.retrace): the unified step must
        # compile exactly once per (prefill_bucket, k1) pair — the
        # decode row count is the fixed max_slots * k1 (k1 = 1 +
        # spec_k, 1 with speculation off), so the pair ladder is one
        # entry per prefill bucket per speculation depth, and
        # speculation adds the k dimension and nothing else
        self._step_fns: Dict[Tuple[int, int], Callable] = {}
        # COW fork + failure scrub: kv is argument 0 in both (same
        # donation contract as above)
        self._fork_fn = audit_jit(
            fork_page, site="serving.fork_page", donate_argnums=(0,),
            xla_contract=kv_contract)
        self._zero_fn = audit_jit(
            zero_pages, site="serving.zero_pages", donate_argnums=(0,),
            xla_contract=kv_contract)
        # page-migration splice (round 16): whole imported pages land in
        # the pool via one donated scatter.  The page-count dimension is
        # padded to a pow2 ladder by _apply_import so migrations of any
        # size share O(log pages) compiles; padding rows target
        # NULL_PAGE with a zero payload (page 0 is reserved scratch).
        n_payload = 4 if self.kv_cfg.quantized else 2
        if self.mesh is None:
            imp_in: Tuple = ((),)
            imp_out: Tuple = ((),)
        else:
            imp_in = (kvspec,) + ((),) * (1 + n_payload)
            imp_out = (kvspec,) * 4
        import_contract = SiteContract(
            per_tick=True, donate=(0,),
            peak_bytes=3 * kv_bytes + (1 << 24),
            in_specs=imp_in, out_specs=imp_out, mesh_axes=mesh_axes,
            comm_bytes=kv_comm)
        if self.kv_cfg.quantized:
            def _import_pages(kv, ids, k, v, ks, vs):
                return write_pages(kv, ids, k, v, ks, vs)
        else:
            def _import_pages(kv, ids, k, v):
                return write_pages(kv, ids, k, v)
        self._import_fn = audit_jit(
            _import_pages, site="serving.import_pages", donate_argnums=(0,),
            xla_contract=import_contract)
        self._results: Dict[int, List[int]] = {}
        self._requests: Dict[int, Request] = {}
        # terminal rids in retirement order; oldest evicted past
        # max_retained so a long-running engine's memory stays bounded
        self._retired: Deque[int] = deque()
        self._tick = 0
        self._last_tick_at: Optional[float] = None
        self._prev_tick_busy = False
        self._tick_dur_ema = 0.0      # drives the unmeetable-deadline shed
        self._draining = False        # drain(): REJECT new submits

    # ---- observability wiring -------------------------------------------

    def set_tracer(self, tracer) -> None:
        """(Re)bind the engine's span tracer — the fleet calls this with
        its shared tracer scoped to the replica index.  The pool,
        scheduler and prefix cache get the raw hook (None when tracing
        is off, so their hot paths pay one is-None check); when the
        retrace auditor is active the tracer also receives its
        ``jit_compile`` events."""
        self._tracer = tracer if tracer is not None else NULL_TRACER
        hook = self._tracer if self._tracer.enabled else None
        self.pool.tracer = hook
        self.scheduler.tracer = hook
        if self.cache is not None:
            self.cache.tracer = hook
        if self.host_tier is not None:
            self.host_tier.tracer = hook
        if hook is not None and getattr(FLAGS, "jit_audit", False):
            auditor().attach_tracer(self._tracer.base)

    def set_registry(self, registry: MetricsRegistry, **labels) -> None:
        """(Re)bind the unified metrics registry (fleet: one registry,
        per-replica labels).  All later stage observations and healthz
        publishes land there."""
        self.registry = registry
        self._reg_labels = {k: str(v) for k, v in labels.items()}

    def _observe_stage(self, stage: str, seconds: float) -> None:
        """Per-stage latency attribution (queue / prefill / decode) on
        the engine's injected clock — the registry half of the span
        timeline, cheap enough to stay on unconditionally."""
        self.registry.histogram(
            "serving_stage_seconds",
            "request time per lifecycle stage").labels(
            stage=stage, **self._reg_labels).observe(max(0.0, seconds))

    def _dump_postmortem(self, reason: str) -> None:
        """Flight-recorder dump on a tripped conservation invariant —
        once per reason per engine, so a prober that calls healthz in a
        leaky steady state doesn't spray one file per probe."""
        if reason not in self._postmortems_dumped:
            self._postmortems_dumped.add(reason)
            self._tracer.dump_postmortem(reason)

    # ---- compiled device functions --------------------------------------

    def tp_step_comm_bytes(self, rows: int) -> float:
        """Closed-form per-call collective budget for ``serving.step``
        under ``tp``-way tensor parallelism: each of the model's layers
        pays exactly TWO row-parallel psums (attention-output and
        FFN-down projections — the megatron pattern), each moving
        ``2 * b * (N-1)/N`` bytes over the ``model`` links for the
        ``[rows, E]`` f32 activation of ``b = 4 * rows * E`` bytes.
        Attention itself is head-local and the paged pool ops are
        batching-dim scatters, so NOTHING else may touch the links —
        the sharding gate checks the audited estimate against exactly
        this number, which is how "the decode step stays
        reduce-not-gather" becomes a CI property."""
        if self.tp <= 1:
            return 0.0
        # the residual-stream width: duck-typed models may carry an
        # embed_dim decoupled from num_heads * head_dim
        e = int(getattr(self.model, "embed_dim", 0)
                or self.model.num_heads * self.model.head_dim)
        psum = 2.0 * (4.0 * rows * e) * (self.tp - 1) / self.tp
        return float(self.model.num_layers * 2 * psum)

    def _tp_kv(self, kv: KVPages) -> KVPages:
        """Pin the returned pool to its canonical per-chip layout
        (``[L, pages, page, H_kv/TP, D]``, THE ``kv_pool_sharding``
        layout — same source of truth as placement and the contract) so
        the donated-in/aliased-out pair stays shard-identical across
        ticks (no-op replicated)."""
        if self.mesh is None:
            return kv
        from paddle_tpu.serving.kv_cache import kv_pool_sharding

        wsc = jax.lax.with_sharding_constraint
        sh = kv_pool_sharding(self.mesh, self.tp_axis)
        return KVPages(
            wsc(kv.k, sh), wsc(kv.v, sh),
            None if kv.k_scale is None else wsc(kv.k_scale, sh),
            None if kv.v_scale is None else wsc(kv.v_scale, sh))

    def _tp_ctx(self, ctx):
        """Re-assert the head sharding on an attention output (no-op on
        replicated engines).  The reference fallback's row-blocked
        ``lax.map`` is a scan whose body GSPMD — and the static
        propagation walk — cannot see through; without this constraint
        the downstream row-parallel projection would consume an
        unconstrained operand and the partitioner would be free to
        all-gather instead of psum."""
        if self.mesh is None:
            return ctx
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.lax.with_sharding_constraint(
            ctx, NamedSharding(self.mesh, P(None, self.tp_axis, None)))

    def _attend(self, kv: KVPages, layer: int, q, table, att_lens,
                row_seq, qpos, k1: int = 1):
        """One ragged paged attention over the tick's mixed row stack.
        The reference path consumes the compact ``[B * k1 + pb]`` rows
        as-is; the kernel path expands each slot's ``k1`` decode/verify
        rows to whole BLOCK_ROWS blocks (the one-sequence-per-block
        packing contract) — prefill rows are already block-aligned by
        the packer — and slices the context back out.  The expansion
        touches [B*k1, H, D]-sized data, noise next to the attention
        itself.  Under TP the kernel rides a ``shard_map`` over the
        model axis (heads are attention-local, so each chip runs the
        unchanged kernel on its head shard) and both paths re-assert
        the head sharding on the context."""
        ks = kv.k_scale[layer] if kv.k_scale is not None else None
        vs = kv.v_scale[layer] if kv.v_scale is not None else None
        if not self._ragged_kernel:
            # row-blocked fallback: identical math to the oracle, with
            # the per-row K/V gather bounded to one block of rows
            return self._tp_ctx(_ragged_reference_blocked(
                q, kv.k[layer], kv.v[layer], table, att_lens, row_seq,
                qpos, k_scale=ks, v_scale=vs))
        b, rb = self._max_slots, BLOCK_ROWS
        bd = b * k1                      # compact decode/verify rows
        rbk = -(-k1 // rb) * rb          # padded rows per slot
        td = b * rbk                     # expanded decode/verify rows
        h, d = q.shape[1], q.shape[2]
        # decode rows expand through THE shared packing helper (one copy
        # of the one-sequence-per-block contract); prefill rows are
        # already block-aligned by the packer and concatenate behind
        qd, rsd, qpd = expand_decode_rows(q[:bd], qpos[:bd],
                                          rows_per_seq=k1)
        qe = jnp.concatenate([qd, q[bd:]])
        rs = jnp.concatenate([rsd, row_seq[bd:]])
        qp = jnp.concatenate([qpd, qpos[bd:]])
        if self.mesh is not None and self.tp > 1:
            ctx = ragged_paged_attention_tp(
                self.mesh, self.tp_axis, qe, kv.k[layer], kv.v[layer],
                table, att_lens, rs, qp, k_scale=ks, v_scale=vs,
                use_kernel=True)
        else:
            ctx = ragged_paged_attention(
                qe, kv.k[layer], kv.v[layer], table, att_lens, rs, qp,
                k_scale=ks, v_scale=vs, use_kernel=True)
        cd = ctx[:td].reshape(b, rbk, h, d)[:, :k1].reshape(bd, h, d)
        return self._tp_ctx(jnp.concatenate([cd, ctx[td:]]))

    def _step_fn(self, pb: int, k1: int = 1):
        """The unified per-tick step for prefill bucket ``pb`` (0 =
        decode-only) at ``k1`` decode/verify rows per slot (1 = plain
        decode; ``1 + spec_k`` when speculating — the widened verify
        step): ONE dispatch embeds every slot's verify rows and the
        packed prefill-chunk rows, scatters every row's K/V into its
        page (quantize-on-write on int8 pools; masked rows write ZEROS
        to the shared null page so computed junk can never leak into
        gathered fallback reads), runs one ragged paged attention over
        the whole mixed batch per layer, and returns logits for ALL
        ``B * k1`` decode/verify rows plus each slot's chunk-final row
        — prior context, in-chunk causality AND in-verify causality
        (draft ``i`` sees drafts ``< i``) all come from the ONE
        ``token <= position`` mask, with no separate paths to keep in
        sync."""
        fn = self._step_fns.get((pb, k1))
        if fn is not None:
            return fn
        model, cfg = self.model, self.kv_cfg
        b, page = self._max_slots, cfg.page_size
        bd = b * k1

        def raw(params, kv: KVPages, d_tokens, d_pos, d_valid, p_tokens,
                p_qpos, p_seq, p_last, table, att_lens):
            # d_tokens/d_pos/d_valid: [B, k1] — row 0 of a slot is the
            # plain decode token, rows 1..k its drafted lookahead
            # (invalid rows write the null page and produce garbage
            # logits the host ignores).  p_tokens/p_qpos/p_seq: [pb] —
            # packed prefill rows, qpos -1 = padding (p_seq stays the
            # owning slot so kernel blocks remain sequence-uniform).
            # p_last: [B] — row index of each slot's chunk-final row in
            # the packed stack (0 for slots not prefilling).  table:
            # [B, Pm]; att_lens: [B] — valid KV per slot AFTER this
            # step's writes.
            d_seq = jnp.repeat(jnp.arange(b), k1)
            dt = d_tokens.reshape(bd)
            dp = d_pos.reshape(bd)
            dv = d_valid.reshape(bd)
            p_act = p_qpos >= 0
            pq = jnp.maximum(p_qpos, 0)
            tokens = jnp.concatenate([dt, p_tokens])
            pos = jnp.concatenate([dp, pq])
            x = model.embed(params, tokens, pos)       # [B*k1 + pb, E]
            d_pages = jnp.where(dv, table[d_seq, dp // page], NULL_PAGE)
            p_pages = jnp.where(p_act, table[p_seq, pq // page], NULL_PAGE)
            pages = jnp.concatenate([d_pages, p_pages])
            offs = jnp.concatenate([dp % page, pq % page])
            wmask = jnp.concatenate([dv, p_act])[:, None, None]
            row_seq = jnp.concatenate([d_seq, p_seq])
            qpos = jnp.concatenate([jnp.where(dv, dp, -1), p_qpos])
            for l in range(cfg.num_layers):
                q, k, v = model.qkv(params, l, x)
                kv = append_token(kv, l, jnp.where(wmask, k, 0.0),
                                  jnp.where(wmask, v, 0.0), pages, offs)
                ctx = self._attend(kv, l, q, table, att_lens, row_seq,
                                   qpos, k1=k1)
                x = model.attn_out(params, l, ctx, x)
            # logits only where the host will read them: the B*k1
            # decode/verify rows + each slot's chunk-final row
            sel = jnp.concatenate([jnp.arange(bd), p_last])
            logits = model.logits(params, x[sel])
            return (logits[:bd].reshape(b, k1, -1), logits[bd:],
                    self._tp_kv(kv))

        fn = audit_jit(raw, site="serving.step",
                       donate_argnums=self._donate_kv,
                       xla_contract=self._step_contract)
        self._step_fns[(pb, k1)] = fn
        return fn

    # ---- user surface ----------------------------------------------------

    def submit(self, prompt: Sequence[int], max_tokens: int,
               on_token: Optional[Callable[[int], None]] = None,
               now: Optional[float] = None,
               queue_deadline_s: Optional[float] = None,
               deadline_s: Optional[float] = None,
               sampling: Optional[SamplingParams] = None,
               tenant: str = "default") -> int:
        """Queue a request and return its rid — ALWAYS, even when the
        request is refused (infeasible size or queue backpressure): a
        refused rid carries status ``REJECTED``, so callers distinguish
        "rejected at submit" from "in flight" from "unknown rid" via
        ``status``/``result`` instead of a bare ``None`` sentinel.

        ``queue_deadline_s`` bounds time waiting for admission (engine
        default: ``FLAGS.serving_queue_deadline_s``); ``deadline_s``
        bounds submit-to-last-token.  Either lapsing marks the request
        ``TIMED_OUT`` and frees everything it held.

        ``sampling`` (a :class:`SamplingParams`) turns on real sampling
        — temperature/top-k/top-p with seeded per-position RNG streams,
        bit-reproducible across replays on the injected clock; None (or
        temperature 0) keeps greedy argmax, token-identical to the
        oracle."""
        req = Request(prompt=list(int(t) for t in prompt),
                      max_tokens=int(max_tokens), on_token=on_token,
                      sampling=sampling, tenant=str(tenant))
        t = self._time() if now is None else now
        if queue_deadline_s is None:
            # engine-wide default; self.queue_deadline_s is None when
            # the flag is 0 (the 0-means-off semantic lives on the FLAG,
            # not on the per-request parameters)
            queue_deadline_s = self.queue_deadline_s
        if queue_deadline_s is not None:
            req.queue_deadline_at = t + float(queue_deadline_s)
        if deadline_s is not None:
            req.deadline_at = t + float(deadline_s)
        # for BOTH per-request overrides, None = no deadline and an
        # explicit 0.0 is an already-spent budget (times out next tick)
        if self._draining:
            # drain mode: admission is closed.  The request is REJECTED
            # up front — queued and running work keeps going, but no new
            # demand enters (the fleet router reads this as "route
            # elsewhere").
            req.submitted_at = t
            req.status = RequestStatus.REJECTED
            ok = False
        else:
            ok = self.scheduler.submit(req, now=t)
        self.metrics.on_submit(t, ok)
        self._requests[req.rid] = req
        self._tracer.instant("submit", rid=req.rid, tokens=len(req.prompt),
                             max_tokens=req.max_tokens, accepted=ok)
        if not ok:
            self._retire(req)
        return req.rid

    def _finish(self, req: Request, status: RequestStatus, now: float,
                shed: bool = False) -> None:
        """THE terminal-transition path (every non-completed exit and
        completion itself funnel through here): return the slot and
        pages — or leave the queue — stamp, count, retire.  One copy of
        the invariant, so no path can forget eviction or a counter."""
        if status is RequestStatus.FAILED and req.pages:
            # a FAILED request may have written non-finite K/V; scrub
            # the suspect pages so re-granted ones can't leak inf into
            # the next owner's masked attention reads.  Suspect = the
            # request's UNCACHED pages: cached pages were finite-vouched
            # at insertion (a failing chunk's were just forgotten) and
            # may be shared right now — decode appends and failing
            # chunks only ever write uncached ones.
            suspect = [p for p in req.pages if not self.pool.is_cached(p)]
            if suspect:
                self._kv = self._zero_fn(self._kv,
                                         jnp.asarray(suspect, jnp.int32))
        if self._proposer is not None:
            # drop any draft-model cache state (its pages return to the
            # draft pool); a no-op for the n-gram proposer
            self._proposer.release(req.rid)
        if req.slot is not None:
            self.scheduler.release(req, status)
        else:
            self.scheduler.drop_queued(req, status)
        req.finished_at = now
        hook = self.metrics.on_shed if shed else {
            RequestStatus.COMPLETED: self.metrics.on_complete,
            RequestStatus.TIMED_OUT: self.metrics.on_timeout,
            RequestStatus.CANCELLED: self.metrics.on_cancel,
            RequestStatus.FAILED: self.metrics.on_fail,
        }[status]
        hook()
        if shed or status is RequestStatus.TIMED_OUT:
            # deadline miss billed to the tenant (round 17): both the
            # hard expiry and the unmeetable-estimate shed count — same
            # numerator as deadline_miss_rate, split per tenant
            self.metrics.on_tenant_miss(req.tenant)
        if req.first_token_at is not None:
            self._observe_stage("decode", now - req.first_token_at)
        self._tracer.instant("terminal", rid=req.rid, status=str(status),
                             shed=shed, tokens=len(req.generated))
        self._retire(req)

    def _retire(self, req: Request) -> None:
        """Record a terminal transition; evict the oldest terminal
        requests (and their results) past ``max_retained`` so request
        history doesn't grow without bound on a long-running engine.
        ``status``/``result`` raise KeyError for evicted rids, same as
        never-issued ones."""
        self._retired.append(req.rid)
        while len(self._retired) > self.max_retained:
            old = self._retired.popleft()
            self._requests.pop(old, None)
            self._results.pop(old, None)

    def cancel(self, rid: int, now: Optional[float] = None) -> bool:
        """Cancel a request.  Queued/preempted requests leave the queue;
        a running one releases its slot and pages immediately (its page
        writes are garbage the next owner overwrites).  Returns False if
        the request already reached a terminal status; raises KeyError
        for an unknown rid."""
        req = self._requests[rid]
        if req.finished:
            return False
        now = self._time() if now is None else now
        self._finish(req, RequestStatus.CANCELLED, now)
        return True

    def status(self, rid: int) -> RequestStatus:
        """Lifecycle status of ``rid``; raises KeyError for a rid this
        engine never issued."""
        return self._requests[rid].status

    def drain(self, on: bool = True) -> None:
        """Toggle drain mode: while draining, every new ``submit`` is
        REJECTED immediately, but requests already queued or running
        finish normally (admission from the existing queue continues —
        the drain stops new DEMAND, not accepted work).  ``drain(False)``
        reopens admission (a replica rejoining a fleet)."""
        self._draining = bool(on)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def step(self, now: Optional[float] = None) -> bool:
        """One engine tick: shed expired/unmeetable work, grow/preempt,
        admit + prefill, one fused decode over all running sequences
        (with transient-error retry, finite-logits isolation, and the
        progress watchdog).  Returns True if any work remains."""
        tick, sched, m = self._tick, self.scheduler, self.metrics
        if self.faults is not None:
            self.faults.tick_begin(tick)
            self.faults.apply_page_pressure(tick, self.pool)
            self.faults.apply_cache_storm(tick, self.cache)
        now = self._time() if now is None else now
        # the shed estimator learns tick duration only from ticks that
        # followed a BUSY tick: in a continuous serving loop those run
        # back-to-back so the gap is compute time, while idle gaps (a
        # server polling step() with nothing in flight) would inflate
        # the EMA and shed whole bursts spuriously
        if (self._last_tick_at is not None and now > self._last_tick_at
                and self._prev_tick_busy):
            dur = now - self._last_tick_at
            self._tick_dur_ema = dur if self._tick_dur_ema == 0.0 else \
                0.5 * self._tick_dur_ema + 0.5 * dur
        self._last_tick_at = now
        self._enforce_deadlines(now)
        # growth/preemption BEFORE admission: a tick must not pay for a
        # new request's prefill and then immediately preempt it (the
        # youngest) to grow older sequences.  admit() reserves the first
        # decode append's page, so fresh admissions never need same-tick
        # growth either.
        preempted = sched.ensure_decode_pages()
        npreempt = len(preempted)
        m.on_preempt(npreempt)
        if self._proposer is not None:
            for req in preempted:
                # a preempted request re-prefills from scratch later;
                # keeping its draft-model cache pinned meanwhile would
                # starve the draft pool (and the state is stale anyway
                # — catch-up rebuilds it at the next propose)
                self._proposer.release(req.rid)
        # host-tier advance BEFORE admission: commit the staged spill
        # (depth-one writer) and swap in up to swap_in_budget verified
        # host pages for the head-of-queue request, so the admission
        # lookup right below sees them as ordinary device hits
        self._pump_host_tier(tick)
        admitted = sched.admit()
        for req in admitted:
            if req.admitted_at is None:
                # queue wait is a first-admission stat: re-admissions
                # after preemption would fold running time into it
                wait = now - (req.submitted_at
                              if req.submitted_at is not None else now)
                m.on_admit(wait)
                m.on_tenant_admit(req.tenant, wait)
                self._observe_stage("queue", wait)
                req.admitted_at = now
            req.last_progress_tick = tick
            self._tracer.instant("admit", rid=req.rid, slot=req.slot,
                                 cached=req.cached_len, tick=tick)
            self._begin_prefill(req)
        # the unified step: this tick's decode/verify rows AND every
        # selected prefill chunk ride ONE dispatch (one ragged
        # attention over shared pages), so a long prefill no longer
        # stalls running slots' inter-token latency NOR costs a second
        # dispatch.  Chunk candidates go oldest-progress-first so a
        # request crowded out by the row budget is first in line next
        # tick.
        prefilling = sorted(
            (r for r in sched.running_requests()
             if r.status is RequestStatus.RUNNING and r.prefilling),
            key=lambda r: (r.last_progress_tick, r.slot))
        chunks, total_rows = pack_prefill_chunks(
            prefilling, self._prefill_chunk, self._row_align,
            self._prefill_budget)
        running = [r for r in sched.running_requests()
                   if r.status is RequestStatus.RUNNING
                   and not r.prefilling and r.generated]
        # speculation: draft lookahead tokens per slot BEFORE the retry
        # loop (drafting mutates proposer state — it must run once per
        # tick, and the position-keyed RNG keeps it deterministic)
        drafts = self._propose_drafts(running, under_pressure=npreempt > 0)
        if running or chunks:
            for req, start, n, _ in chunks:
                self._tracer.instant("prefill_chunk", rid=req.rid,
                                     slot=req.slot, start=start, n=n,
                                     tick=tick)
            # span keeps its historical name: it IS the fused tick
            with self._tracer.span("decode_tick", tick=tick,
                                   n=len(running),
                                   prefill_rows=total_rows):
                if self._fuse_tick or not (running and chunks):
                    self._step_with_retry(running, chunks, total_rows,
                                          tick, drafts)
                else:
                    # fuse_tick=False: the v1 tick-interleave shape —
                    # prefill and decode as separate dispatches (bench
                    # control; same math, token-identical)
                    self._step_with_retry([], chunks, total_rows, tick,
                                          {})
                    self._step_with_retry(running, [], 0, tick, drafts)
        self._prev_tick_busy = (bool(running) or bool(admitted) or
                                bool(prefilling))
        self._watchdog_sweep(tick)
        m.on_tick(sched.queue_depth, self.pool.num_live,
                  self.pool.num_cached,
                  self.cache.evictions if self.cache is not None else 0)
        if self.host_tier is not None:
            m.on_host_tier(self.host_tier.snapshot(), self._host_hits)
        self._tick = tick + 1
        return self.has_work

    def run(self, max_ticks: Optional[int] = None) -> Dict[int, List[int]]:
        """Tick until drained (or ``max_ticks``); returns
        {rid: generated tokens} for everything completed so far.  A full
        drain releases any fault-plan page pressure and asserts free-list
        conservation (:class:`PageLeakError` on violation)."""
        ticks = 0
        while self.has_work:
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        if not self.has_work:
            if self.faults is not None:
                self.faults.release_pressure(self.pool)
            if self.host_tier is not None:
                # drain barrier: the staged spill commits (no torn
                # pending across a quiesce) before conservation runs
                self.host_tier.flush()
            self.check_page_conservation()
        return dict(self._results)

    def result(self, rid: int) -> Optional[List[int]]:
        """Generated tokens for a COMPLETED rid; None while the request
        is in flight or if it ended in a non-completed terminal status
        (disambiguate via ``status``); KeyError for a rid the engine
        never issued or already evicted past ``max_retained``."""
        if rid not in self._requests:
            raise KeyError(rid)
        return self._results.get(rid)

    # ---- invariants / health --------------------------------------------

    def check_page_conservation(self) -> None:
        """Two-part conservation (raises :class:`PageLeakError`, whose
        message carries a grep-able token either way):

        - ``PAGE-LEAK`` — every usable page is either on the free list
          or tracked in use (live or cached-reclaimable);
        - ``REF-LEAK`` — the pool's total refcount equals the references
          actually held: one per page-table entry of every running or
          queued request, one per fault-plan pressure page.  Cached
          pages parked at refcount 0 hold none, so sharing, COW forks,
          preemption-unref and eviction all have to balance exactly."""
        pool = self.pool
        if pool.num_free + pool.num_in_use != pool.num_usable:
            # flight recorder: the leak report ships WITH the event
            # history that produced it (no-op when tracing is off)
            self._dump_postmortem("PAGE-LEAK")
            raise PageLeakError(
                f"PAGE-LEAK: free={pool.num_free} in_use={pool.num_in_use} "
                f"usable={pool.num_usable}")
        live = (list(self.scheduler.running.values()) +
                list(self.scheduler.queue))
        held = sum(len(r.pages) for r in live)
        # an admission-time COW pin (fork source awaiting the copy) is a
        # held reference too, until the engine's fork consumes it
        held += sum(1 for r in live if r.cow_src is not None)
        if self.faults is not None:
            held += len(self.faults.held_pages)
        if held != pool.total_refs:
            self._dump_postmortem("REF-LEAK")
            raise PageLeakError(
                f"REF-LEAK: held={held} refs={pool.total_refs} "
                f"cached={pool.num_cached} free={pool.num_free} "
                f"usable={pool.num_usable}")
        if self._proposer is not None:
            # the draft-model pool obeys the same conservation law:
            # pages held by live draft states == draft-pool refcounts
            self._proposer.check_conservation()
        if self.host_tier is not None:
            # third state (round 21): pages now conserve across device,
            # host, and dropped — the tier's own ledger must balance
            # (HOSTTIER-LEAK) at any tick, not just at drain
            try:
                self.host_tier.check()
            except PageLeakError:
                self._dump_postmortem("HOSTTIER-LEAK")
                raise

    # ---- page-migration plane (round 16) --------------------------------

    def migratable_rids(self) -> List[int]:
        """Requests eligible for a chain handoff to a decode-class
        replica: still RUNNING, prefill fully materialized, and at
        least the first token emitted (so the destination starts with a
        decodable state — ``generated[-1]`` is the next step's input)."""
        return [r.rid for r in self.scheduler.running_requests()
                if r.status is RequestStatus.RUNNING and not r.prefilling
                and r.generated]

    def apply_imported_pages(self, page_ids: Sequence[int], k, v,
                             k_scale=None, v_scale=None) -> None:
        """Splice host page payloads (STORED values from
        ``kv_cache.read_pages`` on the source engine) into this
        engine's device pool at ``page_ids``.  The page-count dimension
        is padded up to the next power of two so migrations of any size
        share O(log pages) compiles of the donated
        ``serving.import_pages`` scatter; padding rows write a zero
        payload into NULL_PAGE (reserved scratch, never read)."""
        n = len(page_ids)
        if n == 0:
            return
        padded = 1 << max(0, (n - 1).bit_length())
        pad = padded - n
        ids = list(page_ids) + [NULL_PAGE] * pad

        def _pad(a):
            if a is None or pad == 0:
                return a
            z = np.zeros((a.shape[0], pad) + a.shape[2:], a.dtype)
            return np.concatenate([a, z], axis=1)

        ids_dev = jnp.asarray(ids, jnp.int32)
        if self.kv_cfg.quantized:
            self._kv = self._import_fn(self._kv, ids_dev, _pad(k), _pad(v),
                                       _pad(k_scale), _pad(v_scale))
        else:
            self._kv = self._import_fn(self._kv, ids_dev, _pad(k), _pad(v))

    # ---- hierarchical host tier (round 21) -------------------------------

    def _pump_host_tier(self, tick: int) -> None:
        """One tick of host-tier work, BEFORE admission and never
        blocking decode: advance the depth-one spill writer, then — for
        the head-of-queue request only — walk the host index past the
        device index's longest hit and promote up to ``swap_in_budget``
        verified pages back into the pool (the chunk-prefill charging
        model: bounded pages per tick; a longer host chain continues
        next tick).  Promoted pages are inserted into the device index
        and parked RECLAIMABLE, so the admission lookup right after
        treats them exactly like any other cached prefix — the COW /
        pinning machinery is reused unchanged.  A checksum mismatch
        pops the record, counts HOSTTIER-CORRUPT, and truncates the
        swap-in there: corruption degrades to a shorter hit (a miss for
        that block), never to wrong KV."""
        tier, cache, sched = self.host_tier, self.cache, self.scheduler
        if tier is None or cache is None:
            return
        tier.pump(tick)
        if self._swap_in_budget <= 0 or not sched.queue:
            return
        req = sched.queue[0]
        toks = req.cache_tokens
        page = self.kv_cfg.page_size
        nblocks = len(toks) // page
        if nblocks == 0 or len(tier) == 0:
            return
        _, hit_len = cache.lookup(toks)       # pure probe, no LRU churn
        j = hit_len // page
        if j >= nblocks:
            return
        keys = cache.chain_keys(toks)
        h = _CHAIN_SEED if j == 0 else keys[j - 1]
        probe: List[Tuple[int, int, Tuple[int, ...]]] = []
        jj, hh = j, h
        while jj < nblocks and len(probe) < self._swap_in_budget:
            block = tuple(toks[jj * page:(jj + 1) * page])
            if tier.peek(keys[jj], hh, block) is None:
                break
            probe.append((keys[jj], hh, block))
            hh = keys[jj]
            jj += 1
        if not probe:
            return
        # device pages first (the ladder may evict-and-spill to make
        # room); under pressure the records simply stay host-resident
        # and the walk retries next tick
        new = sched.alloc_pages(len(probe))
        if new is None:
            return
        got = []
        for key, prev, block in probe:
            rec = tier.take_verified(key, prev, block)
            if rec is None:
                break                  # HOSTTIER-CORRUPT: chain ends here
            got.append(rec)
        used, unused = new[:len(got)], new[len(got):]
        if got:
            k = np.concatenate([r.k for r in got], axis=1)
            v = np.concatenate([r.v for r in got], axis=1)
            ks = vs = None
            if got[0].k_scale is not None:
                ks = np.concatenate([r.k_scale for r in got], axis=1)
                vs = np.concatenate([r.v_scale for r in got], axis=1)
            if not self.kv_cfg.quantized and ks is not None:
                # int8-on-host under a float device pool: dequantize on
                # promotion with the one shared rule
                k = np.asarray(dequantize_kv(jnp.asarray(k),
                                             jnp.asarray(ks)))
                v = np.asarray(dequantize_kv(jnp.asarray(v),
                                             jnp.asarray(vs)))
                ks = vs = None
            self.apply_imported_pages(used, k, v, ks, vs)
            # pages[] is indexed by block: blocks < j are already
            # device-resident (insert never touches them — NULL_PAGE
            # padding keeps the indices aligned)
            cache.insert(toks, [NULL_PAGE] * j + used,
                         upto=(j + len(got)) * page, from_block=j,
                         prev_hash=h, tenant=req.tenant)
            self._host_hits += 1
            self._tracer.instant("host_swap_in", rid=req.rid,
                                 n=len(got), tick=tick)
        if used:
            # park the promoted pages reclaimable (insert registered
            # them cached; dropping our alloc ref leaves refcount 0)
            self.pool.free(used)
        if unused:
            self.pool.free(unused)

    def load(self) -> Dict[str, object]:
        """Cheap load probe: the same queue_depth / running /
        free_pages numbers ``healthz`` reports, WITHOUT the
        conservation scan healthz pays for its ``ok`` bit.  The fleet
        router reads this once per candidate replica per submit, so it
        must stay O(1); ``healthz`` remains the full diagnostic for
        external probers."""
        return {"queue_depth": self.scheduler.queue_depth,
                "running": len(self.scheduler.running),
                "free_pages": self.pool.num_free,
                # class-aware routing probe (round 16): prompt tokens
                # still owed a prefill, and this engine's fleet class —
                # both O(1) (the scheduler maintains the backlog
                # incrementally on every cache_len edge)
                "prefill_backlog_tokens":
                    self.scheduler.prefill_backlog_tokens,
                "role": self.role,
                "draining": self._draining,
                # host-tier depth (round 21): pages warm in host RAM —
                # a router's restart/balance decision reads this O(1)
                "pages_host": (len(self.host_tier)
                               if self.host_tier is not None else 0),
                # per-tenant split (round 17): the control plane's WFQ /
                # autoscaler read this; O(live requests), still cheap at
                # the bounded slot/queue sizes this probe already scans
                "tenants": self.tenant_counts()}

    def tenant_counts(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant live/terminal split: running, queued and
        pages_in_use from the bounded live scans, deadline_misses from
        the metrics counter.  Keys appear once a tenant has ever been
        seen live, been admitted, or missed a deadline — "default"
        covers legacy callers that never pass ``tenant=``."""
        out: Dict[str, Dict[str, int]] = {}

        def _slot(t: str) -> Dict[str, int]:
            return out.setdefault(t, {"running": 0, "queued": 0,
                                      "pages_in_use": 0,
                                      "pages_host": 0,
                                      "deadline_misses": 0})

        for req in self.scheduler.running.values():
            s = _slot(req.tenant)
            s["running"] += 1
            s["pages_in_use"] += len(req.pages)
        for req in self.scheduler.queued_requests():
            _slot(req.tenant)["queued"] += 1
        for t, n in self.metrics.tenant_deadline_misses.items():
            _slot(t)["deadline_misses"] = n
        # host-tier residency billed to whoever prefilled the page
        # (round 21): the ledger view splits warm capacity by tenant
        if self.host_tier is not None:
            for t, n in self.host_tier.resident_by_tenant.items():
                _slot(t)["pages_host"] = n
        # tenants whose work all completed cleanly must still report a
        # zero-miss row: the admission window remembers everyone admitted
        for t in self.metrics.tenant_queue_wait_s:
            _slot(t)
        return out

    def healthz(self) -> Dict[str, object]:
        """One-call liveness snapshot for an external prober.  O(live
        requests), not O(history): terminal counts come from the metrics
        counters, live states from the bounded queue/slot scans."""
        m = self.metrics
        counts: Dict[str, int] = {}
        for key, val in (("completed", m.completed),
                         ("timed_out", m.timed_out),
                         ("cancelled", m.cancelled),
                         ("failed", m.failed),
                         ("rejected", m.rejected + m.shed)):
            if val:
                counts[key] = val
        for req in (list(self.scheduler.queue) +
                    list(self.scheduler.running.values())):
            counts[req.status.value] = counts.get(req.status.value, 0) + 1
        try:
            self.check_page_conservation()
            leak = False
        except PageLeakError:
            leak = True
        # the unified-registry surface: publish this engine's counters,
        # then hand back the registry's flat snapshot so one healthz
        # probe reads the same numbers a scraper would.  Host-tier
        # gauges are stamped first so a probe between ticks (or before
        # the first) reads current tier state, not last tick's.
        if self.host_tier is not None:
            m.on_host_tier(self.host_tier.snapshot(), self._host_hits)
        self.metrics.publish(self.registry, **self._reg_labels)
        # retrace-auditor compile counts ride the same scrape surface
        # (jit_compiles_total{site=...}): before this they existed only
        # as jit_compile trace instants, invisible to a scraper.  Gated
        # on the auditor actually having sites, so audit-off engines
        # pay nothing and publish nothing.  Published WITHOUT the
        # per-engine labels: the auditor is process-global (every
        # replica's compiles land on ONE SiteRecord per site name), so
        # stamping replica labels on the shared sums would make each
        # replica appear to own the whole fleet's compiles — in a
        # shared-registry fleet the publishes are idempotent instead.
        if auditor().sites:
            auditor().publish(self.registry)
        return {
            "ok": not leak,
            "metrics": self.registry.snapshot(),
            "tick": self._tick,
            "queue_depth": self.scheduler.queue_depth,
            "running": len(self.scheduler.running),
            "draining": self._draining,
            # first-class load signals for a fleet router's balancing /
            # overflow decision (queue_depth above + free_pages here):
            # admission headroom without reaching into pool internals.
            # pages_free stays as the historical alias.
            "free_pages": self.pool.num_free,
            "pages_free": self.pool.num_free,
            # in_use = live sequence holders; cached/reclaimable pages
            # are reported separately so a prober can assert the cache
            # drains to steady state (live 0, cached >= 0 all evictable)
            "pages_in_use": self.pool.num_live,
            "pages_cached": self.pool.num_cached,
            "pages_reclaimable": self.pool.num_reclaimable,
            # effective cache capacity: what the pool's byte budget buys
            # at this KV dtype (int8 admits ~4x the f32 pages — see
            # ServingEngine(pool_bytes=...))
            "pages_total": self.pool.num_usable,
            "kv_dtype": str(jnp.dtype(self.kv_cfg.dtype).name),
            # per-CHIP pool bytes: under TP each chip holds 1/tp of
            # every page's KV heads (scales sharded with them)
            "kv_bytes": self.kv_cfg.kv_bytes(),
            "tp": self.tp,
            # `is not None`, not truthiness: PrefixCache defines __len__,
            # so an empty-but-active cache is falsy
            "cache_hits": self.cache.hits if self.cache is not None else 0,
            "cache_misses": (self.cache.misses
                             if self.cache is not None else 0),
            # host-tier gauges (round 21) — same is-not-None rule
            # (HostPageTier defines __len__ too); zeros with the tier off
            # so probers read one stable schema
            "pages_host": (len(self.host_tier)
                           if self.host_tier is not None else 0),
            "host_swap_ins": (self.host_tier.swap_ins
                              if self.host_tier is not None else 0),
            "host_swap_outs": (self.host_tier.spills
                               if self.host_tier is not None else 0),
            "host_hits": self._host_hits,
            "host_corrupt": (self.host_tier.corrupt
                             if self.host_tier is not None else 0),
            "spill_stall_ticks": (self.host_tier.spill_stall_ticks
                                  if self.host_tier is not None else 0),
            "page_leak": leak,
            "status_counts": counts,
            "deadline_miss_rate": round(self.metrics.deadline_miss_rate(),
                                        4),
            # disaggregated-fleet probe (round 16): same pair load()
            # exposes, on the full diagnostic surface
            "prefill_backlog_tokens":
                self.scheduler.prefill_backlog_tokens,
            "role": self.role,
            # per-tenant counters (round 17) on the full diagnostic
            # surface, same shape as load()["tenants"]
            "tenants": self.tenant_counts(),
        }

    # ---- internals -------------------------------------------------------

    def _enforce_deadlines(self, now: float) -> None:
        sched = self.scheduler
        # running requests past their total deadline: free immediately
        for req in list(sched.running.values()):
            if req.deadline_at is not None and now >= req.deadline_at:
                self._finish(req, RequestStatus.TIMED_OUT, now)
        for req in sched.queued_requests():
            # the queue deadline is an ADMISSION SLO: once a request has
            # been admitted it is satisfied forever — a preempted request
            # back in the queue is judged only by its total deadline
            expired = (req.deadline_at is not None and
                       now >= req.deadline_at) or \
                      (req.admitted_at is None and
                       req.queue_deadline_at is not None and
                       now >= req.queue_deadline_at)
            if expired:
                self._finish(req, RequestStatus.TIMED_OUT, now)
                continue
            # load shedding, on the WORST-CASE length assumption: at one
            # token per tick (the engine's best rate), a request that
            # runs to its full max_tokens cannot finish by its deadline.
            # An early EOS could beat the estimate — callers who rely on
            # early stopping should size max_tokens to what they
            # actually expect, since it is the only length signal the
            # engine has before decoding.
            if (req.deadline_at is not None and self._tick_dur_ema > 0.0
                    and now + req.tokens_remaining * self._tick_dur_ema
                    > req.deadline_at):
                self._finish(req, RequestStatus.REJECTED, now, shed=True)

    def _propose_drafts(self, running: List[Request],
                        under_pressure: bool) -> Dict[int, Tuple]:
        """Per-tick speculation: ask the proposer for up to ``spec_k``
        drafts per running slot, charge lookahead pages (opportunistic
        — never by preemption), and privatize any shared page the
        verify would write (:meth:`_cow_guard`).  Under page pressure
        (a preemption ran this tick, or the pool is dry) speculation is
        suspended outright: the tick degrades to plain 1-row decode,
        which the base page charge already guaranteed.  Returns
        ``{rid: (draft tokens, warped proposal probs or None)}``."""
        if self._proposer is None or not running:
            return {}
        m = self.metrics
        if under_pressure or self.pool.num_free == 0:
            m.on_spec_suspend(len(running))
            return {}
        caps = {req.rid: max(0, min(self.spec_k,
                                    req.tokens_remaining - 1,
                                    self.kv_cfg.max_seq_len
                                    - req.cache_len - 1))
                for req in running}
        eligible = [r for r in running if caps[r.rid] > 0]
        proposals = self._proposer.propose(eligible,
                                           lambda r: caps[r.rid]) \
            if eligible else {}
        drafts: Dict[int, Tuple] = {}
        for req in running:
            got = proposals.get(req.rid, ((), None))
            toks, probs = list(got[0])[:caps[req.rid]], got[1]
            if toks:
                granted = self.scheduler.grant_lookahead(req, len(toks))
                if granted < len(toks):
                    m.on_spec_suspend()       # page-pressure shrink
                    toks = toks[:granted]
            # the guard also covers the base decode row (toks may be
            # empty): a speculating engine never writes ANY verify row
            # into a cached or refcount-shared page un-forked
            toks = self._cow_guard(req, toks)
            if toks:
                drafts[req.rid] = (
                    toks, None if probs is None else probs[:len(toks)])
        if isinstance(self._proposer, DraftProposer):
            m.on_draft(self._proposer.steps, self._proposer.step_time_s)
        return drafts

    def _cow_guard(self, req: Request, toks: List[int]) -> List[int]:
        """Copy-on-write guard for the verify's multi-token write: every
        page the ``len(toks) + 1`` rows would touch that is cached or
        refcount-shared is forked into a private replica first (table
        entry swapped, our reference moved), so a rejected speculative
        branch can never dirty K/V another holder — a prefix-cache
        sharer, or the cache itself — reads.  If the fork cannot get a
        page, the lookahead truncates to stop short of the shared page
        instead."""
        page = self.kv_cfg.page_size
        for idx in pages_spanned(req.cache_len, len(toks) + 1, page):
            src = req.pages[idx]
            if not self.pool.is_cached(src) and \
                    self.pool.refcount(src) <= 1:
                continue
            got = self.scheduler.alloc_pages(1)
            if got is None:
                # cannot privatize: write nothing into this page.  The
                # base decode row (position cache_len) always ships —
                # its page is never shared under the engine's own
                # insert policy (only FULL prefix pages are ever
                # cached/stitched), this guard exists for duck-typed
                # callers that cache more aggressively.
                self.metrics.on_spec_suspend()
                return toks[:max(0, idx * page - 1 - req.cache_len)]
            # scalar page-id UPLOADS for the rare fork dispatch, not
            # readbacks — same shape _begin_prefill's COW fork uses
            self._kv = self._fork_fn(
                self._kv,
                jnp.asarray(src, jnp.int32),       # lint: allow(host-sync)
                jnp.asarray(got[0], jnp.int32))    # lint: allow(host-sync)
            self.pool.free([src])     # drop OUR ref; sharers keep theirs
            req.pages[idx] = got[0]
            self.metrics.on_spec_cow()
            self._tracer.instant("spec_cow", rid=req.rid, src=src,
                                 dst=got[0])
        return toks

    def _step_with_retry(self, running: List[Request], chunks, total_rows,
                         tick: int, drafts: Dict[int, Tuple]) -> None:
        attempt = 0
        while True:
            try:
                if self.faults is not None and \
                        self.faults.decode_should_fail(tick, attempt):
                    raise InjectedDeviceError(f"injected @ tick {tick} "
                                              f"attempt {attempt}")
                self._do_step(running, chunks, total_rows, drafts)
                return
            except self.transient_errors:
                attempt += 1
                if attempt > self.decode_retries:
                    return   # tick lost; the watchdog counts the stall
                self.metrics.on_retry()

    def _watchdog_sweep(self, tick: int) -> None:
        if self.watchdog_ticks <= 0:
            return
        sched = self.scheduler
        for req in list(sched.running.values()):
            if tick - req.last_progress_tick >= self.watchdog_ticks:
                self._finish(req, RequestStatus.FAILED, self._time())

    def _begin_prefill(self, req: Request) -> None:
        """Stitch-time work for a newly (re-)admitted request: record
        the prefix-cache outcome, run the COW fork, and arm the chunked
        prefill (its first chunk runs this same tick)."""
        toks = req.cache_tokens
        req.prefilling = True
        req.chain_hash, req.chain_blocks = None, 0   # fresh insert cursor
        self.metrics.on_prefix(len(toks), req.cached_len)
        if req.cow_src is not None:
            # full-cover hit: the tail's only token rewrites a position
            # INSIDE the last shared page, so fork it into the request's
            # first private page before anything is written
            dst = req.pages[req.cache_len // self.kv_cfg.page_size]
            self._kv = self._fork_fn(self._kv,
                                     jnp.asarray(req.cow_src, jnp.int32),
                                     jnp.asarray(dst, jnp.int32))
            # the fork consumed the source: drop the admission-time pin
            # that kept it from being evicted before the copy ran
            self.pool.free([req.cow_src])
            req.cow_src = None
            self.metrics.on_cow()

    def _do_step(self, running: List[Request], chunks,
                 total_rows: int, drafts: Dict[int, Tuple]) -> None:
        """Assemble and dispatch ONE unified step, then walk its
        results: chunk bookkeeping first (cache inserts, finite guard,
        final-chunk first-token emission — the v1 tick order),
        decode/verify emissions second.

        Every chunk's final-row logits go through the finite guard
        BEFORE its full pages are indexed (those logits attend over
        every K/V written so far, so finiteness transitively vouches
        for the whole chain): without the per-chunk check, suspect K/V
        from an overflowing prompt would be hittable for the whole
        multi-tick prefill window, and a sharer admitted in that window
        would stitch it before the final-chunk rollback ran.

        With speculation, slot ``s`` ships ``1 + len(drafts[s])`` rows
        (the plain decode token plus the lookahead); the accept walk
        (``speculate.accept_tokens``) emits the longest agreeing prefix
        plus one bonus/corrected token, and a partial acceptance rolls
        the lookahead pages back (``scheduler.rollback_pages``) — the
        rejected rows' K/V beyond the new length is masked junk the
        next real tokens overwrite."""
        b, k1 = self._max_slots, self._k1
        cfg = self.kv_cfg
        d_tokens = np.zeros((b, k1), np.int32)
        d_pos = np.zeros((b, k1), np.int32)
        d_valid = np.zeros((b, k1), bool)
        att_lens = np.zeros((b,), np.int32)
        table = np.full((b, cfg.max_pages_per_seq), NULL_PAGE, np.int32)
        for req in running:
            s = req.slot
            dr = drafts.get(req.rid, ((), None))[0]
            n = 1 + len(dr)
            d_tokens[s, 0] = req.generated[-1]
            d_tokens[s, 1:n] = dr
            d_pos[s, :n] = req.cache_len + np.arange(n)
            d_valid[s, :n] = True
            att_lens[s] = req.cache_len + n
            table[s, :len(req.pages)] = req.pages
        pb = 0
        if chunks:
            pb = bucket_for(total_rows, self._buckets,
                            max(cfg.max_seq_len, total_rows))
            if self._ragged_kernel:  # whole blocks only (kernel packing)
                pb = -(-pb // BLOCK_ROWS) * BLOCK_ROWS
        p_tokens = np.zeros((pb,), np.int32)
        p_qpos = np.full((pb,), -1, np.int32)
        p_seq = np.zeros((pb,), np.int32)
        p_last = np.zeros((b,), np.int32)
        off = 0
        for req, start, n, rows in chunks:
            s = req.slot
            toks = req.cache_tokens
            p_tokens[off:off + n] = toks[start:start + n]
            p_qpos[off:off + n] = np.arange(start, start + n)
            # padding rows keep the owning slot so each kernel block
            # stays sequence-uniform (their qpos -1 masks them out)
            p_seq[off:off + rows] = s
            # absolute row in the step's stack (behind the B*k1
            # decode/verify rows)
            p_last[s] = b * k1 + off + n - 1
            att_lens[s] = start + n
            table[s, :len(req.pages)] = req.pages
            off += rows
        d_logits, p_logits, self._kv = self._step_fn(pb, k1)(
            self.params, self._kv, jnp.asarray(d_tokens),
            jnp.asarray(d_pos), jnp.asarray(d_valid),
            jnp.asarray(p_tokens), jnp.asarray(p_qpos),
            jnp.asarray(p_seq), jnp.asarray(p_last), jnp.asarray(table),
            jnp.asarray(att_lens))
        d_logits = np.asarray(d_logits)   # forces device sync; [B,k1,V]
        p_logits = np.asarray(p_logits)
        self.metrics.on_step(
            sum(1 + len(drafts.get(r.rid, ((),))[0]) for r in running),
            total_rows, pb - sum(c[2] for c in chunks),
            n_slots=len(running))
        # stamp AFTER the sync so TTFT includes the step compute
        now = self._time()
        for req, start, n, _rows in chunks:
            if req.status is not RequestStatus.RUNNING:
                continue    # cancelled from an earlier chunk's on_token
            self._finish_chunk(req, start, n, p_logits[req.slot], now)
        if self.faults is not None and self.faults.nan_rids:
            poisoned = [r for r in running
                        if r.rid in self.faults.nan_rids]
            if poisoned:              # only then pay for a writable copy
                d_logits = d_logits.copy()
                for req in poisoned:
                    d_logits[req.slot] = np.nan
        for req in running:
            if req.status is not RequestStatus.RUNNING:
                continue    # cancelled from another slot's on_token
            dr, dprobs = drafts.get(req.rid, ((), None))
            nrows = 1 + len(dr)
            rows = d_logits[req.slot, :nrows]
            if not np.isfinite(rows).all():
                # poisoned slot (possibly mid-verify): fail ONLY this
                # request — its pages go back (uncached ones scrubbed
                # by _finish), the fused batchmates keep decoding
                # untouched and the proposer state is released
                self._finish(req, RequestStatus.FAILED, now)
                continue
            emitted, accepted = accept_tokens(
                rows, dr, dprobs, req.sampling, len(req.generated),
                self.eos_id)
            req.cache_len += accepted + 1
            if dr:
                req.spec_proposed += len(dr)
                req.spec_accepted += accepted
                self.metrics.on_spec(len(dr), accepted)
                self._tracer.instant("spec_accept", rid=req.rid,
                                     proposed=len(dr), accepted=accepted)
                if accepted < len(dr):
                    # rejected branch: return the lookahead pages past
                    # the accepted length (the rolled-back rows' K/V is
                    # masked junk; a shared page was already COW-forked
                    # before the write)
                    self.scheduler.rollback_pages(req)
                    self._tracer.instant("spec_rollback", rid=req.rid,
                                         rejected=len(dr) - accepted)
            for tok in emitted:
                self._emit(req, tok, now)
                if req.finished:
                    break
            if not req.finished and self._proposer is not None:
                # accepted history is now truth: the draft proposer
                # rolls its own cache back to it (no-op for n-gram)
                self._proposer.commit(req)

    def _finish_chunk(self, req: Request, start: int, n: int, logits,
                      now: float) -> None:
        """Post-dispatch bookkeeping for one prefill chunk that rode
        the unified step: advance the materialized length, guard, index
        the newly-completed full pages, and on the final chunk emit the
        first token from the chunk-final row's logits."""
        toks = req.cache_tokens
        req.cache_len = start + n
        self.scheduler.note_prefill_progress(req, start)
        self.metrics.on_prefill(n)
        req.last_progress_tick = self._tick   # chunks are progress too
        if not np.isfinite(logits).all():
            if self.cache is not None:
                # roll back entries ONLY for pages the FAILING chunk
                # wrote (from the pre-chunk position onward): earlier
                # chunks passed their own finite guard and their cached
                # pages may already be stitched by a concurrent sharer —
                # forgetting them would route them into the FAILED scrub
                # below and zero-wipe K/V the sharer is reading
                self.cache.forget(
                    req.pages[start // self.kv_cfg.page_size:])
            req.prefilling = False
            self._finish(req, RequestStatus.FAILED, now)
            return
        if self.cache is not None:
            # newly-completed FULL pages — now finite-vouched — become
            # hittable immediately, so even a preempted or mid-prefill
            # prompt re-prefills cheaply.  The chain cursor makes each
            # chunk's insert O(chunk), not O(prefix-so-far).
            req.chain_hash, req.chain_blocks = self.cache.insert(
                toks, req.pages, req.cache_len,
                from_block=req.chain_blocks, prev_hash=req.chain_hash,
                tenant=req.tenant)
        if req.cache_len < len(toks):
            return                            # more chunks, later ticks
        req.prefilling = False
        # first token: greedy argmax unless the request samples (seeded
        # per-position draw — position 0 of its generated stream)
        self._emit(req, next_token(logits, req.sampling,
                                   len(req.generated)), now)

    def _emit(self, req: Request, tok: int, now: float) -> None:
        req.generated.append(tok)
        req.last_progress_tick = self._tick
        ttft = None
        if req.first_token_at is None:
            req.first_token_at = now
            ttft = max(0.0, now - (req.submitted_at
                                   if req.submitted_at is not None else now))
            self._observe_stage("prefill", now - (
                req.admitted_at if req.admitted_at is not None else now))
            self._tracer.instant("first_token", rid=req.rid, slot=req.slot)
        self.metrics.on_token(now, ttft)
        if req.on_token is not None:
            req.on_token(tok)
            if req.finished:
                return   # the callback cancelled this request: keep it
        if tok == self.eos_id or len(req.generated) >= req.max_tokens:
            self._results[req.rid] = list(req.generated)
            self._finish(req, RequestStatus.COMPLETED, now)
