"""Page-migration plane: live KV handoff between ServingEngine replicas
(round 16 — ROADMAP item 1's disaggregated prefill/decode fleet).

A request's KV state is already self-describing at page granularity —
the paged pool (PR 4) gives every sequence an explicit page table with
refcounts, int8 pages (PR 8) carry their scales beside them, and the
:class:`~paddle_tpu.serving.kv_cache.PrefixCache` keys full pages by a
chained block hash that is identical on every replica.  This module
turns that into a transfer plane:

- :func:`export_chain` serializes one RUNNING request's whole chain —
  K/V page tensors as STORED (no re-quantization: an int8 page moves as
  int8 bytes plus its f32 scales, ~0.31x the f32 bytes), the token
  stream, the chain-hash cursor, and sampling/position state — into a
  host-side :class:`MigrationBlob`;
- :func:`import_chain` splices a blob into ANOTHER engine: pages
  allocated at refcount 1 through the scheduler's normal seam (cache
  eviction relief included), payload written by one donated device
  scatter (``serving.import_pages``), the request registered directly
  into a free slot as a decoding (non-prefilling) sequence, and its
  full pages re-inserted into the destination's PrefixCache so the
  migrated prefix is immediately hittable;
- :func:`export_prefix` / :func:`import_prefix` move just a CACHED
  prefix between replicas (cross-replica seeding): only the blocks the
  destination does not already hold are transferred, the spliced pages
  are inserted into the destination cache and then parked at
  refcount 0 (RECLAIMABLE) — an opportunistic warm, never a holder.

Because both halves run through the ordinary PagePool/PrefixCache
bookkeeping (alloc/ref/free/mark_cached), the existing PAGE/REF-LEAK
conservation checks keep holding on BOTH pools mid-migration.
:func:`check_migration_conservation` adds the fleet-level half: every
started migration ends exactly one way (applied, fallback, or aborted),
no transfer is left pending at drain, and every replica's incremental
``prefill_backlog_tokens`` probe matches its ground-truth recompute.
Violations raise :class:`~paddle_tpu.serving.faults.PageLeakError`
tagged ``MIGRATE-LEAK`` (tools_tier1.sh exit 11), and ``python -c
"...migrate.main(['check'])"`` replays a seeded disaggregated chaos
trace as a standalone gate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from paddle_tpu.platform.enforce import enforce_that
from paddle_tpu.serving.faults import PageLeakError
from paddle_tpu.serving.kv_cache import read_pages
from paddle_tpu.serving.scheduler import Request, RequestStatus

__all__ = ["MigrationBlob", "export_chain", "import_chain",
           "export_prefix", "import_prefix",
           "check_migration_conservation", "main"]


@dataclass
class MigrationBlob:
    """A self-describing host-side page-chain snapshot.

    Geometry fields pin the pool layout the payload was read from; the
    importer refuses a mismatched engine rather than splicing garbage.
    ``k``/``v`` are ``[L, P, page, H_kv, D]`` host arrays in the pool's
    STORED dtype; ``k_scale``/``v_scale`` ride along (``[L, P, page,
    H_kv]`` f32) for quantized pools and are None otherwise.
    """

    kind: str                      # "chain" (live request) | "prefix"
    page_size: int
    num_layers: int
    kv_heads: int
    head_dim: int
    kv_dtype: str                  # stored dtype name, e.g. "int8"
    quantized: bool
    # request / prefix state
    prompt: List[int]
    generated: List[int]
    max_tokens: int
    cache_len: int                 # tokens materialized in the payload
    sampling: Optional[object] = None
    deadline_at: Optional[float] = None
    chain_blocks: int = 0          # PrefixCache hash cursor at export
    chain_hash: Optional[int] = None
    # tenant identity (round 17): a migrated chain keeps billing to its
    # original tenant on the destination — SLO deadlines, quotas and
    # preemption precedence follow the request across replicas
    tenant: str = "default"
    # page payload
    k: object = None
    v: object = None
    k_scale: object = None
    v_scale: object = None

    @property
    def num_pages(self) -> int:
        return 0 if self.k is None else int(self.k.shape[1])

    @property
    def nbytes(self) -> int:
        """Interconnect bytes this blob costs: payload arrays only (the
        token/cursor metadata is noise next to page tensors)."""
        total = 0
        for a in (self.k, self.v, self.k_scale, self.v_scale):
            if a is not None:
                total += int(a.nbytes)
        return total


def _geometry_of(engine) -> Tuple[int, int, int, int, str, bool]:
    import jax.numpy as jnp

    cfg = engine.kv_cfg
    return (cfg.page_size, cfg.num_layers, cfg.kv_heads, cfg.head_dim,
            str(jnp.dtype(cfg.dtype).name), cfg.quantized)


def _check_geometry(engine, blob: MigrationBlob) -> None:
    page, layers, kv_heads, head_dim, dtype, quant = _geometry_of(engine)
    enforce_that(
        (blob.page_size, blob.num_layers, blob.kv_heads, blob.head_dim,
         blob.kv_dtype, blob.quantized) ==
        (page, layers, kv_heads, head_dim, dtype, quant),
        f"migration blob geometry (page={blob.page_size} L={blob.num_layers}"
        f" H_kv={blob.kv_heads} D={blob.head_dim} dtype={blob.kv_dtype}) "
        f"does not match the destination pool (page={page} L={layers} "
        f"H_kv={kv_heads} D={head_dim} dtype={dtype})",
        context="serving-migrate")


# ---------------------------------------------------------------------------
# chain handoff: a live decoding request moves engines whole
# ---------------------------------------------------------------------------


def export_chain(engine, rid: int) -> MigrationBlob:
    """Snapshot request ``rid``'s page chain off ``engine`` into a
    host blob.  The request must be migration-eligible (RUNNING, prefill
    fully materialized, first token emitted — see
    ``ServingEngine.migratable_rids``); the source keeps running, so the
    export is a pure read and the caller decides when (if ever) to
    cancel the source copy."""
    req = engine._requests[rid]
    enforce_that(req.status is RequestStatus.RUNNING and
                 not req.prefilling and bool(req.generated),
                 f"rid {rid} is not migration-eligible "
                 f"(status={req.status} prefilling={req.prefilling} "
                 f"generated={len(req.generated)})",
                 context="serving-migrate")
    page, layers, kv_heads, head_dim, dtype, quant = _geometry_of(engine)
    n = -(-req.cache_len // page)          # pages covering cache_len
    k, v, k_scale, v_scale = read_pages(engine._kv, req.pages[:n])
    return MigrationBlob(
        kind="chain", page_size=page, num_layers=layers,
        kv_heads=kv_heads, head_dim=head_dim, kv_dtype=dtype,
        quantized=quant, prompt=list(req.prompt),
        generated=list(req.generated), max_tokens=req.max_tokens,
        cache_len=req.cache_len, sampling=req.sampling,
        deadline_at=req.deadline_at, chain_blocks=req.chain_blocks,
        chain_hash=req.chain_hash, tenant=req.tenant, k=k, v=v,
        k_scale=k_scale, v_scale=v_scale)


def import_chain(engine, blob: MigrationBlob, *, on_token=None,
                 now: Optional[float] = None) -> Optional[int]:
    """Splice a chain blob into ``engine`` as a live decoding request.

    Returns the new engine rid, or None when the destination cannot
    host it right now (no free slot, or the page allocation — after
    cache-eviction relief — comes up short); the caller retries later
    or falls back to a re-prefill.  On success the request holds its
    pages at refcount 1 like any admitted sequence (so the existing
    PAGE/REF-LEAK conservation holds unchanged), its full pages are
    re-inserted into the destination PrefixCache, and the next engine
    tick decodes it — no prefill, no queue wait."""
    _check_geometry(engine, blob)
    enforce_that(blob.kind == "chain", "import_chain needs a chain blob",
                 context="serving-migrate")
    now = engine._time() if now is None else now
    sched = engine.scheduler
    cfg = engine.kv_cfg
    if len(blob.prompt) + blob.max_tokens > cfg.max_seq_len:
        return None                      # destination could never run it
    if not sched._free_slots:
        return None
    # charge cache_len + 1, exactly like admission: the freshly-imported
    # request must not become a growth victim on its very first tick
    total = -(-(blob.cache_len + 1) // cfg.page_size)
    if total > cfg.max_pages_per_seq:
        return None
    pages = sched.alloc_pages(total)
    if pages is None:
        return None
    engine.apply_imported_pages(pages[:blob.num_pages], blob.k, blob.v,
                                blob.k_scale, blob.v_scale)
    req = Request(prompt=list(blob.prompt), max_tokens=blob.max_tokens,
                  on_token=on_token, sampling=blob.sampling,
                  tenant=blob.tenant)
    req.generated = list(blob.generated)
    req.pages = pages
    req.cache_len = blob.cache_len
    req.status = RequestStatus.RUNNING
    req.prefilling = False
    req.deadline_at = blob.deadline_at
    req.submitted_at = now
    req.admitted_at = now
    req.first_token_at = now             # its first token landed upstream
    req.last_progress_tick = engine._tick
    req.slot = sched._free_slots.pop()
    sched.running[req.slot] = req
    sched._backlog_enter(req)            # contributes 0 (prefill is done)
    engine._requests[req.rid] = req
    if engine.cache is not None:
        # full pages become hittable HERE immediately; idempotent insert
        # keeps any entry the destination already owns (our page for
        # that block simply stays uncached — the request holds it)
        req.chain_hash, req.chain_blocks = engine.cache.insert(
            req.cache_tokens, req.pages, req.cache_len)
    engine._tracer.instant("import_chain", rid=req.rid,
                           pages=blob.num_pages, tokens=blob.cache_len)
    return req.rid


# ---------------------------------------------------------------------------
# prefix seeding: a cached prefix warms a peer replica's cache
# ---------------------------------------------------------------------------


def export_prefix(engine, tokens: Sequence[int]) -> Optional[MigrationBlob]:
    """Snapshot the longest CACHED full-page prefix of ``tokens`` from
    ``engine``'s PrefixCache into a prefix blob (pure read — refcounts
    untouched).  None when the engine caches nothing useful."""
    if engine.cache is None:
        return None
    page, layers, kv_heads, head_dim, dtype, quant = _geometry_of(engine)
    hit_pages, hit_len = engine.cache.lookup(list(tokens))
    blocks = hit_len // page
    if blocks == 0:
        return None
    k, v, k_scale, v_scale = read_pages(engine._kv, hit_pages[:blocks])
    covered = [int(t) for t in tokens[:blocks * page]]
    return MigrationBlob(
        kind="prefix", page_size=page, num_layers=layers,
        kv_heads=kv_heads, head_dim=head_dim, kv_dtype=dtype,
        quantized=quant, prompt=covered, generated=[], max_tokens=0,
        cache_len=blocks * page, k=k, v=v, k_scale=k_scale,
        v_scale=v_scale)


def import_prefix(engine, blob: MigrationBlob) -> Tuple[int, int]:
    """Seed ``engine``'s PrefixCache from a prefix blob.  Only blocks
    the destination does not already verify locally are spliced (chains
    are prefix-closed, so the missing blocks are exactly the tail);
    the new pages are inserted as cached and then freed to refcount 0 —
    parked RECLAIMABLE, evictable under pressure like any cached page.
    Returns ``(blocks_seeded, payload_bytes_transferred)``; ``(0, 0)``
    when the destination already covers the prefix or has no room."""
    if engine.cache is None:
        return 0, 0
    _check_geometry(engine, blob)
    enforce_that(blob.kind == "prefix", "import_prefix needs a prefix blob",
                 context="serving-migrate")
    page = blob.page_size
    tokens = blob.prompt
    total_blocks = blob.cache_len // page
    dest_pages, dest_len = engine.cache.lookup(tokens)
    start = dest_len // page
    if start >= total_blocks:
        return 0, 0
    need = total_blocks - start
    new = engine.scheduler.alloc_pages(need)
    if new is None:
        return 0, 0
    payload = [None if a is None else a[:, start:total_blocks]
               for a in (blob.k, blob.v, blob.k_scale, blob.v_scale)]
    engine.apply_imported_pages(new, *payload)
    full = list(dest_pages[:start]) + new
    engine.cache.insert(tokens, full, total_blocks * page)
    # insert marked the pages it actually took as cached; free() parks
    # those at refcount 0 (RECLAIMABLE) and returns any it did NOT take
    # (a racing identical entry) straight to the free list — no leak
    # either way
    engine.pool.free(new)
    nbytes = sum(int(a.nbytes) for a in payload if a is not None)
    engine._tracer.instant("import_prefix", blocks=need, bytes=nbytes)
    return need, nbytes


# ---------------------------------------------------------------------------
# conservation
# ---------------------------------------------------------------------------


def check_migration_conservation(router) -> None:
    """Migration-plane conservation over a (drained) fleet.  Raises
    :class:`PageLeakError` tagged ``MIGRATE-LEAK`` when:

    - the migration ledger does not balance: every started chain
      handoff must end exactly one way,
      ``migrations_started == applied + fallbacks + aborted``;
    - a chain transfer is still pending after its fleet request
      finished (an in-flight migration that can never resolve);
    - any replica's incremental ``prefill_backlog_tokens`` probe has
      drifted from its ground-truth recompute (the O(1) number the
      router balances on would be lying).
    """
    problems: List[str] = []
    m = router.metrics
    ended = (m.migrations_applied + m.migration_fallbacks +
             m.migrations_aborted)
    if m.migrations_started != ended:
        problems.append(
            f"migration ledger unbalanced: started={m.migrations_started} "
            f"!= applied={m.migrations_applied} + "
            f"fallbacks={m.migration_fallbacks} + "
            f"aborted={m.migrations_aborted}")
    pending = getattr(router, "_mig_pending", {})
    if pending:
        problems.append(f"{len(pending)} chain transfers still pending "
                        f"(frids {sorted(pending)})")
    for rep in router.replicas:
        sched = rep.engine.scheduler
        got = sched.prefill_backlog_tokens
        want = sched.recompute_backlog()
        if got != want:
            problems.append(f"replica {rep.idx}: prefill_backlog_tokens="
                            f"{got} but recompute says {want}")
    if problems:
        if "MIGRATE-LEAK" not in router._postmortems_dumped:
            router._postmortems_dumped.add("MIGRATE-LEAK")
            router.tracer.dump_postmortem("MIGRATE-LEAK")
        raise PageLeakError("MIGRATE-LEAK: " + "; ".join(problems))


# ---------------------------------------------------------------------------
# standalone gate: python -c "...migrate.main(['check'])"
# ---------------------------------------------------------------------------


def _selfcheck() -> int:
    """Replay a seeded disaggregated trace — 2 prefill + 2 decode
    replicas, shared system prefix, one injected decode-replica kill,
    one scheduled in-flight blob drop, a second submission wave once
    owners exist (so affinity seeding fires) — then run the migration
    AND fleet conservation checks.  The tier-1 ladder's MIGRATE-LEAK
    gate (tools_tier1.sh exit 11).  Returns 0 (clean) or 1 (findings);
    a crash propagates as 2."""
    import jax
    import numpy as np

    from paddle_tpu.serving.engine import DecoderLM, ServingEngine
    from paddle_tpu.serving.faults import FleetFaultPlan, ManualClock
    from paddle_tpu.serving.fleet import FleetRouter

    model = DecoderLM(vocab_size=64, num_layers=1, num_heads=2, head_dim=8,
                      max_positions=64)
    params = model.init_params(jax.random.PRNGKey(0))
    plan = FleetFaultPlan(seed=0, clock=ManualClock(tick_s=0.01),
                          kill_at={6: 2}, drop_migration_at={1})

    def mk(i, time_fn):
        return ServingEngine(model, params, eos_id=1, page_size=4,
                             num_pages=32, max_pages_per_seq=8, max_slots=4,
                             buckets=(8, 16), time_fn=time_fn)

    fleet = FleetRouter(mk, 4, heartbeat_s=0.05, resubmit_budget=2,
                        faults=plan,
                        roles=("prefill", "prefill", "decode", "decode"),
                        migrate_budget=8)
    rng = np.random.RandomState(0)
    system = rng.randint(2, 64, size=8).tolist()    # 2 full pages shared
    frids = [fleet.submit(system + rng.randint(2, 64, size=4).tolist(),
                          max_tokens=6) for _ in range(6)]
    for _ in range(4):             # let the first chains migrate, so the
        fleet.step()               # second wave sees decode-side owners
    frids += [fleet.submit(system + rng.randint(2, 64, size=4).tolist(),
                           max_tokens=6) for _ in range(3)]
    fleet.run(max_ticks=800)       # drain runs check_fleet_conservation
    if fleet.has_work:
        print("MIGRATE-LEAK: disaggregated fleet failed to drain "
              "within 800 ticks")
        return 1
    check_migration_conservation(fleet)
    snap = fleet.snapshot()
    bad = [f for f in frids if not fleet.status(f).terminal]
    if bad or snap["fleet_duplicate_completions"]:
        print(f"MIGRATE-LEAK: non-terminal={bad} "
              f"dups={snap['fleet_duplicate_completions']}")
        return 1
    if snap["fleet_migrations_applied"] == 0:
        print("MIGRATE-LEAK: disaggregated replay applied 0 chain "
              "migrations — the prefill->decode handoff never ran")
        return 1
    if snap["fleet_migration_fallbacks"] == 0:
        print("MIGRATE-LEAK: the scheduled blob drop produced no "
              "re-prefill fallback")
        return 1
    if snap["fleet_cross_replica_seeds"] == 0:
        print("MIGRATE-LEAK: the second submission wave produced no "
              "cross-replica prefix seeds")
        return 1
    if snap["fleet_migration_resubmits"] == 0:
        print("MIGRATE-LEAK: the injected decode kill produced no "
              "page re-adoption on resubmit")
        return 1
    print(f"migrate-check ok: {snap['fleet_completed']} completed, "
          f"{snap['fleet_migrations_applied']} chain migrations "
          f"({snap['fleet_pages_migrated']} pages, "
          f"{snap['fleet_migration_bytes']} B), "
          f"{snap['fleet_migration_fallbacks']} drop fallback, "
          f"{snap['fleet_migrations_aborted']} aborted, "
          f"{snap['fleet_cross_replica_seeds']} seed(s), "
          f"{snap['fleet_migration_resubmits']} re-adopt resubmit(s) "
          "after 1 injected kill, 0 leaks")
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI dispatch, importable so tools_tier1.sh runs the gate via
    ``python -c "...migrate.main(['check'])"`` (``python -m`` would
    have runpy double-import the module — same rationale as
    fleet.main)."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    cmd = args[0] if args else "check"
    if cmd != "check":
        print(f"unknown command {cmd!r}; usage: "
              "python -c \"from paddle_tpu.serving.migrate import main; "
              "main(['check'])\"")
        return 2
    try:
        return _selfcheck()
    except PageLeakError as e:
        print(str(e))
        return 1
    except Exception as e:   # crash != findings: distinct exit code
        print(f"migrate check crashed: {e!r}")
        return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
